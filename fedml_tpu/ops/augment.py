"""On-device image augmentation for federated CV training.

Reference: the torchvision transform pipelines built per DataLoader —
RandomCrop(32, padding=4) + RandomHorizontalFlip + Cutout(16) for the CIFAR
family (cifar10/data_loader.py:58-76) and RandomResizedCrop(224) + flip +
Cutout for ImageNet/Landmarks (ImageNet/data_loader.py:43-67). The reference
augments on the host, example by example, inside each DataLoader worker.

TPU design: augmentation is pure array math inside the jitted round program —
batched pad+dynamic-slice crops, sign flips, and rectangle masks, vmapped
with per-example keys. The (already normalized, device-resident) dataset is
augmented *after* the cohort gather, so the same resident arrays serve every
round with fresh randomness and zero host involvement. Compose with
ClientTrainer via ``with_augmentation`` (the ``augment`` hook applies inside
``loss_fn`` before the forward pass, training only).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

Batch = dict


def random_crop(img: jnp.ndarray, rng: jax.Array, padding: int = 4) -> jnp.ndarray:
    """Pad-and-crop back to the original size (torchvision
    RandomCrop(size, padding) semantics) for one [H, W, C] image."""
    h, w, _ = img.shape
    padded = jnp.pad(
        img, ((padding, padding), (padding, padding), (0, 0)), mode="constant"
    )
    ky, kx = jax.random.split(rng)
    dy = jax.random.randint(ky, (), 0, 2 * padding + 1)
    dx = jax.random.randint(kx, (), 0, 2 * padding + 1)
    return jax.lax.dynamic_slice(padded, (dy, dx, 0), img.shape)


def random_flip(img: jnp.ndarray, rng: jax.Array) -> jnp.ndarray:
    """Horizontal flip with p=0.5 for one [H, W, C] image."""
    return jnp.where(jax.random.bernoulli(rng), img[:, ::-1, :], img)


def cutout(img: jnp.ndarray, rng: jax.Array, length: int = 16) -> jnp.ndarray:
    """Zero a random length x length square (reference Cutout,
    ImageNet/data_loader.py:21-40) for one [H, W, C] image."""
    h, w, _ = img.shape
    ky, kx = jax.random.split(rng)
    cy = jax.random.randint(ky, (), 0, h)
    cx = jax.random.randint(kx, (), 0, w)
    ys = jnp.arange(h)[:, None]
    xs = jnp.arange(w)[None, :]
    # [c - l//2, c + l//2): an exact length x length window (edge-clipped),
    # matching reference Cutout's np.clip(y - length//2 .. y + length//2)
    mask = (
        (ys >= cy - length // 2) & (ys < cy + length // 2)
        & (xs >= cx - length // 2) & (xs < cx + length // 2)
    )
    return img * (1.0 - mask.astype(img.dtype))[..., None]


@dataclasses.dataclass(frozen=True)
class ImageAugment:
    """The reference CIFAR/ImageNet train pipeline as one batched jit-safe
    function: crop -> flip -> cutout, each per-example."""

    padding: int = 4
    cutout_length: int = 16
    flip: bool = True

    def __call__(self, batch: Batch, rng: jax.Array) -> Batch:
        x = batch["x"]
        if x.ndim != 4:
            raise ValueError(
                f"ImageAugment needs [B, H, W, C] images; got shape "
                f"{tuple(x.shape)} — channel-less datasets (e.g. mnist "
                f"[B, 28, 28]) need x[..., None] first"
            )

        def one(img, key):
            k1, k2, k3 = jax.random.split(key, 3)
            img = random_crop(img, k1, self.padding)
            if self.flip:
                img = random_flip(img, k2)
            if self.cutout_length:
                img = cutout(img, k3, self.cutout_length)
            return img

        keys = jax.random.split(rng, x.shape[0])
        return {**batch, "x": jax.vmap(one)(x, keys)}


def with_augmentation(trainer, augment: Callable[[Batch, jax.Array], Batch]):
    """A ClientTrainer whose training forward sees augmented batches
    (evaluation is untouched — the reference's valid_transform applies no
    augmentation). Works anywhere a ClientTrainer does: the jitted round
    program vmaps it over the cohort like any other trainer."""
    import dataclasses as dc

    base_loss_fn = type(trainer).loss_fn

    class AugmentedTrainer(type(trainer)):
        def loss_fn(self, params, model_state, global_params, batch, rng):
            aug_rng, step_rng = jax.random.split(rng)
            batch = augment(batch, aug_rng)
            return base_loss_fn(
                self, params, model_state, global_params, batch, step_rng
            )

    return AugmentedTrainer(**{
        f.name: getattr(trainer, f.name) for f in dc.fields(trainer)
    })
