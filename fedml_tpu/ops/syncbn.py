"""Synchronized BatchNorm across the silo (in-silo data-parallel) axis.

Reference: fedml_api/model/cv/batchnorm_utils.py — ~400 lines of
master/slave thread pipes (SyncMaster, SlavePipe, FutureResult) to gather
per-GPU batch moments under torch DataParallel and broadcast the global
statistics back.

On TPU the whole mechanism is one argument: Flax's BatchNorm takes
``axis_name``, and when the batch axis is sharded over a mesh axis inside
``shard_map``/``pjit``, the mean/variance reduction becomes a ``psum`` over
that axis — XLA schedules it on ICI like any other collective. This module
pins the framework policy:

- ``SyncBatchNorm`` — BatchNorm synchronized over the ``silo`` axis: batch
  statistics are computed over the FULL per-client batch even when it is
  sharded across the silo's devices (exactly what the reference's
  SynchronizedBatchNorm2d does across DataParallel replicas).
- Cross-CLIENT statistics are deliberately NOT synchronized: each client's
  BN sees only its own data (federated semantics); the running averages are
  then federated like ordinary weights (FedAVGAggregator.py:74-81 policy,
  see core/trainer.py module docstring).
"""

from __future__ import annotations

import flax.linen as nn

from fedml_tpu.parallel.mesh import SILO_AXIS


class SyncBatchNorm(nn.Module):
    """Drop-in BatchNorm whose batch statistics reduce over the silo axis.

    Use inside models trained with the silo mesh axis (cross-silo in-silo
    data parallelism, parallel/mesh.py cohort_batch_sharding). Outside a
    mapped context (no ``silo`` axis bound), it behaves as plain BatchNorm —
    same module code runs in single-device tests and sharded training.
    """

    use_running_average: bool | None = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    axis_name: str | None = SILO_AXIS

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None):
        # bind the axis only when it exists in the current mapped context
        axis = self.axis_name
        if axis is not None:
            try:
                import jax

                jax.lax.axis_index(axis)
            except NameError:  # unbound: plain (single-replica) BatchNorm
                axis = None
        return nn.BatchNorm(
            use_running_average=(
                use_running_average
                if use_running_average is not None
                else self.use_running_average
            ),
            momentum=self.momentum,
            epsilon=self.epsilon,
            axis_name=axis,
            name="bn",
        )(x)
