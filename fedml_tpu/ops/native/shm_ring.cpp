// Shared-memory ring transport for single-host multi-process federation.
//
// Role: the native message fabric replacing the reference's MPI-on-localhost
// transport (reference: fedml_core/distributed/communication/mpi/ — mpi4py
// send/recv daemon threads with a 0.3 s polling loop, com_manager.py:71-78).
// Here: one MPSC ring buffer in POSIX shared memory per receiving rank, with
// a process-shared mutex + condvar — blocking receive, no polling.
//
// Layout of the shm segment:
//   [Header | data bytes ...]
// Messages are length-prefixed blobs, contiguous, wrapping at the end.
//
// Exposed C API (consumed from Python via ctypes — see fedml_tpu/comm/shm.py):
//   shmring_create / shmring_open / shmring_close / shmring_unlink
//   shmring_send(handle, buf, len, timeout_ms)
//   shmring_recv(handle, buf, maxlen, timeout_ms) -> nbytes | -1 timeout | -2 too small

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <sys/file.h>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
  uint64_t magic;
  uint64_t capacity;   // data area size in bytes
  uint64_t head;       // read offset  (consumer)
  uint64_t tail;       // write offset (producer)
  uint64_t used;       // bytes in use
  pthread_mutex_t mu;
  pthread_cond_t can_read;
  pthread_cond_t can_write;
};

constexpr uint64_t kMagic = 0x46544d52494e4731ull;  // "FTMRING1"

struct Ring {
  Header* h;
  uint8_t* data;
  size_t map_len;
};

void abs_deadline(timespec* ts, int timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (long)(timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

void ring_write(Ring* r, const uint8_t* src, uint64_t len) {
  uint64_t cap = r->h->capacity;
  uint64_t tail = r->h->tail;
  uint64_t first = (tail + len <= cap) ? len : cap - tail;
  memcpy(r->data + tail, src, first);
  if (first < len) memcpy(r->data, src + first, len - first);
  r->h->tail = (tail + len) % cap;
  r->h->used += len;
}

void ring_read(Ring* r, uint8_t* dst, uint64_t len) {
  uint64_t cap = r->h->capacity;
  uint64_t head = r->h->head;
  uint64_t first = (head + len <= cap) ? len : cap - head;
  memcpy(dst, r->data + head, first);
  if (first < len) memcpy(dst + first, r->data, len - first);
  r->h->head = (head + len) % cap;
  r->h->used -= len;
}

}  // namespace

namespace {

// Wait budget (ms) for init/recovery waits; FEDML_SHMRING_WAIT_MS overrides
// (tests use tiny budgets so the timeout paths don't cost seconds).
int wait_budget_ms(int def_ms) {
  const char* s = getenv("FEDML_SHMRING_WAIT_MS");
  if (!s) return def_ms;
  int v = atoi(s);
  return v > 0 ? v : def_ms;
}

// Whether the segment's magic word is already published — i.e. the segment is
// fully initialized and must NOT be unlinked by stale-segment recovery.
bool magic_published(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return false;
  struct stat st;
  if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
    close(fd);
    return false;
  }
  void* mem = mmap(nullptr, sizeof(Header), PROT_READ, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return false;
  // plain atomic load — an RMW (__sync_fetch_and_add) would store and fault
  // on this read-only mapping
  bool ok = __atomic_load_n(&((Header*)mem)->magic, __ATOMIC_SEQ_CST) == kMagic;
  munmap(mem, sizeof(Header));
  return ok;
}

}  // namespace

extern "C" {

void* shmring_try_create(const char* name, uint64_t capacity);

void* shmring_create(const char* name, uint64_t capacity) {
  void* r = shmring_try_create(name, capacity);
  if (r) return r;
  // Attach timed out: a creator died between O_EXCL and magic publication,
  // leaving a stale half-initialized segment. Recovery must not race: two
  // attachers timing out together could otherwise each unlink + recreate and
  // end up mapped to distinct rings under one name. So (a) never unlink a
  // segment whose magic is now published — just re-attach; (b) elect a single
  // recoverer with an O_EXCL lock segment; losers wait for it to finish.
  if (magic_published(name)) return shmring_try_create(name, capacity);
  // Recovery must be exclusive: serialize with flock on a dedicated lock
  // segment. The kernel releases an flock when its holder dies, so a crashed
  // recoverer can't wedge the name and no timed lock-break (which could
  // delete a live lock and re-admit the split-ring race) is ever needed.
  // The lock segment is deliberately never unlinked here — unlink+recreate
  // would hand out a second lock inode and two "exclusive" holders;
  // shmring_unlink cleans it up with the ring.
  std::string lock = std::string(name) + ".rec";
  int lfd = shm_open(lock.c_str(), O_CREAT | O_RDWR, 0600);
  if (lfd < 0) return nullptr;
  int budget = wait_budget_ms(10000);
  bool locked = false;
  for (int i = 0; i <= budget; ++i) {
    if (flock(lfd, LOCK_EX | LOCK_NB) == 0) {
      locked = true;
      break;
    }
    usleep(1000);
  }
  if (!locked) {
    close(lfd);
    return nullptr;
  }
  if (!magic_published(name)) shm_unlink(name);  // re-check under the lock
  r = shmring_try_create(name, capacity);
  flock(lfd, LOCK_UN);
  close(lfd);
  return r;
}

void* shmring_try_create(const char* name, uint64_t capacity) {
  // Concurrent create must be idempotent (sender lazily creates the
  // receiver's ring while the receiver creates it at startup): elect exactly
  // one initializer with O_EXCL; everyone else waits for magic.
  size_t total = sizeof(Header) + capacity;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  bool creator = fd >= 0;
  if (!creator) {
    if (errno != EEXIST) return nullptr;
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
    // wait for the creator to size the segment (ftruncate not yet done)
    struct stat st;
    int budget = wait_budget_ms(2000);
    for (int i = 0; i < budget; ++i) {
      if (fstat(fd, &st) != 0) {
        close(fd);
        return nullptr;
      }
      if ((size_t)st.st_size >= total) break;
      usleep(1000);
    }
    if ((size_t)st.st_size < total) {
      close(fd);
      return nullptr;
    }
  } else if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* h = (Header*)mem;
  if (creator) {
    h->capacity = capacity;
    h->head = h->tail = h->used = 0;
    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutex_init(&h->mu, &ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_cond_init(&h->can_read, &ca);
    pthread_cond_init(&h->can_write, &ca);
    __sync_synchronize();
    h->magic = kMagic;
  } else {
    int budget = wait_budget_ms(2000);
    for (int i = 0; i < budget && __sync_fetch_and_add(&h->magic, 0) != kMagic; ++i)
      usleep(1000);
    if (__sync_fetch_and_add(&h->magic, 0) != kMagic) {
      munmap(mem, total);
      return nullptr;
    }
  }
  Ring* r = new Ring{h, (uint8_t*)mem + sizeof(Header), total};
  return r;
}

void* shmring_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Ring* r = new Ring{(Header*)mem, (uint8_t*)mem + sizeof(Header), (size_t)st.st_size};
  if (r->h->magic != kMagic) {
    munmap(mem, r->map_len);
    delete r;
    return nullptr;
  }
  return r;
}

int shmring_send(void* handle, const uint8_t* buf, uint64_t len, int timeout_ms) {
  Ring* r = (Ring*)handle;
  uint64_t need = len + 8;
  if (need > r->h->capacity) return -3;  // can never fit
  timespec ts;
  abs_deadline(&ts, timeout_ms);
  pthread_mutex_lock(&r->h->mu);
  while (r->h->capacity - r->h->used < need) {
    if (pthread_cond_timedwait(&r->h->can_write, &r->h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&r->h->mu);
      return -1;
    }
  }
  uint64_t len_le = len;  // little-endian host assumed (x86/ARM LE)
  ring_write(r, (const uint8_t*)&len_le, 8);
  ring_write(r, buf, len);
  pthread_cond_signal(&r->h->can_read);
  pthread_mutex_unlock(&r->h->mu);
  return 0;
}

long long shmring_recv(void* handle, uint8_t* buf, uint64_t maxlen, int timeout_ms) {
  Ring* r = (Ring*)handle;
  timespec ts;
  abs_deadline(&ts, timeout_ms);
  pthread_mutex_lock(&r->h->mu);
  while (r->h->used < 8) {
    if (pthread_cond_timedwait(&r->h->can_read, &r->h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&r->h->mu);
      return -1;
    }
  }
  uint64_t len_le = 0;
  ring_read(r, (uint8_t*)&len_le, 8);
  if (len_le > maxlen) {  // caller buffer too small; message is lost by design
    // skip payload to keep the stream consistent
    uint64_t cap = r->h->capacity;
    r->h->head = (r->h->head + len_le) % cap;
    r->h->used -= len_le;
    pthread_cond_signal(&r->h->can_write);
    pthread_mutex_unlock(&r->h->mu);
    return -2;
  }
  ring_read(r, buf, len_le);
  pthread_cond_signal(&r->h->can_write);
  pthread_mutex_unlock(&r->h->mu);
  return (long long)len_le;
}

int shmring_close(void* handle) {
  Ring* r = (Ring*)handle;
  munmap((void*)r->h, r->map_len);
  delete r;
  return 0;
}

int shmring_unlink(const char* name) {
  shm_unlink((std::string(name) + ".rec").c_str());  // recovery lock, if any
  return shm_unlink(name);
}

}  // extern "C"
