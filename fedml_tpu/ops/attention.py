"""Fused blockwise (flash) attention — the pallas hot-op for transformer
clients.

The reference has no attention anywhere (its NLP models are LSTMs,
fedml_api/model/nlp/rnn.py) and no long-context support (SURVEY §5.7). This
framework treats long sequences as first-class: the single-chip hot path is
this pallas kernel (online-softmax blockwise attention, O(T) memory instead of
the O(T²) score matrix), and the multi-chip path is ring attention over a
sequence-parallel mesh axis (fedml_tpu/parallel/ring_attention.py) which
reuses the same math.

Layout convention: ``[B, H, T, D]`` (batch, heads, sequence, head_dim).
Forward runs the pallas kernel; backward is a custom VJP that recomputes
attention blockwise with plain XLA ops — O(T) memory in both directions.
On non-TPU backends the kernel runs in interpreter mode so the full test
suite exercises it on the 8-device CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _pick_block(t: int, preferred: int) -> int:
    b = min(preferred, t)
    while t % b:
        b -= 1
    return b


def attention_reference(q, k, v, causal: bool = False, sm_scale: float | None = None):
    """Plain XLA attention, the numerical oracle for the kernels.

    Causal convention (shared with the pallas kernel): query i attends to
    keys j with j <= i + (t_k - t_q) — i.e. sequences are right-aligned, the
    standard decode convention."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, sm_scale, block_q):
    # q_ref: [block_q, D]; k_ref/v_ref: [T, D] (whole sequence for this head);
    # grid = (B*H, T // block_q).
    iq = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * sm_scale
    t_k, d = k_ref.shape
    num_kb = t_k // block_k
    t_q = pl.num_programs(1) * block_q

    # right-aligned causal offset, matching attention_reference
    q_pos = (t_k - t_q) + iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(j, carry):
        o, l, m = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o = o * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o, l, m_new

    o = jnp.zeros((block_q, d), jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    if causal:
        # only key blocks at or before this query block's last position
        last_q_pos = (t_k - t_q) + (iq + 1) * block_q - 1
        num_kb_eff = jnp.clip(last_q_pos // block_k + 1, 0, num_kb)
    else:
        num_kb_eff = num_kb
    o, l, m = jax.lax.fori_loop(0, num_kb_eff, body, (o, l, m))
    o_ref[:] = (o / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    b, h, t, d = q.shape
    t_k = k.shape[2]
    block_q = _pick_block(t, block_q)
    block_k = _pick_block(t_k, block_k)
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d)
    kernel = functools.partial(
        _flash_fwd_kernel,
        block_k=block_k,
        causal=causal,
        sm_scale=sm_scale,
        block_q=block_q,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, t_k, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, t_k, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d)


# ---------------------------------------------------------------------------
# Blockwise backward (plain XLA, O(T·block) memory — never materializes the
# [T, T] score matrix; standard flash-attention backward recomputation)
# ---------------------------------------------------------------------------


def _blockwise_bwd(q, k, v, out, g, causal, sm_scale, block_k):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    block_k = _pick_block(t_k, block_k)
    nkb = t_k // block_k
    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    off = t_k - t_q

    # log-sum-exp per query row, recomputed blockwise
    q_pos = off + jnp.arange(t_q)

    def lse_step(carry, j):
        m, l = carry
        k_blk = jax.lax.dynamic_slice_in_dim(k, j * block_k, block_k, 2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32)) * sm_scale
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)
            s = jnp.where((k_pos[None] <= q_pos[:, None])[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # masked entries must contribute 0, not exp(NEG_INF - NEG_INF) = 1
        # (NEG_INF is finite; a fully masked row keeps m_new at NEG_INF)
        e = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        l = l * jnp.exp(m - m_new) + jnp.sum(e, axis=-1, keepdims=True)
        return (m_new, l), None

    m0 = jnp.full((b, h, t_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_q, 1), jnp.float32)
    (m, l), _ = jax.lax.scan(lse_step, (m0, l0), jnp.arange(nkb))
    lse = m + jnp.log(jnp.maximum(l, 1e-20))

    # D_i = rowsum(dO * O)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1, keepdims=True)

    def grad_step(dq, j):
        k_blk = jax.lax.dynamic_slice_in_dim(k, j * block_k, block_k, 2).astype(jnp.float32)
        v_blk = jax.lax.dynamic_slice_in_dim(v, j * block_k, block_k, 2).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk) * sm_scale
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)
            s = jnp.where((k_pos[None] <= q_pos[:, None])[None, None], s, NEG_INF)
        # zero masked entries like the forward kernel does — for a fully
        # masked row lse is ~NEG_INF too and exp(s - lse) would be O(1)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))  # [b,h,t_q,block_k]
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v_blk)
        ds = p * (dp - delta) * sm_scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, k_blk)
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(grad_step, dq0, jnp.arange(nkb))
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(b, h, t_k, d)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(b, h, t_k, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public API: pallas forward + blockwise backward
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
):
    """Blockwise fused attention for ``[B, H, T, D]`` inputs.

    Forward = pallas kernel (interpreter mode off-TPU); backward = blockwise
    recomputation in plain XLA — O(T·block) memory in both directions, the
    [T, T] score matrix is never materialized.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    interpret = jax.default_backend() != "tpu"
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def flash_attention_head_parallel(
    q,
    k,
    v,
    *,
    axis: str | None,
    causal: bool = False,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
):
    """:func:`flash_attention` on a tensor-parallel sharded plan: each
    ``axis`` rank runs the pallas kernel on its LOCAL heads.

    The pallas kernel is an opaque custom call to the XLA SPMD partitioner,
    so under a sharded plan the unwrapped kernel forces a gather to full
    heads per device — the exact memory blow-up the plan exists to avoid.
    Wrapping it in a head-parallel ``shard_map`` over the model axis keeps
    the ``[B, H_local, T, D]`` blocks resident: attention is head-local math
    (softmax normalizes per head), so the per-rank kernel computes bits
    identical to the full-head kernel's.

    Resolution order at trace time:

    - no ``axis``, no active mesh, ``axis`` not on the mesh, or a 1-way
      axis → the plain kernel (unsharded behavior, bit-identical);
    - heads divide the axis → per-rank kernel under ``compat.shard_map``;
    - heads do NOT divide the axis → :func:`attention_reference` (plain XLA
      — the partitioner can split *its* einsums head-wise) with a loud
      warning, because silently gathering the kernel would defeat the plan.
    """
    from fedml_tpu.parallel import compat

    mesh = compat.current_mesh()
    if (
        axis is None
        or mesh is None
        or axis not in mesh.axis_names
        or mesh.shape[axis] == 1
    ):
        return flash_attention(q, k, v, causal, sm_scale, block_q, block_k)
    n_ranks = int(mesh.shape[axis])
    n_heads = q.shape[1]
    if n_heads % n_ranks:
        import logging

        logging.getLogger(__name__).warning(
            "flash attention under a %d-way %r model axis: %d heads do not "
            "divide the axis, so the pallas kernel cannot run per-rank — "
            "falling back to gathered xla attention for this program; pick "
            "num_heads divisible by the model axis to keep the kernel on "
            "the sharded path",
            n_ranks, axis, n_heads,
        )
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    from jax.sharding import PartitionSpec

    hspec = PartitionSpec(None, axis, None, None)
    return compat.shard_map(
        functools.partial(
            flash_attention, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k,
        ),
        mesh=mesh, in_specs=(hspec,) * 3, out_specs=hspec,
        axis_names={axis}, check_vma=False,
    )(q, k, v)


def _fwd_rule(q, k, v, causal, sm_scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out)


def _bwd_rule(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, out = res
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _blockwise_bwd(q, k, v, out, g, causal, sm_scale, block_k)


flash_attention.defvjp(_fwd_rule, _bwd_rule)
