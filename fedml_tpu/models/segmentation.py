"""Semantic-segmentation models for federated segmentation (fedseg).

The reference's fedseg package trains torchvision-style DeepLab/UNet encoders
held outside the repo (SURVEY §2.2 fedseg row: "torchvision-style seg models
(external)") — the in-repo capability is the federated wrapper + evaluator.
Here the zoo carries its own compact TPU-friendly models so fedseg runs end
to end:

- ``UNet`` — classic encoder/decoder with skip connections.
- ``DeepLabLite`` — dilated-conv encoder + ASPP head (DeepLabV3 shape).

Both use GroupNorm (cross-client BN statistics are the reference's known
pain point, SURVEY §7 "BatchNorm across clients") and NHWC layouts; every
conv maps onto the MXU as an implicit matmul. Inputs ``[B, H, W, C]``,
logits ``[B, H, W, num_classes]``.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def _gn(groups: int, c: int) -> int:
    g = min(groups, c)
    while c % g:
        g -= 1
    return g


def _interp_matrix(src: int, dst: int, method: str) -> jnp.ndarray:
    """[dst, src] 1-D interpolation matrix (half-pixel centers).

    Upsampling as two einsum contractions instead of ``jax.image.resize``:
    resize's transpose lowers to a feature-grouped conv that XLA's SPMD
    partitioner rejects when the batch axis is sharded (the vmapped-cohort
    case); a matmul transposes to a matmul and rides the MXU."""
    import numpy as np

    if method == "nearest":
        src_idx = np.clip(((np.arange(dst) + 0.5) * src / dst).astype(int), 0, src - 1)
        m = np.zeros((dst, src), np.float32)
        m[np.arange(dst), src_idx] = 1.0
        return jnp.asarray(m)
    # bilinear
    coords = (np.arange(dst) + 0.5) * src / dst - 0.5
    lo = np.clip(np.floor(coords).astype(int), 0, src - 1)
    hi = np.clip(lo + 1, 0, src - 1)
    frac = np.clip(coords - lo, 0.0, 1.0)
    m = np.zeros((dst, src), np.float32)
    np.add.at(m, (np.arange(dst), lo), 1.0 - frac)
    np.add.at(m, (np.arange(dst), hi), frac)
    return jnp.asarray(m)


def upsample_2d(x: jnp.ndarray, out_hw: tuple[int, int], method: str = "nearest") -> jnp.ndarray:
    """[B, H, W, C] -> [B, out_h, out_w, C] via separable interpolation einsums."""
    mh = _interp_matrix(x.shape[1], out_hw[0], method)
    mw = _interp_matrix(x.shape[2], out_hw[1], method)
    return jnp.einsum("hH,bHWc,wW->bhwc", mh, x, mw)


class ConvBlock(nn.Module):
    features: int
    dilation: int = 1

    @nn.compact
    def __call__(self, x):
        for _ in range(2):
            x = nn.Conv(self.features, (3, 3), kernel_dilation=self.dilation,
                        padding="SAME", use_bias=False)(x)
            x = nn.GroupNorm(num_groups=_gn(8, self.features))(x)
            x = nn.relu(x)
        return x


class UNet(nn.Module):
    num_classes: int = 21
    features: Sequence[int] = (32, 64, 128)

    @nn.compact
    def __call__(self, x, train: bool = False):
        skips = []
        for f in self.features[:-1]:
            x = ConvBlock(f)(x)
            skips.append(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = ConvBlock(self.features[-1])(x)
        for f, skip in zip(reversed(self.features[:-1]), reversed(skips)):
            b, h, w, _ = skip.shape
            x = upsample_2d(x, (h, w), "nearest")
            x = nn.Conv(f, (2, 2), padding="SAME")(x)
            x = jnp.concatenate([x, skip], axis=-1)
            x = ConvBlock(f)(x)
        return nn.Conv(self.num_classes, (1, 1))(x)


class ASPP(nn.Module):
    """Atrous spatial pyramid pooling (DeepLabV3 head)."""

    features: int = 128
    rates: Sequence[int] = (1, 2, 4)

    @nn.compact
    def __call__(self, x):
        branches = [
            ConvBlock(self.features, dilation=r)(x) for r in self.rates
        ]
        # image-level pooling branch
        pooled = jnp.mean(x, axis=(1, 2), keepdims=True)
        pooled = nn.Conv(self.features, (1, 1))(pooled)
        pooled = jnp.broadcast_to(
            pooled, (x.shape[0], x.shape[1], x.shape[2], self.features)
        )
        x = jnp.concatenate(branches + [pooled], axis=-1)
        return nn.Conv(self.features, (1, 1))(x)


class DeepLabLite(nn.Module):
    num_classes: int = 21
    features: Sequence[int] = (32, 64, 128)

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_h, in_w = x.shape[1], x.shape[2]
        x = ConvBlock(self.features[0])(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = ConvBlock(self.features[1])(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = ConvBlock(self.features[2], dilation=2)(x)  # dilated, no more stride
        x = ASPP(self.features[2])(x)
        logits = nn.Conv(self.num_classes, (1, 1))(x)
        return upsample_2d(logits, (in_h, in_w), "bilinear")
