"""Model registry: (model_name, dataset) -> Flax module, mirroring the
reference dispatch (fedml_experiments/distributed/fedavg/main_fedavg.py:354-390
``create_model``) so reference run configs translate 1:1."""

from __future__ import annotations

from typing import Any

from fedml_tpu.models.cnn import CNNDropOut, CNNOriginalFedAvg, LeNet
from fedml_tpu.models.gan import Discriminator, Generator
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.models.mobilenet import MobileNet, MobileNetV3
from fedml_tpu.models.resnet import ResNet18, resnet18_gn, resnet56, resnet110
from fedml_tpu.models.rnn import RNNOriginalFedAvg, RNNStackOverflow
from fedml_tpu.models.transformer import TransformerLM
from fedml_tpu.models.vgg import VGG


def create_model(model_name: str, output_dim: int, dataset: str = "",
                 dtype: Any = None) -> Any:
    """Reference name/dataset dispatch (main_fedavg.py:354-390). Returns the
    Flax module; task selection (classification/nwp/tag) is the trainer's job
    as in the reference (FedAvgAPI.py:85-91).

    ``dtype`` (jnp dtype or string like "bfloat16") selects the compute
    dtype for models that support one (the CV zoo + TransformerLM); models
    without a dtype field raise a clear error rather than silently ignoring
    the request."""
    model = _create(model_name, output_dim, dataset)
    if dtype is not None and str(dtype) != "float32":
        import dataclasses

        import jax.numpy as jnp

        if isinstance(dtype, str):
            dtype = jnp.dtype(dtype).type
        if not any(f.name == "dtype" for f in dataclasses.fields(model)):
            raise ValueError(
                f"model {model_name!r} does not take a compute dtype"
            )
        model = model.clone(dtype=dtype)
    return model


def _create(model_name: str, output_dim: int, dataset: str = "") -> Any:
    if model_name == "lr" and dataset == "stackoverflow_lr":
        return LogisticRegression(num_classes=output_dim)  # 10004-dim input handled by data
    if model_name == "lr":
        return LogisticRegression(num_classes=output_dim)
    if model_name == "rnn" and dataset == "stackoverflow_nwp":
        return RNNStackOverflow()
    if model_name == "rnn":  # shakespeare / fed_shakespeare
        return RNNOriginalFedAvg()
    if model_name == "cnn":  # femnist
        return CNNDropOut(num_classes=output_dim)
    if model_name == "lenet":  # mobile family (reference torch_lenet.py)
        return LeNet(num_classes=output_dim)
    if model_name == "cnn_original":
        return CNNOriginalFedAvg(num_classes=output_dim)
    if model_name == "resnet18_gn":
        return resnet18_gn(class_num=output_dim)
    if model_name == "resnet56":
        return resnet56(class_num=output_dim)
    if model_name == "resnet110":
        return resnet110(class_num=output_dim)
    if model_name == "mobilenet":
        return MobileNet(num_classes=output_dim)
    if model_name == "mobilenet_v3":
        return MobileNetV3(num_classes=output_dim, mode="large")
    if model_name.startswith("efficientnet"):
        from fedml_tpu.models.efficientnet import efficientnet

        name = model_name if "-" in model_name else "efficientnet-b0"
        return efficientnet(name, num_classes=output_dim)
    if model_name == "unet":
        from fedml_tpu.models.segmentation import UNet

        return UNet(num_classes=output_dim)
    if model_name in ("deeplab", "deeplab_lite"):
        from fedml_tpu.models.segmentation import DeepLabLite

        return DeepLabLite(num_classes=output_dim)
    if model_name == "transformer":
        # long-context LM client (no reference equivalent — extends the zoo
        # past nlp/rnn.py; attn_impl flash/ring for single-/multi-chip)
        return TransformerLM(vocab_size=output_dim)
    if model_name.startswith("vgg"):
        depth = int(model_name[3:] or 16)
        return VGG(depth=depth, num_classes=output_dim)
    raise ValueError(f"unknown model {model_name!r} (dataset={dataset!r})")


TASK_BY_DATASET = {
    # reference trainer dispatch (fedml_api/distributed/fedavg/FedAvgAPI.py:85-91)
    "stackoverflow_lr": "tag",
    "stackoverflow_nwp": "nwp",
    "shakespeare": "char_lm",
    "fed_shakespeare": "char_lm",
}


def task_for_dataset(dataset: str) -> str:
    return TASK_BY_DATASET.get(dataset, "classification")
