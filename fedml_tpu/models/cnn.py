"""FedAvg-paper CNNs (reference: fedml_api/model/cv/cnn.py:5 CNN_OriginalFedAvg,
:74 CNN_DropOut).

Architecture (McMahan et al. 2017 / TFF baselines): two 5x5 conv layers
(32, 64 channels) each followed by 2x2 max-pool, then a 512-unit dense layer
and the classifier head. ``CNN_DropOut`` is the TFF variant with 3x3 convs and
dropout. Inputs are NHWC float images ([B, 28, 28] or [B, 28, 28, 1]);
channels-last is the TPU-friendly layout.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def _ensure_nhwc(x):
    if x.ndim == 3:
        x = x[..., None]
    return x.astype(jnp.float32)


class CNNOriginalFedAvg(nn.Module):
    """2x(conv5x5 + maxpool) + FC512 + head; ~1.66M params for femnist."""

    num_classes: int = 62
    only_digits: bool = False
    dtype: jnp.dtype = jnp.float32  # compute dtype (bf16 on TPU); params f32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _ensure_nhwc(x)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        return nn.Dense(10 if self.only_digits else self.num_classes)(
            x.astype(jnp.float32)
        )


class CNNDropOut(nn.Module):
    """TFF dropout variant (cnn.py:74): conv3x3(32) → conv3x3(64) → pool →
    dropout(.25) → FC128 → dropout(.5) → head."""

    num_classes: int = 62
    only_digits: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _ensure_nhwc(x)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else self.num_classes)(
            x.astype(jnp.float32)
        )


class LeNet(nn.Module):
    """LeNet-5 for the mobile client family (reference
    fedml_api/model/mobile/torch_lenet.py LeNet and its MNN twin
    mnn_lenet.py — conv 1->20 5x5, conv 20->50 5x5, fc 800->500, fc 500->10,
    max-pool after each conv). The on-device exchange format for this model
    is the aligned flat weight list (fedml_tpu/models/export.py)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype)(_ensure_nhwc(x))
        h = nn.max_pool(h, (2, 2), strides=(2, 2))
        h = nn.relu(h)
        h = nn.Conv(50, (5, 5), padding="VALID", dtype=self.dtype)(h)
        h = nn.max_pool(h, (2, 2), strides=(2, 2))
        h = nn.relu(h)
        h = h.reshape((h.shape[0], -1))
        h = nn.relu(nn.Dense(500, dtype=self.dtype)(h))
        return nn.Dense(self.num_classes)(h.astype(jnp.float32))
