"""MobileNet V1 and V3 (reference: fedml_api/model/cv/mobilenet.py:207
``mobilenet``, cv/mobilenet_v3.py:137 ``MobileNetV3`` — the cross-silo CV
models).

Depthwise separable convolutions map to the TPU as grouped convs;
channels-last NHWC throughout.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class DepthwiseSeparable(nn.Module):
    filters: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        x = nn.Conv(in_ch, (3, 3), strides=self.stride, padding="SAME",
                    feature_group_count=in_ch, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        x = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        return nn.relu(nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x))


class MobileNet(nn.Module):
    """MobileNet V1 (width 1.0). ``small_input`` keeps stride-1 stem for CIFAR."""

    num_classes: int = 10
    small_input: bool = True
    dtype: jnp.dtype = jnp.float32  # compute dtype (bf16 on TPU); params f32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(jnp.float32)
        stem_stride = 1 if self.small_input else 2
        x = nn.Conv(32, (3, 3), strides=stem_stride, padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1)]
        for filters, stride in cfg:
            x = DepthwiseSeparable(filters, stride, self.dtype)(x, train=train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def _hard_sigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


def _hard_swish(x):
    return x * _hard_sigmoid(x)


class SqueezeExcite(nn.Module):
    reduce: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        ch = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(max(ch // self.reduce, 8), dtype=self.dtype)(s))
        s = _hard_sigmoid(nn.Dense(ch, dtype=self.dtype)(s))
        return x * s[:, None, None, :]


class InvertedResidual(nn.Module):
    expand: int
    filters: int
    kernel: int
    stride: int
    use_se: bool
    use_hs: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = _hard_swish if self.use_hs else nn.relu
        bn = lambda: nn.BatchNorm(use_running_average=not train, dtype=self.dtype)  # noqa: E731
        inp = x.shape[-1]
        y = x
        if self.expand != inp:
            y = nn.Conv(self.expand, (1, 1), use_bias=False, dtype=self.dtype)(y)
            y = act(bn()(y))
        y = nn.Conv(self.expand, (self.kernel, self.kernel), strides=self.stride,
                    padding="SAME", feature_group_count=self.expand,
                    use_bias=False, dtype=self.dtype)(y)
        y = act(bn()(y))
        if self.use_se:
            y = SqueezeExcite(dtype=self.dtype)(y)
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = bn()(y)
        if self.stride == 1 and inp == self.filters:
            y = y + x
        return y


# (expand, filters, kernel, stride, SE, hard-swish) per mobilenet_v3 paper
_V3_LARGE = [
    (16, 16, 3, 1, False, False), (64, 24, 3, 2, False, False),
    (72, 24, 3, 1, False, False), (72, 40, 5, 2, True, False),
    (120, 40, 5, 1, True, False), (120, 40, 5, 1, True, False),
    (240, 80, 3, 2, False, True), (200, 80, 3, 1, False, True),
    (184, 80, 3, 1, False, True), (184, 80, 3, 1, False, True),
    (480, 112, 3, 1, True, True), (672, 112, 3, 1, True, True),
    (672, 160, 5, 2, True, True), (960, 160, 5, 1, True, True),
    (960, 160, 5, 1, True, True),
]
_V3_SMALL = [
    (16, 16, 3, 2, True, False), (72, 24, 3, 2, False, False),
    (88, 24, 3, 1, False, False), (96, 40, 5, 2, True, True),
    (240, 40, 5, 1, True, True), (240, 40, 5, 1, True, True),
    (120, 48, 5, 1, True, True), (144, 48, 5, 1, True, True),
    (288, 96, 5, 2, True, True), (576, 96, 5, 1, True, True),
    (576, 96, 5, 1, True, True),
]


class MobileNetV3(nn.Module):
    num_classes: int = 10
    mode: str = "small"
    small_input: bool = True
    dtype: jnp.dtype = jnp.float32  # compute dtype (bf16 on TPU); params f32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(jnp.float32)
        cfg = _V3_SMALL if self.mode == "small" else _V3_LARGE
        stem_stride = 1 if self.small_input else 2
        x = nn.Conv(16, (3, 3), strides=stem_stride, padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        x = _hard_swish(nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        for block_cfg in cfg:
            x = InvertedResidual(*block_cfg, dtype=self.dtype)(x, train=train)
        head = 576 if self.mode == "small" else 960
        x = nn.Conv(head, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = _hard_swish(nn.BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        x = _hard_swish(nn.Dense(1280 if self.mode == "large" else 1024)(x))
        return nn.Dense(self.num_classes)(x)
