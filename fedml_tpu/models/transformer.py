"""Decoder-only transformer LM for long-context federated clients.

The reference's NLP zoo stops at LSTMs (fedml_api/model/nlp/rnn.py:4,39); this
model extends the zoo to transformer clients with three attention paths:

- ``attn_impl="xla"``  — plain dot-product attention (small sequences; XLA
  fuses it fine).
- ``attn_impl="flash"`` — the pallas blockwise kernel
  (fedml_tpu/ops/attention.py): O(T) memory on one chip.
- ``attn_impl="ring"``  — ring attention over the ``sp`` mesh axis
  (fedml_tpu/parallel/ring_attention.py); the module must then run inside
  ``shard_map`` with the sequence axis sharded (see
  fedml_tpu/parallel/sequence.py). Every other op in this module is
  token-local, so the module is sequence-parallel-safe by construction.

Same interface as the rest of the zoo: int tokens ``[B, T]`` in, logits
``[B, T, V]`` out, ``train`` kwarg, dropout rng when training.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.ops.attention import (
    attention_reference,
    flash_attention,
    flash_attention_head_parallel,
)
from fedml_tpu.parallel.ring_attention import ring_attention


class MultiHeadSelfAttention(nn.Module):
    num_heads: int
    attn_impl: str = "xla"  # xla | flash | ring
    sp_axis: str = "sp"
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32  # compute dtype (bf16 on TPU); params stay f32
    # model-parallel mesh axis (docs/PERFORMANCE.md "Sharded client
    # models"): when set, head-axis sharding constraints pin q/k/v to the
    # tensor-parallel layout the partition rules put on the qkv kernel, so
    # each model shard attends over its own heads. Requires tracing under
    # the plan's mesh (parallel/dispatch.py provides the context). GSPMD
    # partitions the xla attention path by heads on its own; the pallas
    # flash kernel is an opaque custom call to the partitioner, so the
    # flash path routes through ops.attention.flash_attention_head_parallel
    # (a per-rank shard_map over this axis, with a gathered-xla fallback
    # when heads don't divide it).
    mp_axis: str | None = None
    # flash kernel tile sizes, tuned on a v5e at T=1024, D_head=128: a tall
    # 256-row query block with the whole 1024-key sequence in one block beat
    # the 128x128 default by ~4% end-to-end MFU (_pick_block clamps both to T)
    block_q: int = 256
    block_k: int = 1024

    @nn.compact
    def __call__(self, x, train: bool = False):
        from fedml_tpu.parallel.rules import constrain

        b, t, c = x.shape
        head_dim = c // self.num_heads
        qkv = nn.Dense(3 * c, use_bias=False, name="qkv", dtype=self.dtype)(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(a):  # [B, T, C] -> [B, H, T, D]
            return a.reshape(b, t, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if self.mp_axis:
            hspec = (None, self.mp_axis, None, None)
            q = constrain(q, hspec)
            k = constrain(k, hspec)
            v = constrain(v, hspec)
        if self.attn_impl == "flash":
            # head-parallel under a TP plan (mp_axis set + active mesh):
            # each model rank runs the pallas kernel on its local heads;
            # plain kernel otherwise — see flash_attention_head_parallel
            o = flash_attention_head_parallel(
                q, k, v, axis=self.mp_axis, causal=True,
                block_q=self.block_q, block_k=self.block_k)
        elif self.attn_impl == "ring":
            o = ring_attention(q, k, v, axis_name=self.sp_axis, causal=True)
        else:
            o = attention_reference(q, k, v, causal=True)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, c)
        o = nn.Dense(c, use_bias=False, name="proj", dtype=self.dtype)(o)
        if self.dropout_rate:
            o = nn.Dropout(self.dropout_rate, deterministic=not train)(o)
        return o


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    attn_impl: str = "xla"
    sp_axis: str = "sp"
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    # model-parallel mesh axis: when set, the MLP hidden activation is
    # pinned to the column-parallel layout of the Dense_0 kernel and the
    # block output to the replicated boundary layout (the Megatron
    # between-blocks contract) — see parallel/rules.py act_spec
    mp_axis: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        from fedml_tpu.parallel.rules import constrain

        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadSelfAttention(
            self.num_heads, self.attn_impl, self.sp_axis, self.dropout_rate,
            dtype=self.dtype, mp_axis=self.mp_axis,
        )(h, train=train)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        c = x.shape[-1]
        m = nn.Dense(self.mlp_ratio * c, dtype=self.dtype)(h)
        if self.mp_axis:
            m = constrain(m, (None, None, self.mp_axis))
        m = nn.gelu(m)
        m = nn.Dense(c, dtype=self.dtype)(m)
        if self.dropout_rate:
            m = nn.Dropout(self.dropout_rate, deterministic=not train)(m)
        out = x + m
        if self.mp_axis:
            out = constrain(out, (None, None, None))
        return out


class TransformerLM(nn.Module):
    """Causal LM. Position embedding is computed from the *global* token
    position: under sequence parallelism each shard adds ``pos_offset`` (set
    by the SP train step) so token-locality is preserved."""

    vocab_size: int = 90
    embed_dim: int = 128
    num_layers: int = 2
    num_heads: int = 4
    max_len: int = 4096
    attn_impl: str = "xla"
    sp_axis: str = "sp"
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    # model-parallel mesh axis for tensor-parallel plans (docs/
    # PERFORMANCE.md "Sharded client models"): threaded to every Block so
    # block-boundary activations carry explicit sharding constraints. The
    # engine sets it automatically when a TP rule set is active
    # (sim/engine.py); leave None for unsharded / FSDP-gather execution.
    mp_axis: str | None = None
    # LM-head matmul dtype, independent of the block compute dtype: an f32
    # head runs the MXU at half rate but skips two [B, T, V]-sized dtype
    # converts (logits + their gradient). Which side wins is shape-dependent;
    # measured on a v5e at D=1024-2048, T=1024, V=32k the f32 head was ~6%
    # faster end-to-end, hence the default
    head_dtype: jnp.dtype = jnp.float32
    # rematerialize each block's activations in the backward pass
    # (jax.checkpoint): ~1/L of the activation memory for ~33% more FLOPs —
    # the standard TPU trade when HBM, not MXU, binds the batch size
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False, pos_offset: int | jnp.ndarray = 0):
        b, t = x.shape
        tok = nn.Embed(self.vocab_size, self.embed_dim, name="tok_embed",
                       dtype=self.dtype)(x)
        pos_table = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_len, self.embed_dim),
        )
        pos_idx = pos_offset + jnp.arange(t)
        h = tok + jnp.take(pos_table, pos_idx, axis=0)[None].astype(self.dtype)
        # train selects the dropout branch: it must be static under remat
        block_cls = nn.remat(Block, static_argnums=(2,)) if self.remat else Block
        for i in range(self.num_layers):
            h = block_cls(
                self.num_heads,
                attn_impl=self.attn_impl,
                sp_axis=self.sp_axis,
                dropout_rate=self.dropout_rate,
                dtype=self.dtype,
                mp_axis=self.mp_axis,
                name=f"block_{i}",
            )(h, train)
        h = nn.LayerNorm(dtype=self.dtype, name="ln_f")(h)
        # the loss always receives f32 logits (softmax headroom); with a
        # bf16 head they are bf16-quantized before the upcast
        return nn.Dense(self.vocab_size, name="head",
                        dtype=self.head_dtype)(h).astype(jnp.float32)
