"""Linear models (reference: fedml_api/model/linear/lr.py:4).

The reference LogisticRegression is Linear(784 -> C) + sigmoid trained with a
CE criterion; here it is a Flax Dense producing logits — the loss applies the
link function, which is the numerically-stable idiom.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LogisticRegression(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        return nn.Dense(self.num_classes)(x)
