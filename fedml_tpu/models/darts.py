"""DARTS search space for federated NAS.

Reference: fedml_api/model/cv/darts/ — ``model_search.py`` (mixed ops over a
cell DAG, 306 LoC), ``operations.py`` (candidate op set), ``genotypes.py``
(genotype encode/decode), ``architect.py:13`` (bilevel architecture step).

Design: architecture parameters α live in their own ``arch`` variable
collection, separate from ``params`` — the FedNAS server averages both
(FedNASAggregator.py:71-113 averages weights AND alphas), and the client's
bilevel search alternates grads w.r.t. the two collections. The mixed op is a
softmax(α)-weighted sum of candidate branches — all branches execute (dense,
MXU-friendly); discretization happens only at genotype decode.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

PRIMITIVES = ("none", "skip_connect", "conv_3x3", "sep_conv_3x3", "avg_pool_3x3", "max_pool_3x3")


class _Op(nn.Module):
    kind: str
    channels: int
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        k = self.kind
        if k == "none":
            if self.stride > 1:
                x = x[:, :: self.stride, :: self.stride, :]
            return jnp.zeros_like(x) if x.shape[-1] == self.channels else jnp.zeros(
                x.shape[:-1] + (self.channels,), x.dtype
            )
        if k == "skip_connect":
            if self.stride == 1 and x.shape[-1] == self.channels:
                return x
            # factorized reduce
            return nn.Conv(self.channels, (1, 1), strides=self.stride, use_bias=False)(x)
        if k == "conv_3x3":
            h = nn.relu(x)
            h = nn.Conv(self.channels, (3, 3), strides=self.stride, padding="SAME", use_bias=False)(h)
            return nn.BatchNorm(use_running_average=not train)(h)
        if k == "sep_conv_3x3":
            h = nn.relu(x)
            c_in = h.shape[-1]
            h = nn.Conv(c_in, (3, 3), strides=self.stride, padding="SAME",
                        feature_group_count=c_in, use_bias=False)(h)
            h = nn.Conv(self.channels, (1, 1), use_bias=False)(h)
            return nn.BatchNorm(use_running_average=not train)(h)
        if k in ("avg_pool_3x3", "max_pool_3x3"):
            pool = nn.avg_pool if k.startswith("avg") else nn.max_pool
            h = pool(x, (3, 3), strides=(self.stride, self.stride), padding="SAME")
            if h.shape[-1] != self.channels:
                h = nn.Conv(self.channels, (1, 1), use_bias=False)(h)
            return h
        raise ValueError(k)


class MixedOp(nn.Module):
    channels: int
    stride: int

    @nn.compact
    def __call__(self, x, weights, train: bool = False):
        outs = [_Op(p, self.channels, self.stride)(x, train=train) for p in PRIMITIVES]
        return sum(w * o for w, o in zip(weights, outs))


class Cell(nn.Module):
    """DAG cell: ``steps`` intermediate nodes, each summing mixed ops over all
    previous states (model_search.py Cell)."""

    channels: int
    steps: int = 3
    reduction: bool = False

    @nn.compact
    def __call__(self, s0, s1, alphas, train: bool = False):
        s0 = nn.Conv(self.channels, (1, 1), use_bias=False)(nn.relu(s0))
        if s1.shape[1] != s0.shape[1]:  # previous cell reduced
            s0 = nn.avg_pool(s0, (2, 2), strides=(2, 2))
        s1 = nn.Conv(self.channels, (1, 1), use_bias=False)(nn.relu(s1))
        states = [s0, s1]
        offset = 0
        weights = jax.nn.softmax(alphas, axis=-1)
        for i in range(self.steps):
            acc = None
            for j, h in enumerate(states):
                stride = 2 if self.reduction and j < 2 else 1
                out = MixedOp(self.channels, stride)(h, weights[offset + j], train=train)
                acc = out if acc is None else acc + out
            offset += len(states)
            states.append(acc)
        return jnp.concatenate(states[-self.steps:], axis=-1)


def num_edges(steps: int) -> int:
    return sum(2 + i for i in range(steps))


class DARTSNetwork(nn.Module):
    """Searchable network (model_search.py Network): stem → cells → classifier.
    α lives in the ``arch`` collection: ``arch/alphas_normal`` and
    ``arch/alphas_reduce`` [E, |PRIMITIVES|]."""

    num_classes: int = 10
    channels: int = 8
    layers: int = 4
    steps: int = 3

    @nn.compact
    def __call__(self, x, train: bool = False):
        E = num_edges(self.steps)
        a_n = self.variable("arch", "alphas_normal",
                            lambda: 1e-3 * jax.random.normal(self.make_rng("params"), (E, len(PRIMITIVES))))
        a_r = self.variable("arch", "alphas_reduce",
                            lambda: 1e-3 * jax.random.normal(self.make_rng("params"), (E, len(PRIMITIVES))))
        h = nn.Conv(self.channels * 3, (3, 3), padding="SAME", use_bias=False)(x.astype(jnp.float32))
        h = nn.BatchNorm(use_running_average=not train)(h)
        s0 = s1 = h
        c = self.channels
        for layer in range(self.layers):
            reduction = layer in (self.layers // 3, 2 * self.layers // 3) and self.layers >= 3
            if reduction:
                c *= 2
            cell = Cell(c, self.steps, reduction)
            s0, s1 = s1, cell(s0, s1, a_r.value if reduction else a_n.value, train=train)
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(out)


@dataclasses.dataclass
class Genotype:
    normal: list[tuple[str, int]]
    reduce: list[tuple[str, int]]


def decode_genotype(alphas_normal: np.ndarray, alphas_reduce: np.ndarray, steps: int = 3) -> Genotype:
    """Argmax decode (genotypes.py / FedNASAggregator.record_model_global_
    architecture:173): per node keep the 2 strongest non-'none' incoming edges."""

    def _decode(alphas):
        gene = []
        offset = 0
        none_idx = PRIMITIVES.index("none")
        w = np.asarray(jax.nn.softmax(jnp.asarray(alphas), axis=-1))
        for i in range(steps):
            n_in = 2 + i
            edges = w[offset : offset + n_in].copy()
            edges[:, none_idx] = -1
            strength = edges.max(axis=1)
            top2 = np.argsort(-strength)[:2]
            for j in sorted(top2):
                gene.append((PRIMITIVES[int(np.argmax(edges[j]))], int(j)))
            offset += n_in
        return gene

    return Genotype(_decode(alphas_normal), _decode(alphas_reduce))
