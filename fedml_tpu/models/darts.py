"""DARTS search space for federated NAS.

Reference: fedml_api/model/cv/darts/ — ``model_search.py`` (mixed ops over a
cell DAG, 306 LoC), ``operations.py`` (candidate op set), ``genotypes.py``
(genotype encode/decode), ``architect.py:13`` (bilevel architecture step).

Design: architecture parameters α live in their own ``arch`` variable
collection, separate from ``params`` — the FedNAS server averages both
(FedNASAggregator.py:71-113 averages weights AND alphas), and the client's
bilevel search alternates grads w.r.t. the two collections. The mixed op is a
softmax(α)-weighted sum of candidate branches — all branches execute (dense,
MXU-friendly); discretization happens only at genotype decode.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

PRIMITIVES = ("none", "skip_connect", "conv_3x3", "sep_conv_3x3", "avg_pool_3x3", "max_pool_3x3")


class _Op(nn.Module):
    kind: str
    channels: int
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        k = self.kind
        if k == "none":
            if self.stride > 1:
                x = x[:, :: self.stride, :: self.stride, :]
            return jnp.zeros_like(x) if x.shape[-1] == self.channels else jnp.zeros(
                x.shape[:-1] + (self.channels,), x.dtype
            )
        if k == "skip_connect":
            if self.stride == 1 and x.shape[-1] == self.channels:
                return x
            # factorized reduce
            return nn.Conv(self.channels, (1, 1), strides=self.stride, use_bias=False)(x)
        if k == "conv_3x3":
            h = nn.relu(x)
            h = nn.Conv(self.channels, (3, 3), strides=self.stride, padding="SAME", use_bias=False)(h)
            return nn.BatchNorm(use_running_average=not train)(h)
        if k == "sep_conv_3x3":
            h = nn.relu(x)
            c_in = h.shape[-1]
            h = nn.Conv(c_in, (3, 3), strides=self.stride, padding="SAME",
                        feature_group_count=c_in, use_bias=False)(h)
            h = nn.Conv(self.channels, (1, 1), use_bias=False)(h)
            return nn.BatchNorm(use_running_average=not train)(h)
        if k in ("avg_pool_3x3", "max_pool_3x3"):
            pool = nn.avg_pool if k.startswith("avg") else nn.max_pool
            h = pool(x, (3, 3), strides=(self.stride, self.stride), padding="SAME")
            if h.shape[-1] != self.channels:
                h = nn.Conv(self.channels, (1, 1), use_bias=False)(h)
            return h
        raise ValueError(k)


class MixedOp(nn.Module):
    channels: int
    stride: int

    @nn.compact
    def __call__(self, x, weights, train: bool = False):
        outs = [_Op(p, self.channels, self.stride)(x, train=train) for p in PRIMITIVES]
        return sum(w * o for w, o in zip(weights, outs))


class Cell(nn.Module):
    """DAG cell: ``steps`` intermediate nodes, each summing mixed ops over all
    previous states (model_search.py Cell)."""

    channels: int
    steps: int = 3
    reduction: bool = False

    @nn.compact
    def __call__(self, s0, s1, weights, train: bool = False):
        # ``weights`` [E, |PRIMITIVES|] are already normalized edge weights:
        # softmax(alpha) for DARTS, a straight-through Gumbel one-hot for
        # GDAS (model_search_gdas.py:122-133)
        s0 = nn.Conv(self.channels, (1, 1), use_bias=False)(nn.relu(s0))
        if s1.shape[1] != s0.shape[1]:  # previous cell reduced
            s0 = nn.avg_pool(s0, (2, 2), strides=(2, 2))
        s1 = nn.Conv(self.channels, (1, 1), use_bias=False)(nn.relu(s1))
        states = [s0, s1]
        offset = 0
        for i in range(self.steps):
            acc = None
            for j, h in enumerate(states):
                stride = 2 if self.reduction and j < 2 else 1
                out = MixedOp(self.channels, stride)(h, weights[offset + j], train=train)
                acc = out if acc is None else acc + out
            offset += len(states)
            states.append(acc)
        return jnp.concatenate(states[-self.steps:], axis=-1)


def num_edges(steps: int) -> int:
    return sum(2 + i for i in range(steps))


def gumbel_hard_weights(alphas, rng, tau: float):
    """Straight-through Gumbel-softmax over the op axis (torch
    F.gumbel_softmax(alphas, tau, hard=True), model_search_gdas.py:127-129):
    hard one-hot forward, soft gradient."""
    g = jax.random.gumbel(rng, alphas.shape)
    soft = jax.nn.softmax((alphas + g) / tau, axis=-1)
    hard = jax.nn.one_hot(jnp.argmax(soft, axis=-1), alphas.shape[-1])
    return hard + soft - jax.lax.stop_gradient(soft)


class DARTSNetwork(nn.Module):
    """Searchable network (model_search.py Network): stem → cells → classifier.
    α lives in the ``arch`` collection: ``arch/alphas_normal`` and
    ``arch/alphas_reduce`` [E, |PRIMITIVES|].

    ``search_mode="gdas"`` switches to the Gumbel-softmax variant
    (model_search_gdas.py Network_GumbelSoftmax): each forward draws ONE
    hard op selection per edge (straight-through gradient, temperature
    ``tau``), shared by all cells of the same type, exactly like the
    reference's per-forward F.gumbel_softmax. All branches still execute
    densely and the one-hot selects — on TPU the dense batched form keeps
    the MXU busy, whereas per-edge lax.switch would serialize tiny kernels.
    Training needs a ``gumbel`` rng stream; eval uses the argmax ops."""

    num_classes: int = 10
    channels: int = 8
    layers: int = 4
    steps: int = 3
    search_mode: str = "darts"  # darts | gdas
    tau: float = 5.0  # gdas temperature (reference sets 5, annealed outside)

    @nn.compact
    def __call__(self, x, train: bool = False):
        E = num_edges(self.steps)
        a_n = self.variable("arch", "alphas_normal",
                            lambda: 1e-3 * jax.random.normal(self.make_rng("params"), (E, len(PRIMITIVES))))
        a_r = self.variable("arch", "alphas_reduce",
                            lambda: 1e-3 * jax.random.normal(self.make_rng("params"), (E, len(PRIMITIVES))))

        def edge_weights(alphas):
            if self.search_mode == "gdas":
                if train:
                    return gumbel_hard_weights(
                        alphas, self.make_rng("gumbel"), self.tau
                    )
                return jax.nn.one_hot(
                    jnp.argmax(alphas, axis=-1), alphas.shape[-1]
                )
            return jax.nn.softmax(alphas, axis=-1)

        # one sample per forward, shared across same-type cells (the
        # reference draws per cell-visit, but alphas are shared, so one draw
        # per type is the faithful single-sample semantics and cheaper)
        w_n = edge_weights(a_n.value)
        w_r = edge_weights(a_r.value)
        h = nn.Conv(self.channels * 3, (3, 3), padding="SAME", use_bias=False)(x.astype(jnp.float32))
        h = nn.BatchNorm(use_running_average=not train)(h)
        s0 = s1 = h
        c = self.channels
        for layer in range(self.layers):
            reduction = layer in (self.layers // 3, 2 * self.layers // 3) and self.layers >= 3
            if reduction:
                c *= 2
            cell = Cell(c, self.steps, reduction)
            s0, s1 = s1, cell(s0, s1, w_r if reduction else w_n, train=train)
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(out)


@dataclasses.dataclass
class Genotype:
    normal: list[tuple[str, int]]
    reduce: list[tuple[str, int]]


def steps_from_edges(num_edges_: int) -> int:
    """Invert num_edges: E = steps*(steps+3)/2."""
    steps = int((np.sqrt(9 + 8 * num_edges_) - 3) / 2)
    if num_edges(steps) != num_edges_:
        raise ValueError(f"{num_edges_} is not a valid DARTS edge count")
    return steps


def decode_genotype(alphas_normal: np.ndarray, alphas_reduce: np.ndarray,
                    steps: int | None = None) -> Genotype:
    """Argmax decode (genotypes.py / FedNASAggregator.record_model_global_
    architecture:173): per node keep the 2 strongest non-'none' incoming
    edges. ``steps`` is inferred from the alpha row count by default."""
    if steps is None:
        steps = steps_from_edges(len(np.asarray(alphas_normal)))

    def _decode(alphas):
        gene = []
        offset = 0
        none_idx = PRIMITIVES.index("none")
        w = np.asarray(jax.nn.softmax(jnp.asarray(alphas), axis=-1))
        for i in range(steps):
            n_in = 2 + i
            edges = w[offset : offset + n_in].copy()
            edges[:, none_idx] = -1
            strength = edges.max(axis=1)
            top2 = np.argsort(-strength)[:2]
            for j in sorted(top2):
                gene.append((PRIMITIVES[int(np.argmax(edges[j]))], int(j)))
            offset += n_in
        return gene

    return Genotype(_decode(alphas_normal), _decode(alphas_reduce))
