"""Model export for mobile / serving targets (the MNN-conversion role).

Reference: fedml_api/model/mobile/model_transfer.py:19,51 — torch<->MNN
weight transfer via aligned flat layer lists, so a phone-side MNN runtime
and the server-side torch model exchange parameters during federated
rounds.

TPU-native equivalents:

1. :func:`export_stablehlo` / :func:`load_stablehlo` — serialize a jitted
   forward pass as portable StableHLO (``jax.export``). StableHLO is the
   deployment interchange format of the XLA ecosystem: the artifact runs
   under any StableHLO-consuming runtime (IREE and friends on mobile,
   TF/LiteRT converters, server runtimes) without Python or Flax.
2. :func:`params_to_flat_list` / :func:`flat_list_to_params` — the aligned
   flat-layer-list contract itself (model_transfer.py's mnn_pytorch /
   pytorch_mnn round-trip): a deterministic leaf ordering so an on-device
   runtime holding "a list of weight arrays" can exchange parameters with
   the server model, both directions, loss-free.
3. :func:`params_to_nested_lists` / :func:`nested_lists_to_params` — the
   reference's ``is_mobile`` WIRE format
   (fedml_api/distributed/fedavg/utils.py:7-16
   ``transform_tensor_to_list`` / ``transform_list_to_tensor``): a
   JSON-serializable dict keyed by parameter name whose values are the
   ``.tolist()`` nesting of each array. A mobile client speaking the
   reference's JSON can exchange models with this server unchanged.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import numpy as np

import jax

Pytree = Any


# -- aligned flat-list weight transfer (model_transfer.py role) --------------


def params_to_flat_list(params: Pytree) -> list[np.ndarray]:
    """Deterministic (path-sorted) list of weight arrays — the mobile
    runtime's model format."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    leaves.sort(key=lambda kv: jax.tree_util.keystr(kv[0]))
    return [np.asarray(v) for _, v in leaves]


def flat_list_to_params(flat: list[np.ndarray], template: Pytree) -> Pytree:
    """Inverse of :func:`params_to_flat_list` given any same-structure
    template (shape-checked, like the reference's aligned-layer assert)."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    order = sorted(range(len(paths)), key=lambda i: jax.tree_util.keystr(paths[i][0]))
    if len(flat) != len(paths):
        raise ValueError(
            f"model format is not aligned: {len(flat)} arrays vs "
            f"{len(paths)} leaves"
        )
    leaves = [None] * len(paths)
    for slot, arr in zip(order, flat):
        want = np.shape(paths[slot][1])
        arr = np.asarray(arr)
        if arr.shape != want:
            arr = arr.reshape(want)  # reference reshapes on mismatch too
        leaves[slot] = arr
    return jax.tree_util.tree_unflatten(treedef, leaves)


# -- reference is_mobile wire format (fedavg/utils.py:7-16) ------------------


def _path_key(path) -> str:
    """'/'-joined tree path — the parameter-name key of the wire dict."""
    return "/".join(
        str(getattr(e, "key", getattr(e, "idx", getattr(e, "name", e))))
        for e in path
    )


def params_to_nested_lists(params: Pytree) -> dict[str, list]:
    """Reference ``transform_tensor_to_list``: dict keyed by parameter name,
    each value the ``.tolist()`` nesting of the array (nesting depth ==
    array ndim). Keys are emitted in the same deterministic path-sorted
    order as :func:`params_to_flat_list`, so ``json.dumps`` round-trips
    with ordering preserved."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    leaves.sort(key=lambda kv: jax.tree_util.keystr(kv[0]))
    return {_path_key(p): np.asarray(v).tolist() for p, v in leaves}


def nested_lists_to_params(obj: dict[str, list], template: Pytree) -> Pytree:
    """Reference ``transform_list_to_tensor``: rebuild parameters from the
    nested-list wire dict. Values are cast to float32 exactly as the
    reference's ``torch.from_numpy(np.asarray(v)).float()`` does, then to
    the template leaf's dtype."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, tmpl in paths:
        key = _path_key(path)
        if key not in obj:
            raise ValueError(f"wire dict is missing parameter {key!r}")
        arr = np.asarray(obj[key], dtype=np.float32)
        want = np.shape(tmpl)
        if arr.shape != want:
            raise ValueError(
                f"parameter {key!r} has shape {arr.shape}, expected {want}"
            )
        leaves.append(arr.astype(np.asarray(tmpl).dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


# -- StableHLO export (deployment artifact) ----------------------------------


def export_stablehlo(apply_fn, example_args: tuple, path: str | Path) -> bytes:
    """Serialize ``jit(apply_fn)(*example_args)`` as a portable StableHLO
    artifact; also writes it to ``path``. Returns the serialized bytes."""
    from jax import export as jexport

    exported = jexport.export(jax.jit(apply_fn))(*example_args)
    blob = exported.serialize()
    Path(path).write_bytes(blob)
    return blob


def load_stablehlo(path: str | Path):
    """Deserialize a StableHLO artifact; ``.call(*args)`` runs it."""
    from jax import export as jexport

    return jexport.deserialize(Path(path).read_bytes())
