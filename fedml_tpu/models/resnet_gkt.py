"""Split ResNets for Group Knowledge Transfer.

Reference: fedml_api/model/cv/resnet56_gkt/ — ``resnet8_56`` client (stem +
one small stage + its own classifier head, also exposing the feature maps)
and ``resnet56_server`` (takes the client's feature maps, runs the remaining
stages + classifier). The client uploads (features, logits, labels); the
server trains on features with CE + bidirectional KL distillation
(fedgkt/utils.py:75-90 KL_Loss).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.resnet import BasicBlock, _norm


class ResNetGKTClient(nn.Module):
    """Small edge model (resnet8_56 analogue): stem + n blocks @16ch; returns
    (features [B,H,W,16], logits [B,C])."""

    num_classes: int = 10
    blocks: int = 1
    norm: str = "bn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(self.norm, train)
        h = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x.astype(jnp.float32))
        h = nn.relu(norm()(h))
        for _ in range(self.blocks):
            h = BasicBlock(16, 1, self.norm)(h, train=train)
        features = h
        pooled = jnp.mean(h, axis=(1, 2))
        logits = nn.Dense(self.num_classes)(pooled)
        return features, logits


class ResNetGKTServer(nn.Module):
    """Large server model (resnet56_server analogue): consumes client feature
    maps, runs stages 2-3 and the classifier."""

    num_classes: int = 10
    blocks_per_stage: int = 9
    norm: str = "bn"

    @nn.compact
    def __call__(self, features, train: bool = False):
        h = features.astype(jnp.float32)
        for stage, filters in enumerate([32, 64]):
            for block in range(self.blocks_per_stage):
                stride = 2 if block == 0 else 1
                h = BasicBlock(filters, stride, self.norm)(h, train=train)
        h = jnp.mean(h, axis=(1, 2))
        return nn.Dense(self.num_classes)(h)
