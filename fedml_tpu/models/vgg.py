"""VGG (reference: fedml_api/model/cv/vgg.py:13 — VGG-11/13/16/19 with
optional BN, CIFAR-sized head)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

_CFG = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    depth: int = 16
    num_classes: int = 10
    batch_norm: bool = True
    dtype: jnp.dtype = jnp.float32  # compute dtype (bf16 on TPU); params f32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(jnp.float32)
        for v in _CFG[self.depth]:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding="SAME",
                            use_bias=not self.batch_norm, dtype=self.dtype)(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train,
                                     dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x.astype(jnp.float32))
