"""ResNet family.

- CIFAR-style ResNet-56/110 with BatchNorm (reference: fedml_api/model/cv/
  resnet.py:202 ``resnet56``, :225 ``resnet110`` — 3 stages of (depth-2)/6
  BasicBlocks, 16/32/64 channels, option-A shortcuts).
- ResNet-18 with GroupNorm for fed_cifar100 (reference: cv/resnet_gn.py:183 +
  custom group_normalization.py — the Adaptive-FedOpt paper configuration;
  GN avoids federating BN statistics entirely).

TPU notes: NHWC layout, channels padded by XLA onto the MXU; BatchNorm state
(``batch_stats`` collection) is federated by averaging alongside weights, the
reference's deliberate policy (FedAVGAggregator.py:74-81).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp


def _norm(kind: str, train: bool, dtype=jnp.float32):
    if kind == "bn":
        return partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=dtype)
    if kind == "gn":
        return partial(nn.GroupNorm, num_groups=2, dtype=dtype)
    raise ValueError(f"unknown norm {kind!r}")


class BasicBlock(nn.Module):
    filters: int
    stride: int = 1
    norm: str = "bn"
    dtype: jnp.dtype = jnp.float32  # compute dtype (bf16 on TPU); params f32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(self.norm, train, self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        y = conv(self.filters, (3, 3), strides=self.stride, padding="SAME")(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), padding="SAME")(y)
        y = norm()(y)
        if x.shape[-1] != self.filters or self.stride != 1:
            x = conv(self.filters, (1, 1), strides=self.stride)(x)
            x = norm()(x)
        return nn.relu(x + y)


class CifarResNet(nn.Module):
    """3-stage CIFAR ResNet; depth = 6n+2 (56 -> n=9, 110 -> n=18)."""

    depth: int = 56
    num_classes: int = 10
    norm: str = "bn"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        n = (self.depth - 2) // 6
        norm = _norm(self.norm, train, self.dtype)
        x = x.astype(jnp.float32)
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(norm()(x))
        for stage, filters in enumerate([16, 32, 64]):
            for block in range(n):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(filters, stride, self.norm, self.dtype)(x, train=train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class ResNet18(nn.Module):
    """Standard 4-stage ResNet-18; ``norm='gn'`` is the fed_cifar100 config
    (resnet_gn.py:183). ``small_input`` uses a 3x3 stem without max-pool for
    CIFAR-sized images."""

    num_classes: int = 100
    norm: str = "gn"
    small_input: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(self.norm, train, self.dtype)
        x = x.astype(jnp.float32)
        if self.small_input:
            x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        else:
            x = nn.Conv(64, (7, 7), strides=2, padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(norm()(x))
        if not self.small_input:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, filters in enumerate([64, 128, 256, 512]):
            for block in range(2):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(filters, stride, self.norm, self.dtype)(x, train=train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def resnet56(class_num: int = 10, norm: str = "bn",
             dtype: jnp.dtype = jnp.float32) -> CifarResNet:
    return CifarResNet(depth=56, num_classes=class_num, norm=norm, dtype=dtype)


def resnet110(class_num: int = 10, norm: str = "bn",
              dtype: jnp.dtype = jnp.float32) -> CifarResNet:
    return CifarResNet(depth=110, num_classes=class_num, norm=norm, dtype=dtype)


def resnet18_gn(class_num: int = 100,
                dtype: jnp.dtype = jnp.float32) -> ResNet18:
    return ResNet18(num_classes=class_num, norm="gn", dtype=dtype)
