from fedml_tpu.models.cnn import CNNDropOut, CNNOriginalFedAvg, LeNet
from fedml_tpu.models.gan import Discriminator, Generator
from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.models.mobilenet import MobileNet, MobileNetV3
from fedml_tpu.models.registry import create_model, task_for_dataset
from fedml_tpu.models.resnet import (
    CifarResNet,
    ResNet18,
    resnet18_gn,
    resnet56,
    resnet110,
)
from fedml_tpu.models.rnn import RNNOriginalFedAvg, RNNStackOverflow
from fedml_tpu.models.vgg import VGG

__all__ = [
    "CNNDropOut",
    "CNNOriginalFedAvg",
    "LeNet",
    "CifarResNet",
    "Discriminator",
    "Generator",
    "LogisticRegression",
    "MobileNet",
    "MobileNetV3",
    "ResNet18",
    "RNNOriginalFedAvg",
    "RNNStackOverflow",
    "VGG",
    "create_model",
    "resnet18_gn",
    "resnet56",
    "resnet110",
    "task_for_dataset",
]
