"""EfficientNet b0–b8 (reference: fedml_api/model/cv/efficientnet.py:138 +
efficientnet_utils.py — the torch port of the official TF implementation).

TPU-first Flax rewrite: MBConv inverted-residual blocks with squeeze-excite,
SiLU (swish) activations, GroupNorm instead of BatchNorm (federated clients
averaging BN statistics is the reference's known pain point — SURVEY §7), and
NHWC layouts so every conv is an MXU matmul. Compound scaling follows the
paper's (width, depth, resolution, dropout) coefficients — the same table the
reference's ``efficientnet_params`` carries (efficientnet_utils.py).

Stochastic depth (drop-connect) is applied per block when ``train=True``.
"""

from __future__ import annotations

import math
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

# (width_coefficient, depth_coefficient, resolution, dropout_rate) — reference
# efficientnet_utils.efficientnet_params
SCALING = {
    "efficientnet-b0": (1.0, 1.0, 224, 0.2),
    "efficientnet-b1": (1.0, 1.1, 240, 0.2),
    "efficientnet-b2": (1.1, 1.2, 260, 0.3),
    "efficientnet-b3": (1.2, 1.4, 300, 0.3),
    "efficientnet-b4": (1.4, 1.8, 380, 0.4),
    "efficientnet-b5": (1.6, 2.2, 456, 0.4),
    "efficientnet-b6": (1.8, 2.6, 528, 0.5),
    "efficientnet-b7": (2.0, 3.1, 600, 0.5),
    "efficientnet-b8": (2.2, 3.6, 672, 0.5),
}

# (expand_ratio, channels, repeats, stride, kernel) — the 7-stage b0 backbone
BASE_BLOCKS = (
    (1, 16, 1, 1, 3),
    (6, 24, 2, 2, 3),
    (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3),
    (6, 112, 3, 1, 5),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)


def round_filters(filters: int, width: float, divisor: int = 8) -> int:
    filters *= width
    new = max(divisor, int(filters + divisor / 2) // divisor * divisor)
    if new < 0.9 * filters:
        new += divisor
    return int(new)


def round_repeats(repeats: int, depth: float) -> int:
    return int(math.ceil(depth * repeats))


def _gn_groups(c: int, target: int = 8) -> int:
    g = min(target, c)
    while c % g:
        g -= 1
    return g


class SqueezeExcite(nn.Module):
    features: int
    se_ratio: float = 0.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        squeezed = max(1, int(self.features * self.se_ratio))
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = nn.Conv(squeezed, (1, 1), dtype=self.dtype)(s)
        s = nn.silu(s)
        s = nn.Conv(x.shape[-1], (1, 1), dtype=self.dtype)(s)
        return x * nn.sigmoid(s)


class MBConv(nn.Module):
    out_features: int
    expand_ratio: int
    stride: int
    kernel: int
    drop_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        inp = x.shape[-1]
        h = x
        if self.expand_ratio != 1:
            h = nn.Conv(inp * self.expand_ratio, (1, 1), use_bias=False,
                        dtype=self.dtype)(h)
            h = nn.GroupNorm(num_groups=_gn_groups(inp * self.expand_ratio),
                             dtype=self.dtype)(h)
            h = nn.silu(h)
        # depthwise
        c = h.shape[-1]
        h = nn.Conv(c, (self.kernel, self.kernel), strides=self.stride,
                    padding="SAME", feature_group_count=c, use_bias=False,
                    dtype=self.dtype)(h)
        h = nn.GroupNorm(num_groups=_gn_groups(c), dtype=self.dtype)(h)
        h = nn.silu(h)
        h = SqueezeExcite(inp, dtype=self.dtype)(h)
        h = nn.Conv(self.out_features, (1, 1), use_bias=False, dtype=self.dtype)(h)
        h = nn.GroupNorm(num_groups=_gn_groups(self.out_features), dtype=self.dtype)(h)
        if self.stride == 1 and inp == self.out_features:
            if self.drop_rate > 0.0 and train:
                # stochastic depth on the residual branch
                keep = 1.0 - self.drop_rate
                rng = self.make_rng("dropout")
                mask = jax.random.bernoulli(rng, keep, (h.shape[0], 1, 1, 1))
                h = jnp.where(mask, h / keep, 0.0)
            h = h + x
        return h


class EfficientNet(nn.Module):
    num_classes: int = 10
    width: float = 1.0
    depth: float = 1.0
    dropout_rate: float = 0.2
    drop_connect_rate: float = 0.2
    stem_features: int = 32
    dtype: jnp.dtype = jnp.float32  # compute dtype (bf16 on TPU); params f32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Conv(round_filters(self.stem_features, self.width), (3, 3),
                    strides=2, padding="SAME", use_bias=False, dtype=self.dtype)(x)
        h = nn.GroupNorm(num_groups=_gn_groups(h.shape[-1]), dtype=self.dtype)(h)
        h = nn.silu(h)

        total_blocks = sum(round_repeats(r, self.depth) for _, _, r, _, _ in BASE_BLOCKS)
        block_idx = 0
        for expand, feats, repeats, stride, kernel in BASE_BLOCKS:
            feats = round_filters(feats, self.width)
            for i in range(round_repeats(repeats, self.depth)):
                h = MBConv(
                    out_features=feats,
                    expand_ratio=expand,
                    stride=stride if i == 0 else 1,
                    kernel=kernel,
                    drop_rate=self.drop_connect_rate * block_idx / total_blocks,
                    dtype=self.dtype,
                )(h, train=train)
                block_idx += 1

        h = nn.Conv(round_filters(1280, self.width), (1, 1), use_bias=False,
                    dtype=self.dtype)(h)
        h = nn.GroupNorm(num_groups=_gn_groups(h.shape[-1]), dtype=self.dtype)(h)
        h = nn.silu(h)
        h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))
        if self.dropout_rate:
            h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        return nn.Dense(self.num_classes)(h)


def efficientnet(name: str = "efficientnet-b0", num_classes: int = 10,
                 dtype: jnp.dtype = jnp.float32) -> EfficientNet:
    """Factory matching the reference's ``EfficientNet.from_name`` dispatch."""
    width, depth, _res, dropout = SCALING[name]
    return EfficientNet(num_classes=num_classes, width=width, depth=depth,
                        dtype=dtype,
                        dropout_rate=dropout)
