"""Recurrent language models (reference: fedml_api/model/nlp/rnn.py).

- ``RNNOriginalFedAvg`` (rnn.py:4): embedding(8) → 2×LSTM(256) → dense(V) —
  Shakespeare next-char (McMahan 2017), 90-vocab.
- ``RNNStackOverflow`` (rnn.py:39): embedding(96) → LSTM(670) → dense(96) →
  dense(V) — StackOverflow next-word, 10k vocab + 4 special tokens.

Inputs are int token ids [B, T]; outputs logits [B, T, V] (the trainer's LM
loss applies the per-token mask). The recurrence is ``nn.RNN`` over an
``OptimizedLSTMCell`` — XLA unrolls/scans it on-chip; the sequence axis stays
static for jit.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class RNNOriginalFedAvg(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        return nn.Dense(self.vocab_size)(h)


class RNNStackOverflow(nn.Module):
    """1 LSTM + 2 FC (rnn.py:39). vocab = 10000 words + pad/bos/eos/oov."""

    vocab_size: int = 10004
    embedding_dim: int = 96
    hidden_size: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        h = nn.Dense(self.embedding_dim)(h)
        return nn.Dense(self.vocab_size)(h)
