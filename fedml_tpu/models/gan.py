"""MNIST GAN (reference: fedml_api/model/cv/mnist_gan.py:6 Generator /
Discriminator — the fedgan workload, which federates a dict of the two
networks and aggregates them with a nested weighted average,
FedGANAggregator.aggregate:58-88)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class Generator(nn.Module):
    latent_dim: int = 100
    img_shape: tuple[int, int, int] = (28, 28, 1)

    @nn.compact
    def __call__(self, z, train: bool = False):
        h = z.astype(jnp.float32)
        for width, norm in [(128, False), (256, True), (512, True), (1024, True)]:
            h = nn.Dense(width)(h)
            if norm:
                h = nn.BatchNorm(use_running_average=not train, momentum=0.8)(h)
            h = nn.leaky_relu(h, 0.2)
        import numpy as np

        h = nn.tanh(nn.Dense(int(np.prod(self.img_shape)))(h))
        return h.reshape((h.shape[0],) + self.img_shape)


class Discriminator(nn.Module):
    img_shape: tuple[int, int, int] = (28, 28, 1)

    @nn.compact
    def __call__(self, img, train: bool = False):
        h = img.reshape((img.shape[0], -1)).astype(jnp.float32)
        h = nn.leaky_relu(nn.Dense(512)(h), 0.2)
        h = nn.leaky_relu(nn.Dense(256)(h), 0.2)
        return nn.Dense(1)(h)  # logit; loss applies sigmoid
