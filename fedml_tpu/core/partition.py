"""Non-IID client partitioners.

Capability parity with the reference's partitioning stack:
- latent-Dirichlet partition with min-size retry loop
  (reference: fedml_core/non_iid_partition/noniid_partition.py:6-93)
- ``homo`` / ``hetero`` / ``hetero-fix`` methods of the CV loaders
  (reference: fedml_api/data_preprocessing/cifar10/data_loader.py:113-161)
- power-law client sizes used by LEAF MNIST (1000-client benchmark config)
- per-client class histograms (noniid_partition.py:94 ``record_data_stats``)

All functions are host-side numpy: partitioning happens once at startup, the
result is a list of index arrays that the data layer turns into stacked,
padded per-client device arrays.
"""

from __future__ import annotations

import logging

import numpy as np


def homo_partition(n_samples: int, n_clients: int, seed: int = 0) -> dict[int, np.ndarray]:
    """Uniform random split (reference partition_method='homo',
    cifar10/data_loader.py:113-117)."""
    rng = np.random.RandomState(seed)
    idxs = rng.permutation(n_samples)
    return {i: np.sort(part) for i, part in enumerate(np.array_split(idxs, n_clients))}


def dirichlet_partition(
    labels: np.ndarray,
    n_clients: int,
    alpha: float,
    min_size: int = 10,
    seed: int = 0,
    task: str = "classification",
) -> dict[int, np.ndarray]:
    """Latent-Dirichlet non-IID partition.

    For each class, sample proportions ~ Dir(alpha) over clients and split that
    class's samples accordingly; retry until every client has >= ``min_size``
    samples (reference noniid_partition.py:44-69 and
    cifar10/data_loader.py:118-149 — both implement this loop). ``alpha`` -> inf
    approaches a uniform split; small ``alpha`` concentrates classes on few
    clients.

    ``task='segmentation'`` treats ``labels`` as a list of per-sample label
    *sets* (multi-label; reference noniid_partition.py:29-43) and partitions by
    the first category of each sample.
    """
    rng = np.random.RandomState(seed)
    if task == "segmentation":
        flat = np.asarray([np.min(cats) for cats in labels])
    else:
        flat = np.asarray(labels).reshape(-1)
    n_samples = flat.shape[0]
    classes = np.unique(flat)

    size_min = -1
    tries = 0
    while size_min < min(min_size, max(1, n_samples // (n_clients * 2))):
        idx_batch: list[list[int]] = [[] for _ in range(n_clients)]
        for c in classes:
            idx_c = np.where(flat == c)[0]
            rng.shuffle(idx_c)
            proportions = rng.dirichlet(np.repeat(alpha, n_clients))
            # Balance heuristic from the reference (noniid_partition.py:76-93):
            # zero out proportions for clients already at average capacity.
            proportions = np.array(
                [p * (len(b) < n_samples / n_clients) for p, b in zip(proportions, idx_batch)]
            )
            s = proportions.sum()
            proportions = proportions / s if s > 0 else np.ones(n_clients) / n_clients
            cuts = (np.cumsum(proportions) * len(idx_c)).astype(int)[:-1]
            for i, part in enumerate(np.split(idx_c, cuts)):
                idx_batch[i].extend(part.tolist())
        size_min = min(len(b) for b in idx_batch)
        tries += 1
        if tries > 100:  # degenerate config (tiny dataset): accept best effort
            logging.warning("dirichlet_partition: min-size retry cap hit (min=%d)", size_min)
            break

    out = {}
    for i in range(n_clients):
        rng.shuffle(idx_batch[i])
        out[i] = np.sort(np.asarray(idx_batch[i], dtype=np.int64))
    return out


def powerlaw_partition(
    labels: np.ndarray, n_clients: int, alpha: float = 3.0, min_size: int = 2, seed: int = 0
) -> dict[int, np.ndarray]:
    """Power-law client sizes (LEAF MNIST-style: 1000 clients whose sample
    counts follow a power law; reference consumes this pre-partitioned from
    LEAF JSON — we generate it for in-memory datasets)."""
    rng = np.random.RandomState(seed)
    n_samples = len(labels)
    raw = rng.pareto(alpha, n_clients) + 1.0
    sizes = np.maximum((raw / raw.sum() * (n_samples - min_size * n_clients)).astype(int) + min_size, min_size)
    # fix rounding so sizes sum exactly
    diff = n_samples - sizes.sum()
    sizes[np.argmax(sizes)] += diff
    idxs = rng.permutation(n_samples)
    out, start = {}, 0
    for i in range(n_clients):
        out[i] = np.sort(idxs[start : start + sizes[i]])
        start += sizes[i]
    return out


def fixed_partition(distribution: dict[int, np.ndarray]) -> dict[int, np.ndarray]:
    """'hetero-fix': partition loaded from a saved distribution file
    (reference cifar10/data_loader.py:150-158)."""
    return {int(k): np.asarray(v, dtype=np.int64) for k, v in distribution.items()}


def read_net_dataidx_map(path) -> dict[int, np.ndarray]:
    """Read a saved client→sample-index map for ``hetero-fix``.

    Accepts both formats a reference user may have on disk:
    - the reference's printed-dict ``net_dataidx_map.txt``
      (cifar10/data_loader.py:31-43 ``read_net_dataidx_map``): ``N: [`` opens
      client N, subsequent comma-separated integer lines are its indices,
      ``]``/``{``/``}`` lines are structure;
    - plain JSON ``{"client": [indices...]}``.
    """
    import json
    from pathlib import Path

    text = Path(path).read_text()
    try:
        return fixed_partition(json.loads(text))
    except json.JSONDecodeError:
        pass  # not JSON — the reference's printed-dict layout
    mapping: dict[int, list[int]] = {}
    key = None
    for line in text.splitlines():
        line = line.strip()
        if not line or line[0] in "{}]":
            continue
        head, _, tail = line.partition(":")
        if tail.strip() == "[":
            key = int(head)
            mapping[key] = []
        else:
            if key is None:
                raise ValueError(f"malformed dataidx map line: {line!r}")
            mapping[key].extend(
                int(tok) for tok in line.replace("]", "").split(",") if tok.strip()
            )
    if not mapping:
        raise ValueError(f"no client index lists found in {path}")
    return fixed_partition(mapping)


def write_net_dataidx_map(path, net_dataidx_map: dict[int, np.ndarray]) -> None:
    """Write a partition in the reference's ``net_dataidx_map.txt`` layout so
    the file round-trips through both this reader and the reference's."""
    from pathlib import Path

    lines = ["{"]
    for client in sorted(net_dataidx_map):
        lines.append(f"{int(client)}: [")
        idxs = net_dataidx_map[client]
        if len(idxs):
            lines.append(", ".join(str(int(i)) for i in idxs))
        # zero-index clients get NO indices line: the reference reader
        # (cifar10/data_loader.py:38-42) int()s every token of every
        # non-structural line, so an empty line would crash it; both readers
        # parse "N: [" directly followed by "]" as an empty client
        lines.append("]")
    lines.append("}")
    Path(path).write_text("\n".join(lines) + "\n")


def partition(
    method: str,
    labels: np.ndarray,
    n_clients: int,
    alpha: float = 0.5,
    seed: int = 0,
    dataidx_map_path=None,
) -> dict[int, np.ndarray]:
    """Dispatch by reference partition_method name. ``hetero-fix`` loads the
    saved distribution at ``dataidx_map_path`` (reference hard-codes
    ``./data_preprocessing/non-iid-distribution/<DS>/net_dataidx_map.txt``;
    here the path is explicit)."""
    if method == "homo":
        return homo_partition(len(labels), n_clients, seed)
    if method in ("hetero", "dirichlet", "noniid"):
        return dirichlet_partition(labels, n_clients, alpha, seed=seed)
    if method in ("power-law", "power_law"):
        return powerlaw_partition(labels, n_clients, seed=seed)
    if method == "hetero-fix":
        if dataidx_map_path is None:
            raise ValueError(
                "partition_method='hetero-fix' needs dataidx_map_path "
                "(--dataidx_map_path, a saved net_dataidx_map.txt)"
            )
        mapping = read_net_dataidx_map(dataidx_map_path)
        if set(mapping) != set(range(n_clients)):
            raise ValueError(
                f"hetero-fix map at {dataidx_map_path} has clients "
                f"{sorted(mapping)} but client_num_in_total={n_clients} "
                f"needs exactly 0..{n_clients - 1}"
            )
        n = len(labels)
        for client, idxs in mapping.items():
            if len(idxs) and (idxs.min() < 0 or idxs.max() >= n):
                raise ValueError(
                    f"hetero-fix map at {dataidx_map_path}: client {client} "
                    f"indexes outside the {n}-sample dataset"
                )
        return mapping
    raise ValueError(f"unknown partition method: {method!r}")


def record_data_stats(labels: np.ndarray, net_dataidx_map: dict[int, np.ndarray], n_classes: int | None = None):
    """Per-client class histogram (reference noniid_partition.py:94-102)."""
    labels = np.asarray(labels).reshape(-1)
    if n_classes is None:
        n_classes = int(labels.max()) + 1
    stats = {}
    for client, idxs in net_dataidx_map.items():
        hist = np.bincount(labels[idxs], minlength=n_classes)
        stats[client] = {int(c): int(n) for c, n in enumerate(hist) if n > 0}
    logging.debug("client class histograms: %s", stats)
    return stats
