"""Pytree parameter utilities.

TPU-native analogue of the reference's state_dict manipulation helpers
(reference: fedml_core/robustness/robust_aggregation.py:4-29 `vectorize_weight`,
fedml_api/distributed/fedavg/utils.py:7-16 tensor<->list transforms). Model
parameters here are JAX pytrees; flattening to a single vector is used by
robust aggregation (median / norm clipping) and secure aggregation, and the
flat (f32 array + treedef) pair is the wire format of the comm layer — never
pickled objects.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


def tree_vectorize(tree: Pytree, exclude: Callable[[str], bool] | None = None) -> jnp.ndarray:
    """Flatten a pytree of arrays into one 1-D vector.

    ``exclude`` receives the joined key-path string (e.g. ``"BatchNorm_0/mean"``)
    and returns True to skip that leaf — mirroring the reference's policy of
    excluding batch-norm statistics from robust statistics
    (robust_aggregation.py:28-29).
    """
    leaves = tree_leaves_with_paths(tree)
    vecs = [jnp.ravel(v) for k, v in leaves if not (exclude and exclude(k))]
    if not vecs:
        return jnp.zeros((0,), dtype=jnp.float32)
    return jnp.concatenate(vecs)


def tree_unvectorize(vec: jnp.ndarray, like: Pytree) -> Pytree:
    """Inverse of :func:`tree_vectorize` (with no exclusions)."""
    leaves, treedef = jax.tree_util.tree_flatten(like)
    out = []
    i = 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        out.append(jnp.reshape(vec[i : i + n], leaf.shape).astype(leaf.dtype))
        i += n
    return jax.tree_util.tree_unflatten(treedef, out)


def tree_leaves_with_paths(tree: Pytree) -> list[tuple[str, jnp.ndarray]]:
    """List of (path-string, leaf) pairs in canonical traversal order."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in flat:
        key = "/".join(_path_entry_str(p) for p in path)
        out.append((key, leaf))
    return out


def _path_entry_str(entry) -> str:
    if hasattr(entry, "key"):
        return str(entry.key)
    if hasattr(entry, "idx"):
        return str(entry.idx)
    if hasattr(entry, "name"):
        return str(entry.name)
    return str(entry)


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    """a - b, leafwise."""
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * s, tree)


def tree_dot(a: Pytree, b: Pytree) -> jnp.ndarray:
    parts = jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b)
    return jax.tree_util.tree_reduce(jnp.add, parts, jnp.float32(0.0))


def tree_norm(tree: Pytree) -> jnp.ndarray:
    """Global L2 norm over all leaves."""
    return jnp.sqrt(tree_dot(tree, tree))


def tree_weighted_mean(stacked: Pytree, weights: jnp.ndarray) -> Pytree:
    """Weighted mean over a leading axis present on every leaf.

    ``stacked`` has leaves of shape [C, ...]; ``weights`` is [C] (need not be
    normalized — e.g. raw per-client sample counts, matching the reference's
    sample-count weighting in FedAVGAggregator.py:59-88). Weight normalization
    happens in f32 regardless of leaf dtype.
    """
    w = weights.astype(jnp.float32)
    w = w / jnp.maximum(jnp.sum(w), 1e-12)

    def _avg(leaf):
        wb = w.reshape((-1,) + (1,) * (leaf.ndim - 1))
        return jnp.sum(leaf.astype(jnp.float32) * wb, axis=0).astype(leaf.dtype)

    return jax.tree.map(_avg, stacked)


def tree_stack(trees: Sequence[Pytree]) -> Pytree:
    """Stack a list of identically-structured pytrees along a new axis 0."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(stacked: Pytree, n: int) -> list[Pytree]:
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n)]


def tree_cast(tree: Pytree, dtype) -> Pytree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_size(tree: Pytree) -> int:
    """Total number of scalar parameters."""
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))
