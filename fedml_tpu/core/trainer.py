"""Client trainer: the TPU-native replacement for the reference ModelTrainer ABC.

Reference contract (fedml_core/trainer/model_trainer.py:4-37): get/set params,
train(local data, device, args), test. Here the contract is *functional*: a
:class:`ClientTrainer` bundles a Flax module with a task-specific loss/metric
pair, and :func:`make_local_train` compiles "K local epochs of minibatch SGD"
into a single ``lax.scan`` suitable for ``vmap`` over a stacked client axis —
the per-client Python loop of the reference (standalone/fedavg/
my_model_trainer_classification.py:12-60) becomes one XLA program.

Data convention: a *batch* is ``{"x": [B, ...], "y": [B, ...], "mask": [B]}``
(sequence tasks carry a per-token mask ``[B, T]``). Padding examples have
mask 0 and contribute nothing to losses, gradients, or metrics — this is how
ragged per-client datasets live inside fixed-shape jitted code.

Model variables: the full Flax variables dict ``{"params": ..., possibly
"batch_stats": ...}`` is the unit of federation — BN running statistics are
averaged like ordinary weights, matching the reference's deliberate policy
(FedAVGAggregator.py:74-81).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core import scan as scanlib

Pytree = Any
Batch = dict[str, jnp.ndarray]

# ---------------------------------------------------------------------------
# Task losses / metrics
# ---------------------------------------------------------------------------


def _masked_mean(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    total = jnp.sum(values * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count


def classification_loss(logits: jnp.ndarray, batch: Batch) -> jnp.ndarray:
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
    return _masked_mean(ce, batch["mask"])


def classification_metrics(logits: jnp.ndarray, batch: Batch) -> dict[str, jnp.ndarray]:
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
    correct = (jnp.argmax(logits, -1) == batch["y"]).astype(jnp.float32)
    m = batch["mask"]
    return {
        "test_correct": jnp.sum(correct * m),
        "test_loss": jnp.sum(ce * m),
        "test_total": jnp.sum(m),
    }


def lm_loss(logits: jnp.ndarray, batch: Batch) -> jnp.ndarray:
    """Next-token loss for [B, T, V] logits with per-token mask [B, T]
    (reference my_model_trainer_nwp.py — Shakespeare / StackOverflow NWP)."""
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
    return _masked_mean(ce, batch["mask"])


def lm_metrics(logits: jnp.ndarray, batch: Batch) -> dict[str, jnp.ndarray]:
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
    correct = (jnp.argmax(logits, -1) == batch["y"]).astype(jnp.float32)
    m = batch["mask"]
    return {
        "test_correct": jnp.sum(correct * m),
        "test_loss": jnp.sum(ce * m),
        "test_total": jnp.sum(m),
    }


def tag_loss(logits: jnp.ndarray, batch: Batch) -> jnp.ndarray:
    """Multi-label (tag prediction, stackoverflow_lr): sigmoid BCE against a
    multi-hot target (reference my_model_trainer_tag_prediction.py)."""
    bce = optax.sigmoid_binary_cross_entropy(logits, batch["y"]).sum(-1)
    return _masked_mean(bce, batch["mask"])


def tag_metrics(logits: jnp.ndarray, batch: Batch) -> dict[str, jnp.ndarray]:
    bce = optax.sigmoid_binary_cross_entropy(logits, batch["y"]).sum(-1)
    pred = (logits > 0.0).astype(jnp.float32)
    y = batch["y"]
    m = batch["mask"][:, None]
    tp = jnp.sum(pred * y * m)
    return {
        "test_correct": tp,  # reference reports precision-style counts
        "test_loss": jnp.sum(bce * batch["mask"]),
        "test_total": jnp.maximum(jnp.sum(pred * m), 1.0),
        "test_precision": tp / jnp.maximum(jnp.sum(pred * m), 1.0),
        "test_recall": tp / jnp.maximum(jnp.sum(y * m), 1.0),
    }


def _pixel_mask(batch: Batch, ce: jnp.ndarray) -> jnp.ndarray:
    """Broadcast an example-level [B] (or pixel-level [B, H, W]) mask to the
    per-pixel CE shape."""
    m = batch["mask"]
    while m.ndim < ce.ndim:
        m = m[..., None]
    return jnp.broadcast_to(m, ce.shape)


def _masked_seg_ce(logits: jnp.ndarray, batch: Batch):
    """Shared validity contract for the segmentation loss AND metrics: labels
    outside [0, C) (e.g. the 255 ignore label, reference fedseg/utils.py
    Evaluator.add_batch's (gt >= 0) & (gt < num_class)) leave the mask, and CE
    runs on clipped labels — out-of-range labels yield inf, and inf * 0-mask
    is NaN. Returns (ce, mask, clipped labels)."""
    num_classes = logits.shape[-1]
    y = batch["y"]
    valid = ((y >= 0) & (y < num_classes)).astype(jnp.float32)
    y_safe = jnp.clip(y, 0, num_classes - 1)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, y_safe)
    m = _pixel_mask(batch, ce) * valid
    return ce, m, y_safe


def segmentation_loss(logits: jnp.ndarray, batch: Batch) -> jnp.ndarray:
    """Per-pixel CE for [B, H, W, C] logits vs [B, H, W] int labels
    (reference fedml_api/distributed/fedseg/utils.py SegmentationLosses.CELoss)."""
    ce, m, _ = _masked_seg_ce(logits, batch)
    return jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0)


def segmentation_metrics(logits: jnp.ndarray, batch: Batch) -> dict[str, jnp.ndarray]:
    pred = jnp.argmax(logits, -1)
    num_classes = logits.shape[-1]
    ce, m, y_safe = _masked_seg_ce(logits, batch)
    correct = (pred == batch["y"]).astype(jnp.float32)
    # confusion matrix [C, C] (true, pred) — the fedseg Evaluator's core
    # (reference fedseg/utils.py Evaluator.add_batch confusion accumulation)
    idx = y_safe * num_classes + pred  # in-bounds even for ignored labels (masked to 0)
    conf = jnp.zeros((num_classes * num_classes,), jnp.float32).at[idx.ravel()].add(m.ravel())
    return {
        "test_correct": jnp.sum(correct * m),
        "test_loss": jnp.sum(ce * m),  # per-pixel sum; engine divides by total
        "test_total": jnp.sum(m),
        "confusion": conf.reshape(num_classes, num_classes),
    }


TASKS: dict[str, tuple[Callable, Callable]] = {
    "classification": (classification_loss, classification_metrics),
    "nwp": (lm_loss, lm_metrics),
    "char_lm": (lm_loss, lm_metrics),
    "tag": (tag_loss, tag_metrics),
    "segmentation": (segmentation_loss, segmentation_metrics),
}


# ---------------------------------------------------------------------------
# ClientTrainer
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ClientTrainer:
    """Bundles a Flax module with task loss/metrics and local-opt settings.

    ``prox_mu``: FedProx proximal coefficient μ — the term the reference's
    distributed fedprox package *omits* (SURVEY §2.2); implemented here for
    real (loss += μ/2 · ||params − global||²).
    """

    module: Any  # flax.linen.Module
    task: str = "classification"
    optimizer: optax.GradientTransformation = dataclasses.field(
        default_factory=lambda: optax.sgd(0.03)
    )
    epochs: int = 1
    prox_mu: float = 0.0

    @property
    def loss_and_metrics(self):
        return TASKS[self.task]

    def init(self, rng: jax.Array, sample_batch: Batch) -> Pytree:
        variables = self.module.init(
            {"params": rng, "dropout": rng}, sample_batch["x"], train=False
        )
        return dict(variables)

    # -- single gradient step on one masked batch ------------------------------

    def loss_fn(self, params: Pytree, model_state: Pytree, global_params: Pytree,
                batch: Batch, rng: jax.Array):
        out = self.module.apply(
            {"params": params, **model_state},
            batch["x"],
            train=True,
            mutable=list(model_state.keys()),
            rngs={"dropout": rng},
        )
        logits, new_model_state = out
        loss = self.loss_and_metrics[0](logits, batch)
        if self.prox_mu > 0.0:
            from fedml_tpu.core import tree as treelib

            diff = treelib.tree_sub(params, global_params)
            loss = loss + 0.5 * self.prox_mu * treelib.tree_dot(diff, diff)
        return loss, new_model_state

    def train_step(self, variables: Pytree, opt_state, global_params: Pytree,
                   batch: Batch, rng: jax.Array):
        params = variables["params"]
        model_state = {k: v for k, v in variables.items() if k != "params"}
        (loss, new_model_state), grads = jax.value_and_grad(self.loss_fn, has_aux=True)(
            params, model_state, global_params, batch, rng
        )
        # A fully-padded batch (mask all zero) must be a no-op: gradients are
        # already zero there, but guard optimizer statistics too.
        has_data = jnp.sum(batch["mask"]) > 0
        updates, new_opt_state = self.optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params = jax.tree.map(lambda n, o: jnp.where(has_data, n, o), new_params, params)
        new_opt_state = jax.tree.map(
            lambda n, o: jnp.where(has_data, n, o), new_opt_state, opt_state
        )
        new_model_state = jax.tree.map(
            lambda n, o: jnp.where(has_data, n, o), new_model_state, model_state
        )
        return {"params": new_params, **new_model_state}, new_opt_state, loss

    # -- evaluation ------------------------------------------------------------

    def eval_batch(self, variables: Pytree, batch: Batch) -> dict[str, jnp.ndarray]:
        logits = self.module.apply(variables, batch["x"], train=False)
        return self.loss_and_metrics[1](logits, batch)


# ---------------------------------------------------------------------------
# Local training program: K epochs × steps as one lax.scan
# ---------------------------------------------------------------------------


def make_local_train(trainer: ClientTrainer):
    """Returns ``local_train(global_variables, data, rng, num_steps=None)
    -> (variables, metrics)``.

    ``data`` holds one client's epoch of batches, stacked on a leading steps
    axis: ``{"x": [S, B, ...], "y": [S, B, ...], "mask": [S, B]}``. The
    function runs ``trainer.epochs`` passes over those S batches as a single
    nested scan — the whole thing is jit/vmap-compatible, so a cohort of C
    clients is ``vmap(local_train)`` over a [C, S, B, ...] stack.

    ``num_steps`` (optional scalar, vmappable per client) bounds the local
    work: scan steps with global index >= num_steps are masked no-ops. This
    is the SURVEY "hard parts" mask-based early exit enabling heterogeneous
    local-step counts (FedProx straggler protocol / FedNova per-client τ,
    reference standalone/fednova/fednova.py:79-154) inside the one-compile
    round program: stragglers run e_i < E epochs, i.e. num_steps = e_i · S.

    Replaces the reference hot loop (my_model_trainer_classification.train,
    reference standalone/fedavg/my_model_trainer_classification.py:12: Python
    for-epoch/for-batch with .to(device) per batch).
    """

    def local_train(global_variables: Pytree, data: Batch, rng: jax.Array,
                    num_steps=None):
        global_params = global_variables["params"]
        opt_state = trainer.optimizer.init(global_variables["params"])
        S = jax.tree.leaves(data)[0].shape[0]

        def epoch_body(carry, e):
            variables, opt_state, rng = carry

            def step_body(carry, xs):
                variables, opt_state, rng = carry
                s, batch = xs
                if num_steps is not None:
                    active = ((e * S + s) < num_steps).astype(jnp.float32)
                    batch = dict(batch)
                    batch["mask"] = batch["mask"] * active
                rng, step_rng = jax.random.split(rng)
                variables, opt_state, loss = trainer.train_step(
                    variables, opt_state, global_params, batch, step_rng
                )
                # weight for the loss average: did this step see any data?
                w = (jnp.sum(batch["mask"]) > 0).astype(jnp.float32)
                return (variables, opt_state, rng), (loss, w)

            (variables, opt_state, rng), (losses, ws) = scanlib.scan(
                step_body, (variables, opt_state, rng), (jnp.arange(S), data)
            )
            return (variables, opt_state, rng), (jnp.sum(losses * ws), jnp.sum(ws))

        (variables, opt_state, rng), (loss_sums, w_sums) = scanlib.scan(
            epoch_body, (global_variables, opt_state, rng), jnp.arange(trainer.epochs)
        )
        # mean loss over executed (unmasked) steps of the last executed epoch
        if num_steps is None:
            last = trainer.epochs - 1
        else:
            last = jnp.maximum(
                jnp.minimum((num_steps - 1) // S, trainer.epochs - 1), 0
            )
        metrics = {
            "train_loss": loss_sums[last] / jnp.maximum(w_sums[last], 1.0)
        }
        return variables, metrics

    return local_train


def make_lane_step(trainer: ClientTrainer):
    """One packed-lane step: ``lane_step(variables, opt_state, global_variables,
    opt0, batch, rng, is_first) -> (variables, opt_state, loss, w)``.

    The packed execution mode (sim/engine.py, SimConfig.pack_lanes) scans a
    lane carrying ONE client's training state at a time; ``is_first`` marks a
    client boundary — the carry is reset to the broadcast global variables and
    the freshly-initialized optimizer state ``opt0`` (a pure select, no
    arithmetic, so the reset is bit-exact) before the ordinary
    :meth:`ClientTrainer.train_step` runs. ``w`` is the step's loss weight
    (did this step see any data), exactly as in :func:`make_local_train`'s
    step body. Designed to be ``vmap``-ed over the lane axis with ``is_first``
    a per-lane scalar."""

    def lane_step(variables: Pytree, opt_state, global_variables: Pytree,
                  opt0, batch: Batch, rng: jax.Array, is_first):
        reset = lambda fresh, carried: jax.tree.map(  # noqa: E731
            lambda a, b: jnp.where(is_first, a, b), fresh, carried
        )
        variables = reset(global_variables, variables)
        opt_state = reset(opt0, opt_state)
        variables, opt_state, loss = trainer.train_step(
            variables, opt_state, global_variables["params"], batch, rng
        )
        w = (jnp.sum(batch["mask"]) > 0).astype(jnp.float32)
        return variables, opt_state, loss, w

    return lane_step


def make_local_update(trainer: ClientTrainer, codec=None, local_train_fn=None):
    """Compressed local-update program: ``local_update(global_variables,
    data, rng, residual=None, num_steps=None) -> (payload, new_residual,
    metrics)``.

    Runs :func:`make_local_train`, takes the model delta, adds the carried
    error-feedback ``residual`` (compress/error_feedback.py), and encodes it
    with ``codec`` (compress/codec.py) — the client side of the
    update-compression subsystem in one jit-compatible function.
    ``codec=None`` returns the raw delta (``payload`` is a pytree);
    otherwise ``payload`` is an ``EncodedUpdate`` and ``metrics`` gains
    ``uplink_bytes``/``uplink_dense_bytes``.
    """
    from fedml_tpu.compress import error_feedback as ef
    from fedml_tpu.compress.codec import tree_bytes
    from fedml_tpu.core import tree as treelib

    local_train = local_train_fn or make_local_train(trainer)

    def local_update(global_variables, data, rng, residual=None, num_steps=None):
        new_vars, metrics = local_train(global_variables, data, rng, num_steps)
        delta = treelib.tree_sub(new_vars, global_variables)
        if codec is None:
            return delta, residual, metrics
        comp = ef.compensate(delta, residual)
        enc, _, new_residual = ef.encode_with_feedback(
            codec, comp, jax.random.fold_in(rng, 0xC0DEC)
        )
        metrics = dict(metrics)
        metrics["uplink_bytes"] = jnp.float32(enc.nbytes)
        metrics["uplink_dense_bytes"] = jnp.float32(tree_bytes(delta))
        return enc, new_residual, metrics

    return local_update


def make_local_eval(trainer: ClientTrainer):
    """``local_eval(variables, data) -> summed metric dict`` over [S, B, ...]
    batches; vmap over clients for the all-client eval the reference does
    serially (FedAVGAggregator.test_on_server_for_all_clients,
    FedAVGAggregator.py:110-164)."""

    def local_eval(variables: Pytree, data: Batch):
        def step(carry, batch):
            m = trainer.eval_batch(variables, batch)
            return carry, m

        _, metrics = scanlib.scan(step, 0, data)
        return jax.tree.map(lambda x: jnp.sum(x, axis=0), metrics)

    return local_eval
