"""Backend-aware ``lax.scan``: rolled on TPU, straight-lined on XLA:CPU.

XLA:CPU executes convolutions (and other thunk-dispatched ops) inside
``while`` loop bodies on a slow single-threaded fallback path — measured
~50x slower than the same steps emitted straight-line (10-step CNN local
epoch: 24 s vs 0.5 s on one core). ``lax.scan(unroll=True)`` is NOT enough:
nesting one scan inside another still leaves the convolutions inside a
``while`` body (measured: identical 24 s). So on CPU this helper emits a
genuine Python loop — straight-line HLO, no scan at all. On TPU the rolled
``lax.scan`` is the right program: one compiled body, no code-size blowup.

Scans longer than ``UNROLL_CAP`` stay rolled even on CPU — straight-lining
trades compile time for run time and stops paying off for long loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# straight-line budget on CPU; long scans keep the rolled loop (compile time).
# The budget is shared across NESTED scans (an outer straight-lined scan of
# length L gives its body a budget of CAP // L), so E epochs x S steps can
# never emit more than ~CAP total straight-lined bodies.
UNROLL_CAP = 64
_budget = [UNROLL_CAP]


def scan(body, init, xs, length=None):
    """``jax.lax.scan`` with CPU-aware straight-lining (see module docstring)."""
    if length is None:
        length = jax.tree.leaves(xs)[0].shape[0]
    if (length == 0 or length > _budget[-1]
            or jax.default_backend() != "cpu"):
        if jax.default_backend() != "cpu" or length == 0:
            return jax.lax.scan(body, init, xs, length=length)
        # Rolled on CPU: nested scans inside this while body must stay rolled
        # too (straight-lining them would bloat the HLO ~length-fold while the
        # outer loop keeps convs on the slow conv-in-while path anyway).
        _budget.append(0)
        try:
            return jax.lax.scan(body, init, xs, length=length)
        finally:
            _budget.pop()
    carry = init
    ys = []
    _budget.append(max(_budget[-1] // length, 0))
    try:
        for i in range(length):
            x = jax.tree.map(lambda a: a[i], xs)
            carry, y = body(carry, x)
            ys.append(y)
    finally:
        _budget.pop()
    stacked = jax.tree.map(lambda *t: jnp.stack(t), *ys)
    return carry, stacked
