"""RNG discipline.

The reference seeds numpy/torch globally (fedml_experiments/distributed/fedavg/
main_fedavg.py:448-451) and re-seeds client sampling per round with the round
index (fedml_api/distributed/fedavg/FedAVGAggregator.py:90-98). JAX requires
explicit threaded PRNG keys; this module reproduces the *semantics* (determinism,
per-round sampling reproducibility) with explicit key derivation.
"""

from __future__ import annotations

import jax
import numpy as np


def root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def round_key(key: jax.Array, round_idx: int) -> jax.Array:
    """Key for everything that happens inside one FL round."""
    return jax.random.fold_in(key, round_idx)


def client_keys(key: jax.Array, num_clients: int) -> jax.Array:
    """One independent key per client slot (stacked, vmap-able)."""
    return jax.random.split(key, num_clients)


def sample_clients(round_idx: int, client_num_in_total: int, client_num_per_round: int) -> np.ndarray:
    """Reproduce the reference's client-sampling sequence exactly.

    Reference (FedAVGAggregator.client_sampling, FedAVGAggregator.py:90-98):
    ``np.random.seed(round_idx); np.random.choice(range(N), k, replace=False)``.
    Kept host-side numpy on purpose so runs can be compared 1:1 against the
    reference's sampled cohorts.
    """
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total)
    rng = np.random.RandomState(round_idx)
    return rng.choice(client_num_in_total, client_num_per_round, replace=False)
