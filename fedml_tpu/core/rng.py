"""RNG discipline.

The reference seeds numpy/torch globally (fedml_experiments/distributed/fedavg/
main_fedavg.py:448-451) and re-seeds client sampling per round with the round
index (fedml_api/distributed/fedavg/FedAVGAggregator.py:90-98). JAX requires
explicit threaded PRNG keys; this module reproduces the *semantics* (determinism,
per-round sampling reproducibility) with explicit key derivation.
"""

from __future__ import annotations

import jax
import numpy as np


def root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def round_key(key: jax.Array, round_idx: int) -> jax.Array:
    """Key for everything that happens inside one FL round."""
    return jax.random.fold_in(key, round_idx)


def client_keys(key: jax.Array, num_clients: int) -> jax.Array:
    """One independent key per client slot (stacked, vmap-able)."""
    return jax.random.split(key, num_clients)


def sample_clients(round_idx: int, client_num_in_total: int,
                   client_num_per_round: int,
                   eligible: np.ndarray | None = None) -> np.ndarray:
    """Reproduce the reference's client-sampling sequence exactly.

    Reference (FedAVGAggregator.client_sampling, FedAVGAggregator.py:90-98):
    ``np.random.seed(round_idx); np.random.choice(range(N), k, replace=False)``.
    Kept host-side numpy on purpose so runs can be compared 1:1 against the
    reference's sampled cohorts.

    ``eligible`` restricts the draw to an availability-filtered client-id
    subset (the population model's cohort seam,
    fedml_tpu.population.model.Population.round_view). ``eligible=None``
    is bit-identical to the original full-population draw — and so is
    ``eligible=arange(N)``: numpy's ``choice(a, k, replace=False)`` indexes
    ``a`` through the same seeded permutation it returns for the int form,
    so a fully-available population reproduces the reference cohorts
    exactly (tools/population_smoke.py pins this).
    """
    if eligible is not None:
        eligible = np.asarray(eligible)
        if client_num_per_round >= len(eligible):
            # everyone available participates — the full-participation
            # shortcut, applied to the eligible subset
            return eligible.copy()
        rng = np.random.RandomState(round_idx)
        return rng.choice(eligible, client_num_per_round, replace=False)
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total)
    rng = np.random.RandomState(round_idx)
    return rng.choice(client_num_in_total, client_num_per_round, replace=False)
