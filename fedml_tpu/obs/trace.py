"""Process-wide span/event tracer: the one telemetry spine for the round
driver, the prefetch pipeline, the experiment loops, the message-passing
transport, and the compression subsystem (docs/OBSERVABILITY.md).

The reference stack's observability is a pile of disconnected channels —
per-process logging, wandb curves, MLOps MQTT telemetry, comm tick/tock
wall-clock logs (fedml_core/distributed/communication/utils.py:6-18). None
of them answer the questions the pipelined/packed engine raises: where does
the host stall, how deep does the prefetch queue run, how full are the
packed lanes, how long does a wire message spend in its handler. This
module answers them with ONE stream of spans/counters that exports to JSONL
and to Chrome trace-event JSON (loadable in Perfetto / ``chrome://tracing``,
one track per thread).

Design constraints:

- **Read-only.** Tracing wraps host code with timers; it never touches rng,
  staging, or aggregation. Traced runs are bit-identical to untraced runs
  (tools/trace_smoke.py runs under the same engine the bit-identity smokes
  guard).
- **Zero overhead when disabled.** Hot-path call sites use the module-level
  helpers (:func:`span` / :func:`gauge` / ...), which cost one global read
  and return a shared no-op context manager when no tracer is installed.
  Sites whose *attributes* cost anything (e.g. payload byte sums) guard on
  :func:`get` first. bench.py's trace probe measures both sides.
- **Thread-safe.** Spans land from the driver thread, the prefetch staging
  thread, and every comm worker thread; each thread gets its own track id
  (Chrome ``tid``) so Perfetto renders the pipeline overlap visually.

Usage::

    from fedml_tpu.obs import trace

    with trace.span("engine/stage", round=r):
        ...
    trace.gauge("prefetch/queue_depth", q.qsize())

    tracer = trace.install()          # start recording (process-wide)
    ...
    trace.uninstall()
    tracer.export_chrome("trace.chrome.json")

or, scoped (the ``--trace_dir`` entry-point wiring)::

    with trace.trace_to(run_dir):     # exports trace.jsonl + chrome on exit
        ...
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from pathlib import Path
from typing import Any

__all__ = [
    "Tracer", "install", "uninstall", "get", "enabled",
    "span", "event", "counter", "gauge", "trace_to", "wire_ctx",
    "lane_traces",
    "CHROME_TRACE_NAME", "JSONL_TRACE_NAME", "META_EVENT_NAME",
]

JSONL_TRACE_NAME = "trace.jsonl"
CHROME_TRACE_NAME = "trace.chrome.json"
META_EVENT_NAME = "trace/meta"

# ancestors carried in a wire trace context (comm/base.py stamping): enough
# to reconstruct the enclosing handler/broadcast chain at the receiver
# without letting deeply-nested rounds grow the header unboundedly
MAX_CTX_CHAIN = 8


class _NullSpan:
    """Shared do-nothing context manager — the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; created by :meth:`Tracer.span`.

    On enter it is assigned a tracer-unique ``span_id`` and pushed on the
    calling thread's open-span stack (the stack top is its ``parent_id``),
    so every recorded span carries a causal parent link and
    :func:`wire_ctx` can snapshot the open chain for the wire."""

    __slots__ = ("_tracer", "_name", "_attrs", "_t0", "span_id", "_open")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> "_Span":
        tracer = self._tracer
        self._t0 = tracer._clock()
        stack = tracer._stack()
        self.span_id = next(tracer._ids)
        self._open = {
            "name": self._name, "ts": tracer._us(self._t0),
            "tid": tracer._tid(), "span_id": self.span_id,
            "parent_id": stack[-1]["span_id"] if stack else None,
            "attrs": self._attrs,
        }
        stack.append(self._open)
        return self

    def __exit__(self, *exc) -> bool:
        tracer = self._tracer
        t_end = tracer._clock()
        stack = tracer._stack()
        if stack and stack[-1] is self._open:
            stack.pop()
        else:  # out-of-order exit (shouldn't happen): drop just this entry
            try:
                stack.remove(self._open)
            except ValueError:
                pass
        rec = {
            "name": self._name, "ph": "X", "ts": self._open["ts"],
            "dur": max(tracer._us(t_end) - self._open["ts"], 0.0),
            "tid": self._open["tid"],
            "args": {**self._attrs, "span_id": self.span_id},
        }
        if self._open["parent_id"] is not None:
            rec["args"]["parent_id"] = self._open["parent_id"]
        tracer._record(rec)
        return False


class Tracer:
    """Thread-safe in-memory span/event recorder.

    Events are stored directly in Chrome trace-event shape (``name``/``ph``/
    ``ts``/``dur``/``tid``/``args``; timestamps in microseconds relative to
    tracer construction, measured on ``time.perf_counter``), so both
    exporters are a serialization of the same list. ``ph`` values used:
    ``X`` complete span, ``C`` counter/gauge sample, ``i`` instant event.
    """

    PID = 1  # single-process tracer; one Chrome process track

    # events kept in memory while recording (~150 bytes each → ~300 MB
    # worst case). The buffer is a RING: once full, the OLDEST events are
    # evicted, so a multi-hour traced run keeps the most recent window (the
    # part an operator debugging "why did it just get slow" actually wants)
    # at bounded memory; ``dropped`` counts evictions and both exporters
    # surface it as a ``trace/dropped_events`` counter record.
    DEFAULT_MAX_EVENTS = 2_000_000
    DROPPED_EVENT_NAME = "trace/dropped_events"

    def __init__(self, max_events: int | None = None,
                 lane: str | None = None):
        from collections import deque

        self._clock = time.perf_counter
        self._t0 = self._clock()
        # wall-clock anchor for this tracer's t=0 (exported as metadata):
        # lets tools/trace_merge.py coarsely align lanes that never
        # exchanged a message, before send<->recv pairs refine the offset
        self.wall0 = time.time()
        # lane label identifying this tracer's process/rank in a merged
        # multi-rank trace; rides outgoing wire contexts so the receive
        # side can name its causal origin
        self.lane = lane
        self._lock = threading.Lock()
        self._max_events = (self.DEFAULT_MAX_EVENTS if max_events is None
                            else int(max_events))
        self._events: "deque[dict]" = deque(maxlen=self._max_events)  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock
        self._thread_ids: dict[int, int] = {}
        self._thread_names: dict[int, str] = {}
        self._ids = itertools.count(1)  # span ids; count.__next__ is atomic
        self._local = threading.local()
        # thread ident -> that thread's open-span stack, registered on the
        # thread's first span so exporters can surface still-open spans
        self._open_stacks: dict[int, list] = {}  # guarded-by: _lock

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
            with self._lock:
                self._open_stacks[threading.get_ident()] = st
        return st

    def _record(self, rec: dict) -> None:
        with self._lock:
            if len(self._events) >= self._max_events:
                self.dropped += 1  # deque evicts the oldest on append
            if self._max_events > 0:
                self._events.append(rec)

    # -- recording -----------------------------------------------------------

    def _tid(self) -> int:
        t = threading.current_thread()
        ident = t.ident or 0
        tid = self._thread_ids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._thread_ids.setdefault(
                    ident, len(self._thread_ids) + 1
                )
                self._thread_names[tid] = t.name
        return tid

    def _us(self, t: float) -> float:
        return (t - self._t0) * 1e6

    def span(self, name: str, **attrs: Any) -> _Span:
        """Context manager recording one complete span on the calling
        thread's track; ``attrs`` become the span's Chrome ``args``."""
        return _Span(self, name, attrs)

    def add_span(self, name: str, t_start: float, t_end: float,
                 **attrs: Any) -> None:
        """Record an already-timed span (``time.perf_counter`` endpoints) —
        the manual-timing API for callers like RoundTimer that measured the
        interval themselves. Parented under the calling thread's innermost
        open span, like a context-manager span would be."""
        stack = self._stack()
        rec = {
            "name": name, "ph": "X", "ts": self._us(t_start),
            "dur": max((t_end - t_start) * 1e6, 0.0), "tid": self._tid(),
            "args": {**attrs, "span_id": next(self._ids)},
        }
        if stack:
            rec["args"]["parent_id"] = stack[-1]["span_id"]
        self._record(rec)

    def current_ctx(self, origin: int | None = None) -> dict:
        """The calling thread's wire trace context: innermost open span id,
        its ancestor chain (inner-first, capped), this tracer's lane label,
        the sender rank, and the send wall time — the header dict
        ``comm/base.py`` stamps under ``MSG_ARG_KEY_TRACE_CTX``."""
        stack = self._stack()
        ctx: dict[str, Any] = {"rank": origin, "sent_at": time.time()}
        if self.lane is not None:
            ctx["lane"] = self.lane
        if stack:
            ctx["span"] = stack[-1]["span_id"]
            chain = [s["span_id"] for s in stack[-2::-1]]
            if chain:
                ctx["chain"] = chain[:MAX_CTX_CHAIN]
        return ctx

    def event(self, name: str, **attrs: Any) -> None:
        """Record an instant event (a point-in-time marker)."""
        rec = {"name": name, "ph": "i", "ts": self._us(self._clock()),
               "tid": self._tid(), "s": "t"}
        if attrs:
            rec["args"] = attrs
        self._record(rec)

    def counter(self, name: str, value: float, **attrs: Any) -> None:
        """Record one sample of a named counter/gauge series."""
        rec = {"name": name, "ph": "C", "ts": self._us(self._clock()),
               "tid": self._tid(),
               "args": {"value": float(value), **attrs}}
        self._record(rec)

    # a gauge is a counter whose samples are levels, not increments; the
    # trace stream does not distinguish them
    gauge = counter

    # -- reading / export ----------------------------------------------------

    def events(self) -> list[dict]:
        """Snapshot of recorded events (copies the list, not the dicts)."""
        with self._lock:
            return list(self._events)

    def _dropped_record(self) -> dict | None:
        """The exporter-surfaced drop counter: a ``C`` record named
        :data:`DROPPED_EVENT_NAME` appended to both export formats when the
        ring evicted anything — a truncated trace must say so in-band, not
        only in a log line that scrolled away."""
        with self._lock:
            dropped = self.dropped
        if not dropped:
            return None
        return {"name": self.DROPPED_EVENT_NAME, "ph": "C",
                "ts": self._us(self._clock()), "tid": 0,
                "args": {"value": float(dropped),
                         "max_events": self._max_events}}

    def thread_names(self) -> dict[int, str]:
        with self._lock:
            return dict(self._thread_names)

    def open_spans(self) -> list[dict]:
        """Spans entered but not yet exited at call time, as Chrome ``B``
        (begin) records — a span a crash or hang left unterminated exports
        open-ended instead of vanishing. Perfetto renders an unmatched
        ``B`` as running to the end of the trace; tools/trace_report.py
        flags it the same way."""
        with self._lock:
            stacks = [list(st) for st in self._open_stacks.values()]
        recs = []
        for stack in stacks:
            for s in stack:
                args = {**s["attrs"], "span_id": s["span_id"], "open": True}
                if s["parent_id"] is not None:
                    args["parent_id"] = s["parent_id"]
                recs.append({"name": s["name"], "ph": "B", "ts": s["ts"],
                             "tid": s["tid"], "args": args})
        return recs

    def _meta_records(self) -> list[dict]:
        """Lane/wall-clock metadata + thread names, for the JSONL export:
        tools/trace_merge.py reads these to label each per-rank lane and to
        anchor lanes with no send<->recv pair on the wall clock."""
        meta = [{
            "name": META_EVENT_NAME, "ph": "M", "ts": 0.0, "tid": 0,
            "args": {"wall0": self.wall0, "lane": self.lane},
        }]
        for tid, tname in sorted(self.thread_names().items()):
            meta.append({"name": "thread_name", "ph": "M", "ts": 0.0,
                         "tid": tid, "args": {"name": tname}})
        return meta

    def export_jsonl(self, path: str | Path) -> Path:
        """One event per line, same records as the Chrome export, prefixed
        with ``M`` metadata lines (lane label, wall-clock anchor, thread
        names) and suffixed with any still-open spans."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        recs = self._meta_records() + self.events() + self.open_spans()
        dropped = self._dropped_record()
        if dropped is not None:
            recs.append(dropped)
        with open(path, "w") as f:
            for rec in recs:
                f.write(json.dumps({"pid": self.PID, **rec}) + "\n")
        return path

    def export_chrome(self, path: str | Path) -> Path:
        """Chrome trace-event JSON (object form with ``traceEvents``),
        loadable in Perfetto / ``chrome://tracing``. Thread-name metadata
        events give each Python thread its own named track."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        meta = [
            {"name": "process_name", "ph": "M", "pid": self.PID, "tid": 0,
             "args": {"name": self.lane or "fedml_tpu"}},
        ]
        for tid, tname in sorted(self.thread_names().items()):
            meta.append({"name": "thread_name", "ph": "M", "pid": self.PID,
                         "tid": tid, "args": {"name": tname}})
        recs = self.events() + self.open_spans()
        dropped = self._dropped_record()
        if dropped is not None:
            recs.append(dropped)
        payload = {
            "traceEvents": meta + [
                {"pid": self.PID, **rec} for rec in recs
            ],
            "displayTimeUnit": "ms",
            "traceMeta": {"wall0": self.wall0, "lane": self.lane},
        }
        if dropped is not None:
            payload["droppedEvents"] = int(dropped["args"]["value"])
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


# ---------------------------------------------------------------------------
# Process-wide tracer + the zero-overhead module-level helpers every
# instrumented call site uses. With the multi-tenant job plane, tracer
# installs can additionally be job-scoped (obs/jobscope.py): a thread bound
# to a job resolves that job's tracer first and falls back to the process
# one, so N co-scheduled federations keep separate span streams while
# single-job runs keep the one-global-read hot path.
# ---------------------------------------------------------------------------

_tracer: Tracer | None = None
_job_store = None  # lazily built: jobscope is only imported when job-scoping is used


def _job_tracers():
    global _job_store
    if _job_store is None:
        from fedml_tpu.obs import jobscope

        _job_store = jobscope.JobStore("tracer")
    return _job_store


def install(tracer: Tracer | None = None) -> Tracer:
    """Install ``tracer`` (a fresh one by default) as the process tracer and
    return it. Replaces any previously-installed tracer."""
    global _tracer
    _tracer = tracer if tracer is not None else Tracer()
    return _tracer


def uninstall() -> Tracer | None:
    """Remove and return the process tracer (instrumentation reverts to the
    no-op path)."""
    global _tracer
    t, _tracer = _tracer, None
    return t


def install_job(job: str, tracer: Tracer | None = None) -> Tracer:
    """Install a tracer scoped to ``job``: threads bound to the job
    (jobscope.bound / jobscope.wrap_target) resolve it ahead of the process
    tracer, so each co-scheduled federation exports its own span stream."""
    return _job_tracers().install(
        job, tracer if tracer is not None else Tracer())


def uninstall_job(job: str) -> Tracer | None:
    return _job_tracers().uninstall(job)


def job_tracers() -> dict[str, Tracer]:
    """Snapshot of the installed job-scoped tracers (job -> tracer)."""
    return _job_tracers().installed()


def get() -> Tracer | None:
    """The calling thread's job-scoped tracer when one is installed, else
    the process tracer, else None. Call sites whose span *attributes* are
    expensive to compute should guard on this."""
    store = _job_store
    if store is not None:
        t = store.lookup()
        if t is not None:
            return t
    return _tracer


def enabled() -> bool:
    return get() is not None


def span(name: str, **attrs: Any):
    """Span on the resolved tracer; shared no-op when none is installed."""
    t = get()
    return t.span(name, **attrs) if t is not None else _NULL_SPAN


def event(name: str, **attrs: Any) -> None:
    t = get()
    if t is not None:
        t.event(name, **attrs)


def counter(name: str, value: float, **attrs: Any) -> None:
    t = get()
    if t is not None:
        t.counter(name, value, **attrs)


gauge = counter


def wire_ctx(origin: int | None = None) -> dict | None:
    """The calling thread's wire trace context on the resolved tracer, or
    None when no tracer is installed — the value ``comm/base.py`` stamps
    under ``Message.MSG_ARG_KEY_TRACE_CTX`` when a manager's ``trace_wire``
    opt-in is armed. None means: do not stamp, keep the wire byte-identical
    to an untraced run."""
    t = get()
    return t.current_ctx(origin) if t is not None else None


def run_traced(run_fn, args):
    """Entry-point seam for the ``--trace_dir`` flag: run ``run_fn(args)``
    under :class:`trace_to` when ``args.trace_dir`` is set, plain otherwise.
    One definition shared by main_fedavg and every repro entry."""
    trace_dir = getattr(args, "trace_dir", None)
    if not trace_dir:
        return run_fn(args)
    with trace_to(trace_dir):
        return run_fn(args)


def add_cli_flag(parser):
    """Register the canonical ``--trace_dir`` flag (one help text for every
    entry point that supports traced runs)."""
    parser.add_argument(
        "--trace_dir", type=str, default=None,
        help="record host-side span telemetry (round driver, prefetcher, "
             "wire path — docs/OBSERVABILITY.md) and write trace.jsonl + "
             "trace.chrome.json (Perfetto/chrome://tracing) into this dir; "
             "read-only, results are unchanged",
    )
    return parser


class lane_traces:
    """Context manager: install one job-scoped :class:`Tracer` per lane
    label and export each as ``trace_<lane>.jsonl`` into ``trace_dir`` on
    exit — the in-process multi-rank tracing harness the loopback/shm run
    harnesses use (a real multi-process deployment instead passes each
    process its own ``--trace_dir`` and merges the per-process files).
    Threads are routed to their lane's tracer by binding them with
    ``jobscope`` (obs/jobscope.py); ``tools/trace_merge.py`` merges the
    exported files into one Perfetto trace."""

    def __init__(self, trace_dir: str | Path, lanes: list[str]):
        self.trace_dir = Path(trace_dir)
        self.lanes = list(lanes)
        self.tracers: dict[str, Tracer] = {}
        self.paths: dict[str, Path] = {}

    def __enter__(self) -> "lane_traces":
        for lane in self.lanes:
            self.tracers[lane] = install_job(lane, Tracer(lane=lane))
        return self

    def __exit__(self, *exc) -> bool:
        for lane in self.lanes:
            uninstall_job(lane)
            self.paths[lane] = self.tracers[lane].export_jsonl(
                self.trace_dir / f"trace_{lane}.jsonl"
            )
        return False


class trace_to:
    """Context manager: install a fresh process tracer, and on exit export
    ``trace.jsonl`` + ``trace.chrome.json`` into ``trace_dir`` and restore
    the previously-installed tracer (if any). The ``--trace_dir`` wiring of
    the experiment entry points."""

    def __init__(self, trace_dir: str | Path):
        self.trace_dir = Path(trace_dir)
        self.tracer: Tracer | None = None
        self._prev: Tracer | None = None

    def __enter__(self) -> Tracer:
        self._prev = get()
        self.tracer = install()
        return self.tracer

    def __exit__(self, *exc) -> bool:
        global _tracer
        _tracer = self._prev
        assert self.tracer is not None
        self.jsonl_path = self.tracer.export_jsonl(
            self.trace_dir / JSONL_TRACE_NAME
        )
        self.chrome_path = self.tracer.export_chrome(
            self.trace_dir / CHROME_TRACE_NAME
        )
        import logging

        logging.info("trace written: %s (%d events); open %s in Perfetto",
                     self.jsonl_path, len(self.tracer.events()),
                     self.chrome_path)
        dropped = self.tracer._dropped_record()
        if dropped is not None:
            logging.warning(
                "trace ring wrapped: %d oldest events evicted past the "
                "%d-event cap (Tracer(max_events=...) raises it; the "
                "exports carry a %s counter record)",
                int(dropped["args"]["value"]),
                int(dropped["args"]["max_events"]),
                Tracer.DROPPED_EVENT_NAME,
            )
        return False
