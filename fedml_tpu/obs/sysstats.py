"""System metrics (reference: fedml_api/distributed/fedavg_cross_silo/
SysStats.py:13 — psutil+pynvml 13-metric sampler reported through
MLOpsLogger.report_system_metric, fedml_core/mlops_logger.py:89).

TPU equivalents: host cpu/mem from /proc (psutil when present), device HBM
from jax's memory_stats(), plus process uptime/io.
"""

from __future__ import annotations

import os
import time
from typing import Any

import jax

try:
    import psutil

    HAS_PSUTIL = True
except Exception:  # pragma: no cover
    HAS_PSUTIL = False


class SysStats:
    """Samples host + device utilization.

    ``psutil.cpu_percent(interval=None)`` measures utilization *since the
    previous call* — its very first call has no reference window and always
    returns 0.0. The constructor primes that counter, so the first
    :meth:`sample` reports utilization since construction instead of a
    constant 0.0 (each later sample covers the window since the one
    before it)."""

    def __init__(self):
        self._t0 = time.time()
        if HAS_PSUTIL:
            psutil.cpu_percent(interval=None)  # prime the delta counter

    def sample(self) -> dict[str, Any]:
        out: dict[str, Any] = {"uptime_s": time.time() - self._t0}
        if HAS_PSUTIL:
            vm = psutil.virtual_memory()
            p = psutil.Process()
            out.update(
                cpu_utilization=psutil.cpu_percent(interval=None),
                system_memory_utilization=vm.percent,
                process_memory_in_use=p.memory_info().rss,
                process_memory_available=vm.available,
                process_cpu_threads_in_use=p.num_threads(),
            )
        else:  # /proc fallback
            try:
                with open("/proc/self/status") as fh:
                    for line in fh:
                        if line.startswith("VmRSS"):
                            out["process_memory_in_use"] = int(line.split()[1]) * 1024
            except OSError:
                pass
        # device (HBM) stats — the TPU analogue of gpu util/mem/temp/power
        for i, dev in enumerate(jax.local_devices()):
            ms = _device_memory_stats(dev)
            if ms:
                out[f"device{i}_bytes_in_use"] = ms.get("bytes_in_use")
                out[f"device{i}_bytes_limit"] = ms.get("bytes_limit")
                if ms.get("peak_bytes_in_use") is not None:
                    out[f"device{i}_peak_bytes_in_use"] = ms["peak_bytes_in_use"]
        return out

    def publish_device_gauges(self) -> dict[str, int]:
        """JAX device-memory gauges for the fleet telemetry plane
        (docs/OBSERVABILITY.md "Fleet telemetry"): live and peak HBM bytes
        per local device from ``Device.memory_stats()``, published into the
        installed :mod:`fedml_tpu.obs.registry` (silently skipped when none
        is installed). On backends without allocator stats — XLA:CPU —
        ``memory_stats()`` is None/unsupported and this is a silent no-op.
        Returns the gauges it published (empty on CPU)."""
        from fedml_tpu.obs import registry

        reg = registry.get()
        out: dict[str, int] = {}
        for i, dev in enumerate(jax.local_devices()):
            ms = _device_memory_stats(dev)
            if not ms:
                continue
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                v = ms.get(key)
                if v is None:
                    continue
                name = f"device{i}/{key}"
                out[name] = int(v)
                if reg is not None:
                    reg.gauge(name, int(v))
        return out


def _device_memory_stats(dev) -> dict | None:
    """``dev.memory_stats()`` or None — absent/unsupported allocators
    (XLA:CPU) must never raise out of a telemetry path."""
    try:
        return dev.memory_stats()
    except Exception:
        return None
