"""System metrics (reference: fedml_api/distributed/fedavg_cross_silo/
SysStats.py:13 — psutil+pynvml 13-metric sampler reported through
MLOpsLogger.report_system_metric, fedml_core/mlops_logger.py:89).

TPU equivalents: host cpu/mem from /proc (psutil when present), device HBM
from jax's memory_stats(), plus process uptime/io.
"""

from __future__ import annotations

import os
import time
from typing import Any

import jax

try:
    import psutil

    HAS_PSUTIL = True
except Exception:  # pragma: no cover
    HAS_PSUTIL = False


class SysStats:
    """Samples host + device utilization.

    ``psutil.cpu_percent(interval=None)`` measures utilization *since the
    previous call* — its very first call has no reference window and always
    returns 0.0. The constructor primes that counter, so the first
    :meth:`sample` reports utilization since construction instead of a
    constant 0.0 (each later sample covers the window since the one
    before it)."""

    def __init__(self):
        self._t0 = time.time()
        if HAS_PSUTIL:
            psutil.cpu_percent(interval=None)  # prime the delta counter

    def sample(self) -> dict[str, Any]:
        out: dict[str, Any] = {"uptime_s": time.time() - self._t0}
        if HAS_PSUTIL:
            vm = psutil.virtual_memory()
            p = psutil.Process()
            out.update(
                cpu_utilization=psutil.cpu_percent(interval=None),
                system_memory_utilization=vm.percent,
                process_memory_in_use=p.memory_info().rss,
                process_memory_available=vm.available,
                process_cpu_threads_in_use=p.num_threads(),
            )
        else:  # /proc fallback
            try:
                with open("/proc/self/status") as fh:
                    for line in fh:
                        if line.startswith("VmRSS"):
                            out["process_memory_in_use"] = int(line.split()[1]) * 1024
            except OSError:
                pass
        # device (HBM) stats — the TPU analogue of gpu util/mem/temp/power
        for i, dev in enumerate(jax.local_devices()):
            try:
                ms = dev.memory_stats()
            except Exception:
                ms = None
            if ms:
                out[f"device{i}_bytes_in_use"] = ms.get("bytes_in_use")
                out[f"device{i}_bytes_limit"] = ms.get("bytes_limit")
        return out
