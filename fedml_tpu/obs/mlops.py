"""MLOps telemetry: the reference's topic protocol over pluggable messengers.

Reference: fedml_core/mlops_logger.py:15 — a singleton publishing client/
server status, training metrics, round info, model info, and system
performance as JSON to fixed MQTT topics (``fl_client/mlops/status``,
``fl_server/mlops/training_progress_and_eval``, ...). The MLOps platform
subscribes to those topics.

Here the logger keeps the reference's exact topic names and payload keys so
an MLOps consumer sees the same wire protocol, but the transport is a
pluggable ``messenger`` with ``send_message_json(topic, payload_json)``:

- :class:`MqttMessenger` — real MQTT broker (production; requires paho).
- :class:`FileMessenger` — JSONL sink (offline runs, tests, and audit logs).

No singleton: construct one logger per run and pass it around — global
mutable state was a reference defect, not a feature.
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Any, Protocol

from fedml_tpu.obs.sysstats import SysStats

# reference topic names (mlops_logger.py:32-110), kept verbatim
TOPIC_CLIENT_STATUS = "fl_client/mlops/status"
TOPIC_CLIENT_ID_STATUS = "fl_client/mlops/{edge_id}/status"
TOPIC_SERVER_STATUS = "fl_server/mlops/status"
TOPIC_SERVER_ID_STATUS = "fl_server/mlops/id/status"
TOPIC_CLIENT_METRICS = "fl_client/mlops/training_metrics"
TOPIC_SERVER_METRICS = "fl_server/mlops/training_progress_and_eval"
TOPIC_ROUND_INFO = "fl_client/mlops/training_roundx"
TOPIC_CLIENT_MODEL = "fl_server/mlops/client_model"
TOPIC_AGGREGATED_MODEL = "fl_server/mlops/global_aggregated_model"
TOPIC_SYSTEM = "fl_client/mlops/system_performance"


class Messenger(Protocol):
    def send_message_json(self, topic: str, payload_json: str) -> None: ...


class FileMessenger:
    """JSONL sink: one ``{"ts", "topic", "payload"}`` record per message."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, "a")

    def send_message_json(self, topic: str, payload_json: str) -> None:
        rec = {"ts": time.time(), "topic": topic, "payload": json.loads(payload_json)}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


class MqttMessenger:
    """Publishes each topic to a real MQTT broker (paho-mqtt)."""

    def __init__(self, host: str = "localhost", port: int = 1883,
                 client_id: str = "fedml_tpu_mlops"):
        import paho.mqtt.client as mqtt  # gated: optional dependency

        if hasattr(mqtt, "CallbackAPIVersion"):  # paho >= 2.0
            self._client = mqtt.Client(
                mqtt.CallbackAPIVersion.VERSION1, client_id=client_id
            )
        else:
            self._client = mqtt.Client(client_id=client_id)
        self._client.connect(host, port)
        self._client.loop_start()

    def send_message_json(self, topic: str, payload_json: str) -> None:
        self._client.publish(topic, payload_json, qos=1)

    def close(self) -> None:
        self._client.loop_stop()
        self._client.disconnect()


class MLOpsLogger:
    """Reference-protocol telemetry reporter (mlops_logger.py API names)."""

    def __init__(self, messenger: Messenger, run_id: Any = None, edge_id: Any = None):
        self.messenger = messenger
        self.run_id = run_id
        self.edge_id = edge_id
        self._sys = SysStats()

    def _send(self, topic: str, msg: dict) -> None:
        payload = json.dumps(msg)
        logging.debug("mlops %s: %s", topic, payload)
        self.messenger.send_message_json(topic, payload)

    # -- status (reference :32-57) -----------------------------------------
    def report_client_training_status(self, edge_id, status) -> None:
        self._send(TOPIC_CLIENT_STATUS, {"edge_id": edge_id, "status": status})

    def report_client_id_status(self, run_id, edge_id, status) -> None:
        self._send(
            TOPIC_CLIENT_ID_STATUS.format(edge_id=edge_id),
            {"run_id": run_id, "edge_id": edge_id, "status": status},
        )

    def report_server_training_status(self, run_id, status) -> None:
        self._send(TOPIC_SERVER_STATUS, {"run_id": run_id, "status": status})

    def report_server_id_status(self, run_id, status) -> None:
        self._send(TOPIC_SERVER_ID_STATUS, {"run_id": run_id, "status": status})

    # -- metrics / round / model info (reference :59-88) --------------------
    def report_client_training_metric(self, metric: dict) -> None:
        self._send(TOPIC_CLIENT_METRICS, metric)

    def report_server_training_metric(self, metric: dict) -> None:
        self._send(TOPIC_SERVER_METRICS, metric)

    def report_server_training_round_info(self, round_info: dict) -> None:
        self._send(TOPIC_ROUND_INFO, round_info)

    def report_client_model_info(self, model_info: dict) -> None:
        self._send(TOPIC_CLIENT_MODEL, model_info)

    def report_aggregated_model_info(self, model_info: dict) -> None:
        self._send(TOPIC_AGGREGATED_MODEL, model_info)

    # -- system performance (reference :90-110) -----------------------------
    def report_system_metric(self, metric: dict | None = None) -> None:
        if metric is None:
            metric = {"run_id": self.run_id, "edge_id": self.edge_id}
            metric.update(self._sys.sample())
        self._send(TOPIC_SYSTEM, metric)

    def round_callback(self):
        """A FedSim ``callback`` that streams every round record as a server
        training metric plus round info — wiring the engine's history into
        the MLOps protocol."""

        def cb(rec: dict) -> None:
            self.report_server_training_metric(
                {"run_id": self.run_id, **rec}
            )
            self.report_server_training_round_info(
                {"run_id": self.run_id, "round_index": rec.get("round")}
            )

        return cb
