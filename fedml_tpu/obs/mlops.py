"""MLOps telemetry: the reference's topic protocol over pluggable messengers.

Reference: fedml_core/mlops_logger.py:15 — a singleton publishing client/
server status, training metrics, round info, model info, and system
performance as JSON to fixed MQTT topics (``fl_client/mlops/status``,
``fl_server/mlops/training_progress_and_eval``, ...). The MLOps platform
subscribes to those topics.

Here the logger keeps the reference's exact topic names and payload keys so
an MLOps consumer sees the same wire protocol, but the transport is a
pluggable ``messenger`` with ``send_message_json(topic, payload_json)``:

- :class:`MqttMessenger` — real MQTT broker (production; requires paho).
- :class:`FileMessenger` — JSONL sink (offline runs, tests, and audit logs).

No singleton: construct one logger per run and pass it around — global
mutable state was a reference defect, not a feature.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from pathlib import Path
from typing import Any, Protocol

from fedml_tpu.obs.sysstats import SysStats

# reference topic names (mlops_logger.py:32-110, FedEventSDK.py:72), verbatim
TOPIC_CLIENT_STATUS = "fl_client/mlops/status"
TOPIC_CLIENT_ID_STATUS = "fl_client/mlops/{edge_id}/status"
TOPIC_SERVER_STATUS = "fl_server/mlops/status"
TOPIC_SERVER_ID_STATUS = "fl_server/mlops/id/status"
TOPIC_CLIENT_METRICS = "fl_client/mlops/training_metrics"
TOPIC_SERVER_METRICS = "fl_server/mlops/training_progress_and_eval"
TOPIC_ROUND_INFO = "fl_client/mlops/training_roundx"
TOPIC_CLIENT_MODEL = "fl_server/mlops/client_model"
TOPIC_AGGREGATED_MODEL = "fl_server/mlops/global_aggregated_model"
TOPIC_SYSTEM = "fl_client/mlops/system_performance"
TOPIC_EVENTS = "/mlops/events"
TOPIC_LOGS = "/mlops/logs"


class Messenger(Protocol):
    def send_message_json(self, topic: str, payload_json: str) -> None: ...


class FileMessenger:
    """JSONL sink: one ``{"ts", "topic", "payload"}`` record per message."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, "a")

    def send_message_json(self, topic: str, payload_json: str) -> None:
        rec = {"ts": time.time(), "topic": topic, "payload": json.loads(payload_json)}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


class MqttMessenger:
    """Publishes each topic to a real MQTT broker (paho-mqtt)."""

    def __init__(self, host: str = "localhost", port: int = 1883,
                 client_id: str = "fedml_tpu_mlops"):
        import paho.mqtt.client as mqtt  # gated: optional dependency

        if hasattr(mqtt, "CallbackAPIVersion"):  # paho >= 2.0
            self._client = mqtt.Client(
                mqtt.CallbackAPIVersion.VERSION1, client_id=client_id
            )
        else:
            self._client = mqtt.Client(client_id=client_id)
        self._client.connect(host, port)
        self._client.loop_start()

    def send_message_json(self, topic: str, payload_json: str) -> None:
        self._client.publish(topic, payload_json, qos=1)

    def close(self) -> None:
        self._client.loop_stop()
        self._client.disconnect()


class MLOpsLogger:
    """Reference-protocol telemetry reporter (mlops_logger.py API names)."""

    def __init__(self, messenger: Messenger, run_id: Any = None, edge_id: Any = None):
        self.messenger = messenger
        self.run_id = run_id
        self.edge_id = edge_id
        self._sys = SysStats()

    def _send(self, topic: str, msg: dict) -> None:
        payload = json.dumps(msg)
        logging.debug("mlops %s: %s", topic, payload)
        self.messenger.send_message_json(topic, payload)

    # -- status (reference :32-57) -----------------------------------------
    def report_client_training_status(self, edge_id, status) -> None:
        self._send(TOPIC_CLIENT_STATUS, {"edge_id": edge_id, "status": status})

    def report_client_id_status(self, run_id, edge_id, status) -> None:
        self._send(
            TOPIC_CLIENT_ID_STATUS.format(edge_id=edge_id),
            {"run_id": run_id, "edge_id": edge_id, "status": status},
        )

    def report_server_training_status(self, run_id, status) -> None:
        self._send(TOPIC_SERVER_STATUS, {"run_id": run_id, "status": status})

    def report_server_id_status(self, run_id, status) -> None:
        self._send(TOPIC_SERVER_ID_STATUS, {"run_id": run_id, "status": status})

    # -- metrics / round / model info (reference :59-88) --------------------
    def report_client_training_metric(self, metric: dict) -> None:
        self._send(TOPIC_CLIENT_METRICS, metric)

    def report_server_training_metric(self, metric: dict) -> None:
        self._send(TOPIC_SERVER_METRICS, metric)

    def report_server_training_round_info(self, round_info: dict) -> None:
        self._send(TOPIC_ROUND_INFO, round_info)

    def report_client_model_info(self, model_info: dict) -> None:
        self._send(TOPIC_CLIENT_MODEL, model_info)

    def report_aggregated_model_info(self, model_info: dict) -> None:
        self._send(TOPIC_AGGREGATED_MODEL, model_info)

    # -- system performance (reference :90-110) -----------------------------
    def report_system_metric(self, metric: dict | None = None) -> None:
        if metric is None:
            metric = {"run_id": self.run_id, "edge_id": self.edge_id}
            metric.update(self._sys.sample())
        self._send(TOPIC_SYSTEM, metric)

    def round_callback(self):
        """A FedSim ``callback`` that streams every round record as a server
        training metric plus round info — wiring the engine's history into
        the MLOps protocol."""

        def cb(rec: dict) -> None:
            self.report_server_training_metric(
                {"run_id": self.run_id, **rec}
            )
            self.report_server_training_round_info(
                {"run_id": self.run_id, "round_index": rec.get("round")}
            )

        return cb


class FedEvents:
    """Start/end event spans on the reference's ``/mlops/events`` topic with
    its exact payload keys (FedEventSDK.py:37-81). The reference's singleton
    and hardcoded MqttS3 transport are dropped: one instance per run over any
    :class:`Messenger`."""

    def __init__(self, messenger: Messenger, run_id: Any = None, edge_id: Any = 0):
        self.messenger = messenger
        self.run_id = run_id
        self.edge_id = edge_id

    def _send(self, msg: dict) -> None:
        self.messenger.send_message_json(TOPIC_EVENTS, json.dumps(msg))

    def log_event_started(self, event_name, event_value=None, event_edge_id=None):
        self._send({
            "run_id": self.run_id,
            "edge_id": self.edge_id if event_edge_id is None else event_edge_id,
            "event_name": event_name,
            "event_value": "" if event_value is None else event_value,
            "started_time": int(time.time()),
        })

    def log_event_ended(self, event_name, event_value=None, event_edge_id=None):
        self._send({
            "run_id": self.run_id,
            "edge_id": self.edge_id if event_edge_id is None else event_edge_id,
            "event_name": event_name,
            "event_value": "" if event_value is None else event_value,
            "ended_time": int(time.time()),
        })

    @contextlib.contextmanager
    def span(self, event_name, event_value=None):
        """Context manager emitting a paired started/ended event."""
        self.log_event_started(event_name, event_value)
        try:
            yield
        finally:
            self.log_event_ended(event_name, event_value)


class FedLogs:
    """Incremental log shipper (FedLogsSDK.py:97-139 role): tails a run's
    log file and publishes batches of new lines with the reference's upload
    payload keys. The reference POSTs to open.fedml.ai in a background
    process and tracks its offset in log-config.yaml; here upload is an
    explicit ``upload_once()`` the caller schedules (cron thread, round
    callback, or atexit), the offset lives on the instance, and the sink is
    any :class:`Messenger` on ``/mlops/logs``."""

    LOG_LINES_PER_UPLOAD = 100
    MAX_BYTES_PER_READ = 8 << 20  # backlog is shipped in bounded chunks

    def __init__(self, log_file_path: str | Path, messenger: Messenger,
                 run_id: Any = None, edge_id: Any = 0):
        self.log_file_path = Path(log_file_path)
        self.messenger = messenger
        self.run_id = run_id
        self.edge_id = edge_id
        self._offset = 0  # byte offset of the first unshipped line
        self._ino = None  # inode of the file the offset refers to

    def upload_once(self) -> int:
        """Ship all new complete lines since the last call; returns lines
        shipped. Reads from a byte offset in bounded chunks (never the whole
        backlog at once) and holds back a trailing partial line until its
        newline arrives, so tailing a live log neither truncates records nor
        rereads history. A rotated file (new inode) or one that shrank
        (copytruncate / reopen with mode "w") restarts from byte 0 rather
        than silently going quiet."""
        import os

        if not self.log_file_path.exists():
            return 0
        shipped = 0
        with open(self.log_file_path, "rb") as f:
            st = os.fstat(f.fileno())
            if st.st_ino != self._ino or st.st_size < self._offset:
                self._offset = 0
            self._ino = st.st_ino
            f.seek(self._offset)
            while True:
                data = f.read(self.MAX_BYTES_PER_READ)
                end = data.rfind(b"\n") + 1
                if end == 0:
                    if len(data) < self.MAX_BYTES_PER_READ:
                        break  # genuine partial tail — wait for its newline
                    # a single line longer than the read chunk: ship it as a
                    # forced newline-less batch so the offset keeps advancing
                    # (otherwise every later call re-reads this chunk forever)
                    end = len(data)
                self._offset += end
                lines = data[:end].decode(errors="replace").splitlines(keepends=True)
                for start in range(0, len(lines), self.LOG_LINES_PER_UPLOAD):
                    batch = lines[start:start + self.LOG_LINES_PER_UPLOAD]
                    now = time.time()
                    self.messenger.send_message_json(TOPIC_LOGS, json.dumps({
                        "run_id": self.run_id,
                        "edge_id": self.edge_id,
                        "logs": batch,
                        "create_time": now,
                        "update_time": now,
                        "created_by": str(self.edge_id),
                        "updated_by": str(self.edge_id),
                    }))
                    shipped += len(batch)
                if len(data) < self.MAX_BYTES_PER_READ:
                    break
                f.seek(self._offset)  # re-read the held-back partial tail
        return shipped
