"""Fleet telemetry plane: metric registry + per-rank health view
(docs/OBSERVABILITY.md "Fleet telemetry").

The tracer (obs/trace.py) answers *where the time went in one process*; it
says nothing about the FLEET — which clients are slow, how stale the async
fold really runs, what upload latency looks like at p99, which worker went
SLOW → OFFLINE → readmitted and when. The reference ships that signal over
a dedicated MLOps telemetry channel (system metrics over MQTT, SURVEY
§5.5); here it rides the planes this repo already has:

- :class:`MetricRegistry` — a process-wide, thread-safe registry of
  counters (monotonic adds), gauges (last value wins), and log-bucketed
  :class:`Histogram` series, with ATOMIC snapshot and snapshot merge. Same
  install/no-op discipline as ``obs.trace``: the module-level helpers
  (:func:`counter` / :func:`gauge` / :func:`observe`) cost one global read
  and do nothing when no registry is installed, so instrumented hot paths
  are free in ordinary runs.
- :class:`FleetHealth` — the server-side fleet view: per-rank (or per tree
  tier) health records combining what the server observes (state
  transitions, stale uploads, dup absorptions, staleness distribution,
  heartbeat freshness) with the compact telemetry dict clients/edge tiers
  piggyback on ordinary uploads (:data:`fedml_tpu.comm.message.Message.
  MSG_ARG_KEY_TELEMETRY`; :meth:`FleetHealth.merge_report` defines the
  field semantics).

Telemetry is READ-ONLY by contract: it never touches rng, aggregation, or
the protocol state machine, so a run with ``--fleet_stats`` is bit-identical
to the same run without it (tools/fleet_smoke.py holds the contract).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any

from fedml_tpu.obs import jobscope

__all__ = [
    "Histogram", "MetricRegistry", "FleetHealth",
    "install", "uninstall", "get", "enabled",
    "install_job", "uninstall_job", "job_registries", "merged_snapshot",
    "counter", "gauge", "observe", "add_cli_flag",
    "STATE_READMITTED", "FLEET_JSONL_NAME",
]

FLEET_JSONL_NAME = "fleet.jsonl"

# fleet-view state recorded at the readmission boundary — not a wire
# ClientStatus (the tracker flips OFFLINE -> ONLINE); the timeline keeps the
# distinct event so an operator can tell a readmitted worker from one that
# was never excluded
STATE_READMITTED = "READMITTED"


class Histogram:
    """Log-bucketed histogram: bucket ``i`` holds values in
    ``(growth**(i-1), growth**i]`` (so with the default growth of 2 the
    bucket upper bounds are ..., 0.5, 1, 2, 4, ...); non-positive values
    land in a dedicated ``zeros`` bucket (staleness 0, a zero-length wait).
    O(observed magnitude range) memory — a multi-hour latency series costs
    a few dozen buckets, never one entry per sample.

    Snapshots are plain JSON-able dicts; :meth:`merge` folds a snapshot (or
    another histogram) back in, which is what makes fleet records
    aggregatable across ranks and rounds."""

    __slots__ = ("growth", "_log_g", "count", "total", "min", "max",
                 "zeros", "buckets")

    def __init__(self, growth: float = 2.0):
        if growth <= 1.0:
            raise ValueError(f"histogram growth must be > 1, got {growth}")
        self.growth = float(growth)
        self._log_g = math.log(self.growth)
        self.count = 0
        self.total = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self.zeros = 0
        self.buckets: dict[int, int] = {}

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if v <= 0.0:
            self.zeros += 1
            return
        # ceil with a tiny slack so exact powers land in their own bucket
        # (log2(4)/log2(2) == 2.0 -> bucket 2, upper bound 4)
        idx = math.ceil(math.log(v) / self._log_g - 1e-9)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1

    def bound(self, idx: int) -> float:
        """Upper bound of bucket ``idx``."""
        return self.growth ** idx

    def snapshot(self) -> dict:
        return {
            "count": self.count, "sum": self.total,
            "min": self.min, "max": self.max,
            "growth": self.growth, "zeros": self.zeros,
            "buckets": {str(i): n for i, n in sorted(self.buckets.items())},
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "Histogram":
        h = cls(growth=snap.get("growth", 2.0))
        h.merge(snap)
        return h

    def merge(self, other: "Histogram | dict") -> "Histogram":
        snap = other.snapshot() if isinstance(other, Histogram) else other
        if float(snap.get("growth", self.growth)) != self.growth:
            raise ValueError(
                f"cannot merge histograms with different growth factors: "
                f"{self.growth} vs {snap.get('growth')}"
            )
        self.count += int(snap.get("count", 0))
        self.total += float(snap.get("sum", 0.0))
        for name, v in (("min", snap.get("min")), ("max", snap.get("max"))):
            if v is None:
                continue
            cur = getattr(self, name)
            pick = min if name == "min" else max
            setattr(self, name, v if cur is None else pick(cur, float(v)))
        self.zeros += int(snap.get("zeros", 0))
        for i, n in snap.get("buckets", {}).items():
            i = int(i)
            self.buckets[i] = self.buckets.get(i, 0) + int(n)
        return self

    def mean(self) -> float | None:
        return self.total / self.count if self.count else None

    def percentile(self, q: float) -> float | None:
        """Approximate q-quantile (q in [0, 1]): the upper bound of the
        bucket where the cumulative count crosses ``q * count``, clamped to
        the observed [min, max] so outliers don't report a bound the data
        never reached."""
        if not self.count:
            return None
        target = q * self.count
        seen = self.zeros
        if seen >= target:
            return 0.0
        bound = self.max
        for i in sorted(self.buckets):
            seen += self.buckets[i]
            if seen >= target:
                bound = self.bound(i)
                break
        return max(min(float(bound), float(self.max)), float(self.min))


class MetricRegistry:
    """Thread-safe registry of counters, gauges, and histograms.

    One lock guards every series, which is what makes :meth:`snapshot`
    ATOMIC — a snapshot taken while other threads record is a consistent
    point-in-time view, never a half-updated mix. :meth:`merge` folds a
    snapshot back in (counters add, gauges last-wins, histograms merge), so
    registries compose across threads, processes, and wire hops."""

    def __init__(self, growth: float = 2.0):
        self._lock = threading.Lock()
        self._growth = float(growth)
        self._counters: dict[str, float] = {}  # guarded-by: _lock
        self._gauges: dict[str, float] = {}  # guarded-by: _lock
        self._hists: dict[str, Histogram] = {}  # guarded-by: _lock

    def counter(self, name: str, inc: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + inc

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(growth=self._growth)
            h.observe(value)

    def histogram(self, name: str) -> Histogram | None:
        """A COPY of the named histogram (None when never observed)."""
        with self._lock:
            h = self._hists.get(name)
            return Histogram.from_snapshot(h.snapshot()) if h else None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.snapshot()
                               for k, h in self._hists.items()},
            }

    def merge(self, snap: dict) -> None:
        with self._lock:
            for k, v in snap.get("counters", {}).items():
                self._counters[k] = self._counters.get(k, 0) + v
            self._gauges.update(snap.get("gauges", {}))
            for k, hs in snap.get("histograms", {}).items():
                h = self._hists.get(k)
                if h is None:
                    h = self._hists[k] = Histogram(
                        growth=hs.get("growth", self._growth))
                h.merge(hs)

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


# ---------------------------------------------------------------------------
# Process-wide registry + zero-overhead module-level helpers (the
# install/no-op discipline of obs.trace: one global read when disabled).
# With the multi-tenant job plane, installs can additionally be job-scoped
# (obs/jobscope.py): a thread bound to a job resolves that job's registry
# first and falls back to the process one, so N co-scheduled federations
# keep separate metric streams while single-job runs are untouched.
# ---------------------------------------------------------------------------

_registry: MetricRegistry | None = None
_job_store = jobscope.JobStore("registry")


def install(registry: MetricRegistry | None = None) -> MetricRegistry:
    """Install ``registry`` (a fresh one by default) process-wide and return
    it. Replaces any previously-installed registry."""
    global _registry
    _registry = registry if registry is not None else MetricRegistry()
    return _registry


def uninstall() -> MetricRegistry | None:
    """Remove and return the process registry (helpers revert to no-ops)."""
    global _registry
    r, _registry = _registry, None
    return r


def install_job(job: str, registry: MetricRegistry | None = None) -> MetricRegistry:
    """Install a registry scoped to ``job``: threads bound to the job
    (jobscope.bound / jobscope.wrap_target) resolve it ahead of the process
    registry. Used by the tenancy runner so each federation's telemetry
    lands in its own registry."""
    return _job_store.install(
        job, registry if registry is not None else MetricRegistry())


def uninstall_job(job: str) -> MetricRegistry | None:
    return _job_store.uninstall(job)


def job_registries() -> dict[str, MetricRegistry]:
    """Snapshot of the installed job-scoped registries (job -> registry)."""
    return _job_store.installed()


def merged_snapshot() -> dict:
    """Process-level merge view: the process registry's snapshot merged with
    every job-scoped registry's, through the :meth:`MetricRegistry.merge`
    composition seam (counters add, gauges last-wins in sorted job order,
    histograms merge)."""
    merged = MetricRegistry()
    if _registry is not None:
        merged.merge(_registry.snapshot())
    for _job, reg in sorted(_job_store.installed().items()):
        merged.merge(reg.snapshot())
    return merged.snapshot()


def get() -> MetricRegistry | None:
    """The calling thread's job-scoped registry when one is installed, else
    the process registry, else None. Call sites whose metric *values* are
    expensive to compute (timers, byte walks) should guard on this before
    computing them."""
    r = _job_store.lookup()
    return r if r is not None else _registry


def enabled() -> bool:
    return get() is not None


def counter(name: str, inc: float = 1.0) -> None:
    r = get()
    if r is not None:
        r.counter(name, inc)


def gauge(name: str, value: float) -> None:
    r = get()
    if r is not None:
        r.gauge(name, value)


def observe(name: str, value: float) -> None:
    r = get()
    if r is not None:
        r.observe(name, value)


def add_cli_flag(parser):
    """Register the canonical ``--fleet_stats`` flag (one help text for
    every entry point that supports fleet telemetry)."""
    parser.add_argument(
        "--fleet_stats", type=str, default=None,
        help="record per-client fleet telemetry (health registry, latency/"
             "staleness histograms, piggybacked client metrics — docs/"
             "OBSERVABILITY.md 'Fleet telemetry') and write per-round "
             "fleet.jsonl snapshots into this dir (render with "
             "tools/fleet_report.py); read-only, results are unchanged; "
             "message-passing backends only",
    )
    return parser


# ---------------------------------------------------------------------------
# Fleet health view
# ---------------------------------------------------------------------------


class FleetHealth:
    """Per-rank health records, keyed by wire rank (flat server: worker
    rank; tree root: edge-tier rank). Owned by a server manager — unlike the
    process registry this is explicitly server-LOCAL state, because rank
    numbering is fabric-local.

    Each record carries the rank's current ``state`` plus a bounded
    transition timeline (``[(t_seconds, state), ...]``, consecutive
    duplicates deduped — heartbeats refresh liveness without growing it),
    counters, gauges, and histograms. :meth:`merge_report` folds the compact
    telemetry dict a client/edge piggybacked on an upload
    (docs/OBSERVABILITY.md "Fleet telemetry" documents the wire fields)."""

    MAX_TIMELINE = 1024  # per-rank transition ring; oldest entries dropped

    def __init__(self, growth: float = 2.0):
        self._lock = threading.Lock()
        self._growth = float(growth)
        self._t0 = time.monotonic()
        self._ranks: dict[int, dict] = {}  # guarded-by: _lock

    def _rec(self, rank: int) -> dict:  # lock-held: _lock
        rec = self._ranks.get(rank)
        if rec is None:
            rec = self._ranks[rank] = {
                "state": None, "timeline": [], "timeline_dropped": 0,
                "counters": {}, "gauges": {}, "hists": {},
            }
        return rec

    def record_state(self, rank: int, state: str) -> None:
        """Record a health-state transition (consecutive duplicates are
        deduped; the timeline is a bounded ring)."""
        t = time.monotonic() - self._t0
        with self._lock:
            rec = self._rec(int(rank))
            if rec["state"] == state:
                return
            rec["state"] = state
            tl = rec["timeline"]
            tl.append((round(t, 4), str(state)))
            if len(tl) > self.MAX_TIMELINE:
                del tl[0]
                rec["timeline_dropped"] += 1

    def state(self, rank: int) -> str | None:
        with self._lock:
            rec = self._ranks.get(int(rank))
            return rec["state"] if rec else None

    def timeline(self, rank: int) -> list[tuple[float, str]]:
        with self._lock:
            rec = self._ranks.get(int(rank))
            return list(rec["timeline"]) if rec else []

    def counter(self, rank: int, name: str, inc: float = 1.0) -> None:
        with self._lock:
            c = self._rec(int(rank))["counters"]
            c[name] = c.get(name, 0) + inc

    def gauge(self, rank: int, name: str, value: float) -> None:
        with self._lock:
            self._rec(int(rank))["gauges"][name] = value

    def observe(self, rank: int, name: str, value: float) -> None:
        with self._lock:
            hists = self._rec(int(rank))["hists"]
            h = hists.get(name)
            if h is None:
                h = hists[name] = Histogram(growth=self._growth)
            h.observe(value)

    def merge_report(self, rank: int, report: dict | None,
                     now: float | None = None) -> None:
        """Fold one piggybacked telemetry dict into the rank's record. Wire
        fields (all optional — absent fields cost nothing):

        - ``sent_at``: sender's ``time.time()`` at send → an ``upload_ms``
          histogram sample (receive minus send; clock-skew-honest only
          within one host, which is where the latency question is asked)
        - ``step_ms``: sender-side local compute wall ms → histogram
        - ``retries``: the sender manager's cumulative retry count → gauge
          (cumulative at source, so last-wins, never summed)
        - ``counts``: ``{name: cumulative_value}`` sender-side totals (edge
          tiers report fold/discard/stale/dup counts here) → gauges
        """
        if not report:
            return
        rank = int(rank)
        sent = report.get("sent_at")
        if sent is not None:
            t = time.time() if now is None else now
            self.observe(rank, "upload_ms",
                         max(t - float(sent), 0.0) * 1e3)
        step = report.get("step_ms")
        if step is not None:
            self.observe(rank, "step_ms", float(step))
        retries = report.get("retries")
        if retries is not None:
            self.gauge(rank, "retries", float(retries))
        for name, v in (report.get("counts") or {}).items():
            self.gauge(rank, str(name), float(v))

    def ranks(self) -> list[int]:
        with self._lock:
            return sorted(self._ranks)

    def snapshot(self) -> dict:
        """Atomic point-in-time view: ``{"ranks": {rank: record}}`` with
        histogram snapshots inlined — plain JSON-able data."""
        with self._lock:
            return {"ranks": {
                str(rank): {
                    "state": rec["state"],
                    "timeline": [list(e) for e in rec["timeline"]],
                    "timeline_dropped": rec["timeline_dropped"],
                    "counters": dict(rec["counters"]),
                    "gauges": dict(rec["gauges"]),
                    "histograms": {k: h.snapshot()
                                   for k, h in rec["hists"].items()},
                }
                for rank, rec in sorted(self._ranks.items())
            }}

    def round_record(self, round_idx: int, extra: dict | None = None) -> dict:
        """One JSONL fleet snapshot line: the cumulative fleet view stamped
        with the round (sync) / emitted-version (async) index."""
        rec: dict[str, Any] = {"round": int(round_idx), **self.snapshot()}
        if extra:
            rec.update(extra)
        return rec

    def merged_histogram(self, name: str) -> Histogram | None:
        """The named histogram merged across every rank (the fleet-wide
        distribution a report renders), or None if no rank observed it."""
        out: Histogram | None = None
        with self._lock:
            for rec in self._ranks.values():
                h = rec["hists"].get(name)
                if h is None:
                    continue
                if out is None:
                    out = Histogram(growth=h.growth)
                out.merge(h.snapshot())
        return out
