"""MLOps packaging: build distributable client/server run packages.

Reference: build-mlops-package/build.sh — copies fedml_api/fedml_core/
fedml_experiments into ``mlops-core/fedml-{client,server}/package/fedml``
and zips each into ``dist-packages/{client,server}/package.zip`` for upload
to the MLOps platform.

Same artifact contract here, pythonic implementation: the whole
``fedml_tpu`` package plus a role entry script and a build manifest go into
each zip. ``verify_package`` round-trips a built zip (unzip + import-check
via compileall) so CI can prove the artifact is runnable without a
platform."""

from __future__ import annotations

import compileall
import json
import time
import zipfile
from pathlib import Path

EXCLUDE_DIRS = {"__pycache__", ".git", "tests"}

_CLIENT_ENTRY = '''\
"""MLOps client-package entry: run one federated client against the server
in the bundled config (reference mlops-core client runner role)."""
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

from fedml_tpu.exp.main_fedavg import main

if __name__ == "__main__":
    cfg = json.loads((Path(__file__).parent / "fedml_config.json").read_text())
    main(cfg["client_args"] + sys.argv[1:])
'''

_SERVER_ENTRY = '''\
"""MLOps server-package entry: run the aggregation server for the bundled
config (reference mlops-core server runner role)."""
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

from fedml_tpu.exp.main_fedavg import main

if __name__ == "__main__":
    cfg = json.loads((Path(__file__).parent / "fedml_config.json").read_text())
    main(cfg["server_args"] + sys.argv[1:])
'''


def _package_files(src_root: Path):
    for p in sorted((src_root / "fedml_tpu").rglob("*")):
        if p.is_dir():
            continue
        rel = p.relative_to(src_root)
        if any(part in EXCLUDE_DIRS for part in rel.parts):
            continue
        if p.name.endswith((".pyc", ".so.tmp")):
            continue
        yield p


def build_mlops_package(
    src_root: str | Path,
    out_dir: str | Path,
    run_config: dict | None = None,
) -> dict[str, Path]:
    """Build ``dist-packages/{client,server}/package.zip``; returns the two
    zip paths. ``run_config`` may carry ``client_args`` / ``server_args``
    CLI argument lists baked into each package's fedml_config.json."""
    src_root = Path(src_root)
    out = Path(out_dir)
    run_config = run_config or {}
    manifest = {
        "framework": "fedml_tpu",
        "built_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "entry": "run.py",
    }
    results: dict[str, Path] = {}
    for role, entry_src in (("client", _CLIENT_ENTRY), ("server", _SERVER_ENTRY)):
        zip_path = out / "dist-packages" / role / "package.zip"
        zip_path.parent.mkdir(parents=True, exist_ok=True)
        cfg = {
            "role": role,
            "client_args": run_config.get("client_args", []),
            "server_args": run_config.get("server_args", []),
        }
        with zipfile.ZipFile(zip_path, "w", zipfile.ZIP_DEFLATED) as z:
            for f in _package_files(src_root):
                z.write(f, Path("package") / f.relative_to(src_root))
            z.writestr("package/run.py", entry_src)
            z.writestr("package/fedml_config.json", json.dumps(cfg, indent=2))
            z.writestr("package/manifest.json", json.dumps({**manifest, "role": role}, indent=2))
        results[role] = zip_path
    return results


def verify_package(zip_path: str | Path, work_dir: str | Path) -> bool:
    """Unzip and byte-compile the package — proves the artifact is complete
    and syntactically runnable (CI-checkable without an MLOps platform)."""
    work = Path(work_dir)
    with zipfile.ZipFile(zip_path) as z:
        z.extractall(work)
    pkg = work / "package"
    assert (pkg / "run.py").exists() and (pkg / "manifest.json").exists()
    return compileall.compile_dir(str(pkg / "fedml_tpu"), quiet=2, force=True)
