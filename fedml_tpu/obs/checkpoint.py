"""Round checkpointing with orbax.

The reference has essentially no FL-round checkpoint/resume (SURVEY §5.4 —
only pretrained model files and wandb history). This is a first-class feature
here: the tuple (global variables, server/aggregator state, round index,
metric history) is saved every N rounds and training resumes exactly.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any

import jax
import numpy as np


def _np_tree(tree):
    return jax.tree.map(np.asarray, tree)


def save_params(path: str | Path, variables: Any) -> Path:
    """Save model variables (or any array pytree of nested dicts) as a single
    portable ``.npz`` keyed by '/'-joined key paths.

    The warm-start half of the reference's pretrained-checkpoint story
    (fedml_api/model/cv/resnet.py:202-224 loads
    ``cv/pretrained/*/resnet56/checkpoint.pth``): any zoo model's params can
    be saved once and loaded into a fresh run via ``--init_from``.
    """
    path = Path(path)
    if path.suffix != ".npz":
        # np.savez would silently append .npz, making the returned (and
        # --init_from'd) path not exist; normalize up front instead
        path = path.with_name(path.name + ".npz")
    path.parent.mkdir(parents=True, exist_ok=True)
    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}/{k}" if prefix else str(k), v)
        else:
            flat[prefix] = np.asarray(node)

    walk("", dict(variables))
    if not flat:
        raise ValueError("save_params: empty variables pytree")
    np.savez(path, **flat)
    return path


def load_params(path: str | Path, like: Any = None) -> Any:
    """Load a ``save_params`` file back into a nested dict.

    With ``like`` (a template pytree), every loaded leaf must exist in the
    template with the same shape — loudly catching a checkpoint/model
    mismatch — and the result keeps exactly the template's structure with
    loaded leaves grafted in (missing leaves keep the template's values, so a
    backbone-only file warm-starts a model with a fresh head).
    """
    blob = np.load(Path(path))
    out: dict = {}
    for key in blob.files:
        node = out
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = blob[key]
    if like is None:
        return out
    return graft_params(dict(like), out)


def graft_params(template: Any, loaded: Any, prefix: str = "") -> Any:
    """Graft ``loaded`` leaves over ``template`` (shape-checked; loaded dict
    keys must exist in the template; template leaves absent from ``loaded``
    keep their — e.g. freshly initialized — values)."""
    if not isinstance(loaded, dict):
        tmpl_arr = np.asarray(template)
        loaded = np.asarray(loaded)
        if tmpl_arr.shape != loaded.shape:
            raise ValueError(
                f"load_params: {prefix or 'root'} shape {loaded.shape} does "
                f"not match model {tmpl_arr.shape}"
            )
        return loaded.astype(tmpl_arr.dtype)
    if not isinstance(template, dict):
        raise ValueError(f"load_params: {prefix or 'root'} is a dict in the "
                         "file but a leaf in the model")
    unknown = set(loaded) - set(template)
    if unknown:
        raise ValueError(
            f"load_params: keys {sorted(unknown)} under {prefix or 'root'} "
            f"not present in the model (has {sorted(template)})"
        )
    return {
        k: graft_params(template[k], loaded[k], f"{prefix}/{k}" if prefix else k)
        if k in loaded else template[k]
        for k in template
    }


class RoundCheckpointer:
    """Orbax-backed checkpointer; falls back to .npz pytree dumps if orbax is
    unavailable. Layout: <dir>/round_<k>/ with state + meta.json."""

    def __init__(self, ckpt_dir: str | Path, keep: int = 3):
        self.dir = Path(ckpt_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.keep = keep
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self._ckptr = ocp.PyTreeCheckpointer()
        except Exception:  # pragma: no cover
            self._ocp = None
            self._ckptr = None

    def save(self, round_idx: int, variables: Any, server_state: Any = None,
             history: list | None = None) -> Path:
        path = self.dir / f"round_{round_idx:06d}"
        payload = {"variables": _np_tree(variables)}
        if server_state is not None and jax.tree_util.tree_leaves(server_state):
            payload["server_state"] = _np_tree(server_state)
        if self._ckptr is not None:
            self._ckptr.save((path / "state").absolute(), payload, force=True)
        else:
            leaves, treedef = jax.tree_util.tree_flatten(payload)
            np.savez(path / "state.npz", *leaves)
        with open(path / "meta.json", "w") as fh:
            json.dump({"round": round_idx, "history": history or []}, fh)
        self._gc()
        return path

    def latest_round(self) -> int | None:
        rounds = sorted(
            int(p.name.split("_")[1]) for p in self.dir.glob("round_*") if (p / "meta.json").exists()
        )
        return rounds[-1] if rounds else None

    def restore(self, like_variables: Any, round_idx: int | None = None,
                like_server_state: Any = None):
        """Returns (variables, server_state, round_idx, history)."""
        if round_idx is None:
            round_idx = self.latest_round()
        if round_idx is None:
            raise FileNotFoundError(f"no checkpoints under {self.dir}")
        path = self.dir / f"round_{round_idx:06d}"
        template = {"variables": _np_tree(like_variables)}
        has_server = like_server_state is not None and jax.tree_util.tree_leaves(like_server_state)
        if has_server:
            template["server_state"] = _np_tree(like_server_state)
        if self._ckptr is not None:
            payload = self._ckptr.restore((path / "state").absolute(), item=template)
        else:
            blob = np.load(path / "state.npz")
            leaves = [blob[k] for k in blob.files]
            payload = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(template), leaves
            )
        with open(path / "meta.json") as fh:
            meta = json.load(fh)
        server_state = payload.get("server_state", like_server_state)
        return payload["variables"], server_state, meta["round"], meta.get("history", [])

    def _gc(self):
        rounds = sorted(self.dir.glob("round_*"), key=lambda p: p.name)
        for p in rounds[: -self.keep]:
            import shutil

            shutil.rmtree(p, ignore_errors=True)
            logging.debug("checkpoint gc: removed %s", p)

    # -- distributed-server snapshots (docs/ROBUSTNESS.md "Failure recovery")

    # The message-passing server's round state is a nested dict mixing
    # numpy arrays (global flat model, streaming-accumulator tally,
    # reservoir stacks) with JSON-safe scalars/tables (round index, weight
    # sum, miss counts, status table). Arrays land in one .npz keyed by
    # '/'-joined paths; everything else lands in a .json written LAST — its
    # presence is the commit marker, so a crash DURING a save can never
    # yield a half-readable snapshot (restore only ever sees committed
    # rounds).

    def _server_paths(self, round_idx: int) -> tuple[Path, Path]:
        stem = self.dir / f"server_round_{round_idx:06d}"
        return stem.with_suffix(".npz"), stem.with_suffix(".json")

    def save_server(self, round_idx: int, state: dict) -> Path:
        """Save a distributed-server round snapshot (atomic at the .json
        commit marker). ``state`` is a nested dict of np.ndarray leaves and
        JSON-safe values."""
        arrays: dict[str, np.ndarray] = {}

        def strip(node, prefix: str):
            if isinstance(node, dict):
                return {k: strip(v, f"{prefix}/{k}" if prefix else str(k))
                        for k, v in node.items()}
            if isinstance(node, np.ndarray):
                arrays[prefix] = node
                return {"__array__": prefix}
            return node

        meta = strip(state, "")
        npz_path, json_path = self._server_paths(round_idx)
        if arrays:
            np.savez(npz_path, **arrays)
        # the .json is the commit marker, so its own write must be atomic:
        # dump to a temp file and rename into place — a crash mid-dump
        # leaves no half-readable marker for restore to trip on
        tmp = json_path.with_suffix(".json.tmp")
        with open(tmp, "w") as fh:
            json.dump({"round": round_idx, "state": meta,
                       "has_arrays": bool(arrays)}, fh)
        tmp.replace(json_path)
        self._gc_server()
        return json_path

    def latest_server_round(self) -> int | None:
        rounds = sorted(
            int(p.stem.split("_")[-1])
            for p in self.dir.glob("server_round_*.json")
        )
        return rounds[-1] if rounds else None

    def restore_server(self, round_idx: int | None = None) -> dict:
        """Load a server snapshot (latest committed round by default) back
        into the nested dict :meth:`save_server` was given."""
        if round_idx is None:
            round_idx = self.latest_server_round()
        if round_idx is None:
            raise FileNotFoundError(f"no server checkpoints under {self.dir}")
        npz_path, json_path = self._server_paths(round_idx)
        with open(json_path) as fh:
            payload = json.load(fh)
        blob = np.load(npz_path) if payload.get("has_arrays") else None

        def graft(node):
            if isinstance(node, dict):
                if set(node) == {"__array__"}:
                    return blob[node["__array__"]]
                return {k: graft(v) for k, v in node.items()}
            return node

        return graft(payload["state"])

    def _gc_server(self):
        rounds = sorted(self.dir.glob("server_round_*.json"))
        for json_path in rounds[: -self.keep]:
            json_path.with_suffix(".npz").unlink(missing_ok=True)
            json_path.unlink(missing_ok=True)
            logging.debug("checkpoint gc: removed %s", json_path.stem)
