"""Round checkpointing with orbax.

The reference has essentially no FL-round checkpoint/resume (SURVEY §5.4 —
only pretrained model files and wandb history). This is a first-class feature
here: the tuple (global variables, server/aggregator state, round index,
metric history) is saved every N rounds and training resumes exactly.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any

import jax
import numpy as np


def _np_tree(tree):
    return jax.tree.map(np.asarray, tree)


class RoundCheckpointer:
    """Orbax-backed checkpointer; falls back to .npz pytree dumps if orbax is
    unavailable. Layout: <dir>/round_<k>/ with state + meta.json."""

    def __init__(self, ckpt_dir: str | Path, keep: int = 3):
        self.dir = Path(ckpt_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.keep = keep
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self._ckptr = ocp.PyTreeCheckpointer()
        except Exception:  # pragma: no cover
            self._ocp = None
            self._ckptr = None

    def save(self, round_idx: int, variables: Any, server_state: Any = None,
             history: list | None = None) -> Path:
        path = self.dir / f"round_{round_idx:06d}"
        payload = {"variables": _np_tree(variables)}
        if server_state is not None and jax.tree_util.tree_leaves(server_state):
            payload["server_state"] = _np_tree(server_state)
        if self._ckptr is not None:
            self._ckptr.save((path / "state").absolute(), payload, force=True)
        else:
            leaves, treedef = jax.tree_util.tree_flatten(payload)
            np.savez(path / "state.npz", *leaves)
        with open(path / "meta.json", "w") as fh:
            json.dump({"round": round_idx, "history": history or []}, fh)
        self._gc()
        return path

    def latest_round(self) -> int | None:
        rounds = sorted(
            int(p.name.split("_")[1]) for p in self.dir.glob("round_*") if (p / "meta.json").exists()
        )
        return rounds[-1] if rounds else None

    def restore(self, like_variables: Any, round_idx: int | None = None,
                like_server_state: Any = None):
        """Returns (variables, server_state, round_idx, history)."""
        if round_idx is None:
            round_idx = self.latest_round()
        if round_idx is None:
            raise FileNotFoundError(f"no checkpoints under {self.dir}")
        path = self.dir / f"round_{round_idx:06d}"
        template = {"variables": _np_tree(like_variables)}
        has_server = like_server_state is not None and jax.tree_util.tree_leaves(like_server_state)
        if has_server:
            template["server_state"] = _np_tree(like_server_state)
        if self._ckptr is not None:
            payload = self._ckptr.restore((path / "state").absolute(), item=template)
        else:
            blob = np.load(path / "state.npz")
            leaves = [blob[k] for k in blob.files]
            payload = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(template), leaves
            )
        with open(path / "meta.json") as fh:
            meta = json.load(fh)
        server_state = payload.get("server_state", like_server_state)
        return payload["variables"], server_state, meta["round"], meta.get("history", [])

    def _gc(self):
        rounds = sorted(self.dir.glob("round_*"), key=lambda p: p.name)
        for p in rounds[: -self.keep]:
            import shutil

            shutil.rmtree(p, ignore_errors=True)
            logging.debug("checkpoint gc: removed %s", p)
