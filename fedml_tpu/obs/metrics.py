"""Metrics & logging.

Reference channels (SURVEY §5.5): python logging with per-process format
(fedml_api/utils/logger.py:7), wandb learning curves keyed Train/Acc,
Train/Loss, Test/Acc, Test/Loss by round (FedAVGAggregator.py:137-163), MLOps
MQTT telemetry (fedml_core/mlops_logger.py). Here: one MetricsLogger with the
same wandb key names, writing JSONL locally and forwarding to wandb when
available; MLOps-style system metrics come from obs.sysstats.
"""

from __future__ import annotations

import json
import logging
import os
import time
from pathlib import Path
from typing import Any

from fedml_tpu.obs import trace


# Canonical bytes-on-wire metric keys (compress subsystem): actual bytes
# that crossed (or would cross) the transport vs the dense-f32 equivalent,
# per round. Emitted by the sim engine's compressed aggregator and the
# message-passing FedAvg server so compression ratio shows up in the same
# metrics stream as Train/Acc (docs/COMPRESSION.md).
COMM_UPLINK_BYTES = "Comm/UplinkBytes"
COMM_UPLINK_DENSE_BYTES = "Comm/UplinkDenseBytes"
COMM_DOWNLINK_BYTES = "Comm/DownlinkBytes"
COMM_DOWNLINK_DENSE_BYTES = "Comm/DownlinkDenseBytes"
COMM_RATIO = "Comm/CompressionRatio"
COMM_DOWNLINK_RATIO = "Comm/DownlinkCompressionRatio"
# Downlink delta coding (compress/downlink.py, docs/COMPRESSION.md
# "Downlink delta coding"): how many receivers were served a dense
# keyframe this round (vs an encoded delta chain). With the plane armed,
# DownlinkBytes measures the ENCODED payloads actually on the wire
# (chain blob + descriptor), so DownlinkCompressionRatio is real, not
# the dense/dense identity it was before the plane existed.
COMM_DOWNLINK_KEYFRAMES = "Comm/DownlinkKeyframes"

# ratio keys are derived, not additive — totals() must never sum them
_RATIO_KEYS = (COMM_RATIO, COMM_DOWNLINK_RATIO)

# Interior (tier-to-tier) uplink bytes in tree mode (async_agg/tree.py,
# docs/PERFORMANCE.md "Barrier-free aggregation"): actual bytes each edge
# tier's partial put on the wire toward its parent vs the raw-f64
# accumulator equivalent. With the tier uplink codec armed the partial
# ships as an EncodedUpdate (delta framing against the round global), so
# the ratio measures real interior-bandwidth savings; without a codec the
# two are equal. Summed over every edge into tier_stats/comm_stats totals
# by run_tree_fedavg and the cascade harness.
COMM_TIER_UPLINK_BYTES = "Comm/TierUplinkBytes"
COMM_TIER_UPLINK_DENSE_BYTES = "Comm/TierUplinkDenseBytes"

# retry/backoff send plane (comm/retry.py, docs/ROBUSTNESS.md "Failure
# recovery"): how many send attempts were re-tried after a transient
# failure over the whole run. Emitted into comm_stats totals by
# run_distributed_fedavg when a RetryPolicy is armed.
COMM_RETRY_COUNT = "Comm/RetryCount"

# Stale uploads at the synchronous server (docs/PERFORMANCE.md
# "Barrier-free aggregation"): a straggler's model from an already-closed
# round that the sync round protocol must discard (the async server folds
# these with a staleness weight instead). Emitted into comm_stats totals by
# run_distributed_fedavg — the observability baseline async staleness
# weighting builds on.
COMM_STALE_UPLOADS = "Comm/StaleUploads"

# Async / barrier-free server keys (docs/PERFORMANCE.md "Barrier-free
# aggregation"): per-emission-window fold counts from the buffered-async
# tally (async_agg.AsyncFedAggregator). Arrivals is the number of uploads
# folded into the emitted model (== buffer_goal), StaleFolds how many of
# them trained an older model version (folded with the staleness weight,
# never dropped), DuplicateUploads how many replayed (sender, version)
# pairs the idempotence guard absorbed, MeanStaleness the mean version lag
# over the window's folds. ModelsEmitted rides the run totals.
ASYNC_ARRIVALS = "Async/Arrivals"
ASYNC_STALE_FOLDS = "Async/StaleFolds"
ASYNC_DUP_UPLOADS = "Async/DuplicateUploads"
ASYNC_MEAN_STALENESS = "Async/MeanStaleness"
ASYNC_MODELS_EMITTED = "Async/ModelsEmitted"

# Robust-aggregation defense keys (docs/ROBUSTNESS.md): per-round mean
# pre-clip update norm, fraction of the cohort whose delta got clipped, and
# how many client updates the combine rule discarded (krum keeps one,
# trimmed mean drops 2k, non-finite wire uploads are rejected). Emitted by
# the sim engine's robust_aggregator and the message-passing
# RobustDistAggregator so both defense paths land in one metrics stream.
ROBUST_UPDATE_NORM = "Robust/UpdateNorm"
ROBUST_CLIP_FRACTION = "Robust/ClipFraction"
ROBUST_FILTERED = "Robust/FilteredClients"

# Multi-tenant job plane keys (fedml_tpu/tenancy/, docs/MULTITENANCY.md):
# per-job accounting when N federations share one wire, one send pool, and
# one scheduler. SendBytes/SendLegs/SchedulerTurns are emitted by the fair
# fan-out scheduler's per-job stats (tenancy/scheduler.py — bytes actually
# dispatched for the job, individual send legs, and deficit-round-robin
# visits that dispatched work); Rounds/Errors ride each job's totals from
# the tenancy runner (rounds that closed, 1 if the job died with a captured
# exception). All land in per-job ``totals`` (jobs.json) and, when a
# job-scoped registry is installed, in that job's metric stream.
JOB_SEND_BYTES = "Job/SendBytes"
JOB_SEND_LEGS = "Job/SendLegs"
JOB_SCHED_TURNS = "Job/SchedulerTurns"
JOB_ROUNDS = "Job/Rounds"
JOB_ERRORS = "Job/Errors"

# Sharded fold plane keys (algorithms/fold_plane.py, docs/PERFORMANCE.md
# "The server fold plane"): QueueDepth is the gauge of uploads submitted to
# the chunk workers and not yet fully folded (sampled at each enqueue, after
# the plane condition is released); StallMs is the histogram of wall time a
# quiesce point (aggregate / emit / snapshot / export) spent draining the
# queues — how much fold debt the barrier actually paid. Rendered by
# tools/fleet_report.py from the run's registry snapshot.
FOLD_QUEUE_DEPTH = "Fold/QueueDepth"
FOLD_STALL_MS = "Fold/StallMs"


class CommBytesAccountant:
    """Per-round uplink/downlink byte ledger for the message-passing path.

    The sim engine computes these inside the round program (shapes are
    static); the wire path counts real payload sizes here instead — one
    ``record_*`` call per message, ``round_record`` to flush a round's
    totals into the metrics stream under the canonical keys."""

    def __init__(self):
        import threading

        # record_* runs on the server's receive thread; round_record can run
        # on the straggler-timeout timer thread (fedavg_distributed
        # _round_timed_out -> _complete_round) — counters need the lock or
        # an interleaved read-add-store loses straggler bytes
        self._lock = threading.Lock()
        self.rounds: list[dict] = []  # guarded-by: _lock
        self._up = 0  # guarded-by: _lock
        self._up_dense = 0  # guarded-by: _lock
        self._down = 0  # guarded-by: _lock
        self._down_dense = 0  # guarded-by: _lock
        self._keyframes = 0  # guarded-by: _lock

    def record_uplink(self, actual: int, dense: int) -> None:
        with self._lock:
            self._up += int(actual)
            self._up_dense += int(dense)

    def record_downlink(self, actual: int, dense: int) -> None:
        with self._lock:
            self._down += int(actual)
            self._down_dense += int(dense)

    def record_keyframes(self, count: int = 1) -> None:
        """Receivers served a dense keyframe instead of a delta chain
        (downlink delta plane only — the key is emitted only when the
        counter moved, so pre-downlink records are unchanged)."""
        with self._lock:
            self._keyframes += int(count)

    def round_record(self, round_idx: int) -> dict:
        with self._lock:
            rec = {
                "round": round_idx,
                COMM_UPLINK_BYTES: self._up,
                COMM_UPLINK_DENSE_BYTES: self._up_dense,
                COMM_DOWNLINK_BYTES: self._down,
                COMM_DOWNLINK_DENSE_BYTES: self._down_dense,
            }
            if self._up:
                rec[COMM_RATIO] = self._up_dense / self._up
            if self._down:
                rec[COMM_DOWNLINK_RATIO] = self._down_dense / self._down
            if self._keyframes:
                rec[COMM_DOWNLINK_KEYFRAMES] = self._keyframes
            self.rounds.append(rec)
            self._up = self._up_dense = self._down = self._down_dense = 0
            self._keyframes = 0
            return rec

    def totals(self) -> dict:
        out: dict = {}
        # include traffic recorded since the last round flush (e.g. the
        # final stop broadcast, which lands after the last round_record)
        with self._lock:
            pending = {
                COMM_UPLINK_BYTES: self._up,
                COMM_UPLINK_DENSE_BYTES: self._up_dense,
                COMM_DOWNLINK_BYTES: self._down,
                COMM_DOWNLINK_DENSE_BYTES: self._down_dense,
            }
            if self._keyframes:
                pending[COMM_DOWNLINK_KEYFRAMES] = self._keyframes
            rounds = list(self.rounds)
        for rec in rounds + [pending]:
            for k, v in rec.items():
                if k.startswith("Comm/") and k not in _RATIO_KEYS:
                    out[k] = out.get(k, 0) + v
        if out.get(COMM_UPLINK_BYTES):
            out[COMM_RATIO] = (
                out[COMM_UPLINK_DENSE_BYTES] / out[COMM_UPLINK_BYTES]
            )
        if out.get(COMM_DOWNLINK_BYTES):
            out[COMM_DOWNLINK_RATIO] = (
                out[COMM_DOWNLINK_DENSE_BYTES] / out[COMM_DOWNLINK_BYTES]
            )
        return out


def logging_config(process_id: int = 0, level=logging.INFO) -> None:
    """Per-process log format (fedml_api/utils/logger.py:7-32)."""
    logging.basicConfig(
        level=level,
        format=f"%(asctime)s [{process_id}] %(filename)s[%(lineno)d] %(levelname)s: %(message)s",
        force=True,
    )

class MetricsLogger:
    """wandb-key-compatible metric sink (Train/Acc, Test/Acc, ... by round).

    Usable as a context manager — the JSONL handle is closed even when the
    run body raises. ``close()`` is idempotent; ``log()`` after close raises
    instead of writing to a closed handle."""

    def __init__(self, run_dir: str | Path | None = None, use_wandb: bool = False,
                 wandb_kwargs: dict | None = None):
        self.run_dir = Path(run_dir) if run_dir else None
        self._fh = None
        self._closed = False
        if self.run_dir:
            self.run_dir.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.run_dir / "metrics.jsonl", "a")
        self._wandb = None
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb
                wandb.init(**(wandb_kwargs or {}))
            except Exception as e:  # wandb optional, never fatal
                logging.warning("wandb unavailable: %s", e)
        self.history: list[dict[str, Any]] = []

    def log(self, metrics: dict[str, Any], round_idx: int | None = None) -> None:
        if self._closed:
            raise RuntimeError(
                "MetricsLogger.log() after close(): the JSONL sink is gone; "
                "records logged here would be silently lost"
            )
        rec = dict(metrics)
        if round_idx is not None:
            rec["round"] = round_idx
        rec["_ts"] = time.time()
        self.history.append(rec)
        if self._fh:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        if self._wandb:
            self._wandb.log({k: v for k, v in rec.items() if not k.startswith("_")})

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._fh:
            self._fh.close()
            self._fh = None
        if self._wandb:
            self._wandb.finish()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RoundTimer:
    """Comm/compute tick-tock instrumentation (reference fedml_core/
    distributed/communication/utils.py:6-18 log_communication_tick/tock,
    log_round_start/end) — wall-clock spans keyed by tag.

    Every ``tock`` also lands the span in the process tracer's stream
    (obs/trace.py) when one is installed, so tick/tock call sites show up on
    the same Perfetto timeline as the engine/prefetch/comm spans."""

    def __init__(self, tracer=None):
        # explicit tracer wins; default resolves the process tracer at tock
        # time (so a timer built before trace.install() still exports)
        self._tracer = tracer
        self._open: dict[str, float] = {}
        self.spans: list[tuple[str, float]] = []

    def tick(self, tag: str) -> None:
        self._open[tag] = time.perf_counter()

    def tock(self, tag: str) -> float:
        if tag not in self._open:
            raise ValueError(
                f"RoundTimer.tock({tag!r}) without a matching tick; "
                f"currently open tags: {sorted(self._open) or 'none'}"
            )
        t0 = self._open.pop(tag)
        t1 = time.perf_counter()
        dt = t1 - t0
        self.spans.append((tag, dt))
        tracer = self._tracer if self._tracer is not None else trace.get()
        if tracer is not None:
            tracer.add_span(tag, t0, t1)
        logging.debug("--- %s cost: %.4fs", tag, dt)
        return dt

    def summary(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for tag, dt in self.spans:
            out[tag] = out.get(tag, 0.0) + dt
        return out
