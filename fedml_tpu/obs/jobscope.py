"""Thread-bound job scoping for the process-global observability installs.

The multi-tenant job plane (fedml_tpu/tenancy/, docs/MULTITENANCY.md) runs N
federations in one process, but obs.registry / obs.trace expose ONE
process-global install each — every job's telemetry would land in one shared
sink. This module is the scoping seam both facilities share: a process-wide
``thread -> job`` binding (a job's server loop, client threads, and timer
callbacks all bind to the job that spawned them) plus a per-facility
``job -> installed object`` store. ``registry.get()`` / ``trace.get()``
consult the calling thread's binding first and fall back to the process
install, so:

- single-job runs are untouched (no bindings, one dict-emptiness check on
  the hot path);
- a job's telemetry lands in ITS registry/tracer regardless of which of its
  threads emitted it;
- the process-level merge view (``registry.merged_snapshot()``) composes the
  per-job registries through the PR 10 ``MetricRegistry.merge`` seam.

Bindings are plain thread-ident dict entries, not contextvars: the wire
runtime spawns threads from many places (client run loops, heartbeats,
round-timeout timers, send pools) and contextvars do not cross
``threading.Thread`` — :func:`wrap_target` is the explicit inheritance
point the spawn sites use.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

_lock = threading.Lock()
# thread ident -> job name. Written under _lock; read lock-free on the
# instrumentation hot path (a CPython dict read is atomic, and a stale read
# only mis-scopes the first records of a just-(un)bound thread).
_thread_jobs: dict[int, str] = {}


def current_job() -> str | None:
    """The job the calling thread is bound to, or None (process scope)."""
    return _thread_jobs.get(threading.get_ident())


def bind_thread(job: str) -> None:
    """Bind the calling thread to ``job`` until unbound (prefer :class:`bound`
    or :func:`wrap_target`, which restore the previous binding)."""
    with _lock:
        _thread_jobs[threading.get_ident()] = job


def unbind_thread() -> None:
    with _lock:
        _thread_jobs.pop(threading.get_ident(), None)


class bound:
    """Context manager: bind the calling thread to ``job`` for the block,
    restoring the previous binding (usually none) on exit. ``job=None`` is a
    no-op so call sites can pass an optional job straight through."""

    def __init__(self, job: str | None):
        self._job = job
        self._prev: str | None = None

    def __enter__(self) -> "bound":
        if self._job is not None:
            self._prev = current_job()
            bind_thread(self._job)
        return self

    def __exit__(self, *exc) -> None:
        if self._job is None:
            return
        if self._prev is None:
            unbind_thread()
        else:
            bind_thread(self._prev)


def wrap_target(target: Callable, job: str | None = None) -> Callable:
    """Thread-entry inheritance point: wrap a ``threading.Thread`` /
    ``threading.Timer`` target so the new thread runs bound to ``job``
    (default: the SPAWNING thread's binding at wrap time). Returns ``target``
    unchanged when there is no job to inherit — zero overhead for every
    single-job run."""
    job = current_job() if job is None else job
    if job is None:
        return target

    def run(*args: Any, **kwargs: Any):
        with bound(job):
            return target(*args, **kwargs)

    return run


class JobStore:
    """Per-facility ``job -> installed object`` store (one for the metric
    registries, one for the tracers). Lookup is hot-path: one emptiness
    check when no jobs are installed."""

    def __init__(self, facility: str):
        self.facility = facility
        self._lock = threading.Lock()
        # written under _lock; read lock-free from lookup()
        self._objects: dict[str, Any] = {}

    def install(self, job: str, obj: Any) -> Any:
        with self._lock:
            self._objects[job] = obj
        return obj

    def uninstall(self, job: str) -> Any | None:
        with self._lock:
            return self._objects.pop(job, None)

    def installed(self) -> dict[str, Any]:
        with self._lock:
            return dict(self._objects)

    def lookup(self) -> Any | None:
        """The calling thread's job-scoped object, or None (process scope).
        Fast path first: no jobs installed -> no thread-map read at all."""
        objects = self._objects
        if not objects:
            return None
        job = _thread_jobs.get(threading.get_ident())
        if job is None:
            return None
        return objects.get(job)
