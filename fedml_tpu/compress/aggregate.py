"""Compression-aware aggregation.

Two consumers:

- The **sim engine** (`sim/engine.py`): :func:`compressed_aggregator` wraps
  any broadcast-mode server rule (FedAvg / FedOpt / FedNova / robust) so the
  round program encodes each client's delta (with optional error feedback),
  decodes, and hands the inner rule the *reconstructed* stack — compression
  becomes a pure transform on the stacked-client axis, and the per-round
  bytes-on-wire metrics ride the ordinary agg-metrics channel into the
  metrics stream.

- The **message-passing server** (`algorithms/fedavg_distributed.py`):
  :func:`accumulate_encoded` folds one client's encoded delta into a single
  dense f64 accumulator — top-k planes scatter-add directly from their
  (index, value) planes, so the server never materializes per-client dense
  trees; dense-plane codecs stream one transient decode at a time.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.base import Aggregator, fedavg_aggregator
from fedml_tpu.compress import error_feedback as ef
from fedml_tpu.compress.codec import Codec, EncodedUpdate, tree_bytes
from fedml_tpu.core.tree import tree_leaves_with_paths
from fedml_tpu.obs import metrics as metricslib
from fedml_tpu.obs import trace

Pytree = Any


def compressed_aggregator(
    codec: Codec,
    inner: Aggregator | None = None,
    error_feedback: bool = True,
    num_slots: int | None = None,
) -> Aggregator:
    """Wrap ``inner`` so client updates pass through ``codec`` (+EF) first.

    ``num_slots`` is the padded cohort size the engine stages ([C_pad]); the
    EF residual stack is [num_slots, ...] and is matched to clients by slot,
    which is identity exactly when the cohort is the full population
    (rng.sample_clients returns ``arange`` at full participation) — the
    engine enforces that precondition. Padding slots train fully-masked
    (zero delta) so their residuals stay zero.
    """
    inner = inner or fedavg_aggregator()
    if getattr(inner, "per_client", False):
        raise NotImplementedError(
            "update compression wraps broadcast-mode aggregators; per-client "
            f"rules ({inner.name}) keep models resident and have no uplink "
            "delta to compress"
        )
    if error_feedback and num_slots is None:
        raise ValueError("error_feedback=True needs num_slots (padded cohort)")

    def init_state(global_variables):
        res = ()
        if error_feedback:
            res = jax.tree.map(
                lambda l: jnp.zeros((num_slots,) + np.shape(l), jnp.result_type(l)),
                global_variables,
            )
        return {"inner": inner.init_state(global_variables), "residual": res}

    def aggregate(global_variables, stacked, weights, state, rng, extras=None):
        c = weights.shape[0]
        delta = jax.tree.map(lambda s, g: s - g[None].astype(s.dtype),
                             stacked, global_variables)
        comp = delta
        if error_feedback:
            comp = jax.tree.map(jnp.add, delta, state["residual"])
        keys = jax.random.split(jax.random.fold_in(rng, 0xC0DEC), c)
        enc, dec, new_res = jax.vmap(
            lambda t, k: ef.encode_with_feedback(codec, t, k)
        )(comp, keys)
        reconstructed = jax.tree.map(
            lambda g, d: (g[None] + d.astype(jnp.result_type(g))).astype(
                jnp.result_type(g)
            ),
            global_variables, dec,
        )
        new_global, inner_state, inner_metrics = inner.aggregate(
            global_variables, reconstructed, weights, state["inner"], rng, extras
        )
        # Byte accounting is static (shapes/dtypes only): per-client encoded
        # bytes come out of the vmapped planes' [C, ...] leaves; only the
        # non-padding cohort (weight > 0) actually crosses the wire.
        per_client = enc.nbytes / c
        dense = float(tree_bytes(global_variables))
        real = jnp.sum((weights > 0).astype(jnp.float32))
        metrics = {
            metricslib.COMM_UPLINK_BYTES: real * per_client,
            metricslib.COMM_UPLINK_DENSE_BYTES: real * dense,
            metricslib.COMM_DOWNLINK_BYTES: real * dense,
            metricslib.COMM_DOWNLINK_DENSE_BYTES: real * dense,
            metricslib.COMM_RATIO: jnp.float32(dense / per_client),
        }
        new_state = {
            "inner": inner_state,
            "residual": new_res if error_feedback else (),
        }
        return new_global, new_state, {**inner_metrics, **metrics}

    return Aggregator(
        init_state, aggregate, name=f"compressed[{codec.name}]>{inner.name}"
    )


# ---------------------------------------------------------------------------
# Host-side streaming accumulation for the message-passing server
# ---------------------------------------------------------------------------


def _flat_leaves(tree: Pytree) -> list[np.ndarray]:
    return [np.ravel(np.asarray(v)) for _, v in tree_leaves_with_paths(tree)]


def accumulate_encoded(
    acc: np.ndarray, enc: EncodedUpdate, weight: float, codec: Codec
) -> None:
    """``acc += weight * decode(enc)`` into a flat f64 accumulator laid out in
    canonical leaf order (the ``pack_pytree`` wire layout).

    Plain top-k updates scatter-add straight from their int32/bf16 planes —
    O(k) work and no dense materialization per client. Other schemes decode
    one client at a time (one transient dense vector, never C of them).
    """
    # traced (hot only on the message-passing server, once per upload); the
    # sim engine's encode/decode is fused into the round program and shows
    # up inside engine/dispatch instead (docs/OBSERVABILITY.md)
    with trace.span("compress/accumulate", scheme=enc.scheme):
        if enc.scheme == "topk" and not isinstance(
            enc.planes.get("values"), EncodedUpdate
        ):
            vals = _flat_leaves(enc.planes["values"])
            idxs = _flat_leaves(enc.planes["indices"])
            off = 0
            for v, idx, spec in zip(vals, idxs, enc.meta_dict()["leaves"]):
                n = int(np.prod(spec["shape"])) if spec["shape"] else 1
                np.add.at(acc, off + idx.astype(np.int64),
                          weight * v.astype(np.float64))
                off += n
            return
        with trace.span("compress/decode", scheme=enc.scheme):
            dense = _flat_leaves(codec.decode(enc))
        off = 0
        for leaf in dense:
            acc[off : off + leaf.size] += weight * leaf.astype(np.float64)
            off += leaf.size


# ---------------------------------------------------------------------------
# Chunked accumulation for the sharded fold plane (algorithms/fold_plane.py)
# ---------------------------------------------------------------------------


def prepare_encoded(enc: EncodedUpdate, weight: float, codec: Codec):
    """One-shot per-upload prep for chunk-partitioned folding: everything
    :func:`accumulate_encoded` computes once per upload (the decode, the
    global index plane) moves here so :func:`fold_encoded_slice` can apply
    any ``[lo, hi)`` slice of the contribution independently — off the comm
    receive thread, one chunk worker at a time — with the exact arithmetic
    of the serial fold.

    Top-k planes sort their global (leaf-offset) indices once; dense-plane
    schemes decode once into a single transient f64 vector. Both carry the
    same per-element contribution expression as the serial path
    (``weight * value.astype(np.float64)``), so a chunked apply is
    bit-identical to :func:`accumulate_encoded` over the full vector."""
    with trace.span("compress/accumulate", scheme=enc.scheme):
        if enc.scheme == "topk" and not isinstance(
            enc.planes.get("values"), EncodedUpdate
        ):
            vals = _flat_leaves(enc.planes["values"])
            idxs = _flat_leaves(enc.planes["indices"])
            gidx_parts, contrib_parts = [], []
            off = 0
            for v, idx, spec in zip(vals, idxs, enc.meta_dict()["leaves"]):
                n = int(np.prod(spec["shape"])) if spec["shape"] else 1
                gidx_parts.append(off + idx.astype(np.int64))
                contrib_parts.append(weight * v.astype(np.float64))
                off += n
            gidx = (np.concatenate(gidx_parts) if gidx_parts
                    else np.zeros(0, np.int64))
            contrib = (np.concatenate(contrib_parts) if contrib_parts
                       else np.zeros(0, np.float64))
            order = np.argsort(gidx, kind="stable")
            return ("topk", gidx[order], contrib[order])
        with trace.span("compress/decode", scheme=enc.scheme):
            dense = _flat_leaves(codec.decode(enc))
        full = (np.concatenate([leaf.astype(np.float64) for leaf in dense])
                if dense else np.zeros(0, np.float64))
        return ("dense", float(weight), full)


def fold_encoded_slice(acc: np.ndarray, prep, lo: int, hi: int) -> None:
    """Apply the ``[lo, hi)`` slice of a prepared upload to ``acc``.

    Top-k slices scatter through a bincount over the chunk's index
    partition (replacing the serial ``np.add.at`` — same sums, since top-k
    indices are unique per leaf and leaves occupy disjoint offset ranges,
    so every element receives at most one contribution; untouched elements
    add an exact ``+0.0``, and the accumulator can never hold ``-0.0``
    because it starts at ``+0.0`` and an IEEE sum is ``-0`` only when both
    operands are). Dense slices re-apply the serial per-element expression
    ``weight * full64[j]`` verbatim."""
    kind = prep[0]
    if kind == "topk":
        _, sidx, scontrib = prep
        a, b = np.searchsorted(sidx, (lo, hi))
        if a == b:
            return
        acc[lo:hi] += np.bincount(sidx[a:b] - lo, weights=scontrib[a:b],
                                  minlength=hi - lo)
    else:
        _, weight, full = prep
        acc[lo:hi] += weight * full[lo:hi]


# ---------------------------------------------------------------------------
# Tier partials through the codec plane (async_agg/tree.py encoded uplinks)
# ---------------------------------------------------------------------------


def encode_partial(
    acc64: np.ndarray, weight_sum: float, base64: np.ndarray | None,
    codec: Codec, rng,
) -> EncodedUpdate:
    """Encode an edge tier's raw partial (the f64 accumulator
    ``sum_i w_i x_i``) for the tier-to-tier uplink.

    Delta-domain codecs ship ``acc - weight_sum * base`` as f32 (the PR 14
    delta framing applied to the accumulator: the parent holds the SAME
    round global, so the weighted base mass is reconstructable and only the
    update mass pays for quantization). The ``none`` codec ships the f64
    accumulator itself — a pure passthrough, so a none-coded partial is
    BIT-IDENTICAL to the raw-f64 wire payload (the identity arm in
    tools/async_smoke.py)."""
    if codec.delta_domain:
        if base64 is None:
            raise ValueError(
                f"delta-domain tier codec {codec.name!r} needs the round "
                "global as its base (dense downlink only)"
            )
        tree = {"acc": (acc64 - float(weight_sum) * base64).astype(np.float32)}
    else:
        tree = {"acc": acc64}
    with trace.span("compress/encode", scheme=codec.name, partial=True):
        return codec.encode(tree, rng)


def decode_partial(
    enc: EncodedUpdate, weight_sum: float, base64: np.ndarray | None,
    codec: Codec,
) -> np.ndarray:
    """Inverse of :func:`encode_partial`: recover the f64 accumulator a
    parent tier folds. The ``none`` path is a dtype-preserving view — no
    cast touches the bits."""
    with trace.span("compress/decode", scheme=enc.scheme, partial=True):
        leaves = _flat_leaves(codec.decode(enc))
    arr = (np.asarray(leaves[0], np.float64) if len(leaves) == 1
           else np.concatenate([l.astype(np.float64) for l in leaves]))
    if codec.delta_domain:
        if base64 is None:
            raise ValueError(
                f"delta-domain tier codec {codec.name!r} needs the round "
                "global to reconstruct the partial"
            )
        arr = arr + float(weight_sum) * base64
    return arr
