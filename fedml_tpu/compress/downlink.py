"""Downlink delta coding: quantized model distribution at fan-out.

Uplink compression (codec.py) left the downlink dense: every round the
server shipped the full f32 global model to every receiver — the dominant
bytes bill in the reference's mobile/IoT MQTT+S3 paradigm (SURVEY §1,
§5.8) once cohorts scale. This module closes it: at each round close (or
async emission) the server encodes the new global ONCE as a delta against
the previous *emitted* version through any delta-domain codec
(q8/topk/bf16 and chains), keeps a short chain of one-step encoded deltas,
and serves each receiver by the model version it echoed — a fresh client
gets the one-step delta, a straggler gets the cumulative chain, a client
whose base was retired gets the periodic full keyframe.

Error-free reconstruction, the invariant everything hangs off:

- the server's model of record is the DECODED model — after every advance
  ``decoded_r = decoded_{r-1} + decode(encode(global_r - decoded_{r-1}))``
  replaces ``global_r`` — so the delta is always formed against what the
  clients actually hold, and quantization error never accumulates across
  rounds (it is re-measured into the next delta, the server-side analogue
  of error feedback);
- a client applies chain steps with the SAME f32 host adds in the SAME
  order the server used, so ``held == decoded`` holds BIT-EXACTLY for
  every client at its version (tools/downlink_smoke.py asserts it end to
  end); a cumulative chain is the ordered pack of the retained one-step
  deltas, never a re-encoded sum — float addition only replays exactly;
- every ``keyframe_every``-th version is a dense keyframe: the chain
  resets, ``decoded`` snaps back to the exact aggregate, and any receiver
  (new, restarted, or beyond retention) resynchronizes losslessly.

Retention is staleness-driven: the async server feeds its observed
version-lag distribution in via :meth:`DownlinkCodecState.observe_staleness`
and the chain keeps ``max(retention, p99_staleness + 1)`` steps, so a
deliberately slow client keeps finding its delta base; a base retired
anyway falls back to the keyframe with a loud warning
(``fedml_tpu.algorithms.fedavg_distributed.FedAvgServerManager``).
"""

from __future__ import annotations

import functools
import json
import logging
import threading

import numpy as np

import jax

from fedml_tpu.comm.message import (
    pack_encoded_update,
    pack_pytree,
    unpack_encoded_update,
    unpack_pytree,
)
from fedml_tpu.compress.codec import Codec, make_codec

# descriptor "kind" tag so a receiver can reject a payload that is not a
# downlink chain (e.g. a misrouted uplink EncodedUpdate descriptor)
DOWNLINK_CHAIN_KIND = "downlink_delta_chain"


def resolve_downlink_codec(spec, topk_frac: float = 0.01,
                           quantize_bits: int = 8) -> Codec | None:
    """CLI/runner seam: a ``--downlink_compressor`` spec (or an already-built
    codec) to the armed downlink codec, or None for the dense path. ``none``
    resolves to None — NOT to an identity-codec delta plane: a none-codec
    "delta" would still replace the broadcast with ``decoded + (new -
    decoded)``, which float addition does not round-trip, so the only honest
    none arm is the unchanged dense broadcast (bit-identity guarded by
    tools/downlink_smoke.py)."""
    if spec is None:
        return None
    if isinstance(spec, Codec):
        codec = spec
    else:
        s = str(spec).strip()
        if not s or s == "none":
            return None
        codec = make_codec(s, topk_frac=topk_frac, quantize_bits=quantize_bits)
    return codec if codec.delta_domain else None


@functools.lru_cache(maxsize=None)
def _encode_fn(codec: Codec):
    return jax.jit(codec.encode)


@functools.lru_cache(maxsize=None)
def _decode_fn(codec: Codec):
    return jax.jit(codec.decode)


def _decode_flat(codec: Codec, enc) -> np.ndarray:
    """Decode an EncodedUpdate to the flat f32 wire layout. ONE definition
    shared by the server's advance and the client's chain apply — both sides
    must run the identical jitted decode program and the identical host-side
    flatten, or the bit-exact held == decoded contract breaks."""
    tree = _decode_fn(codec)(enc)
    flat, _ = pack_pytree(jax.tree.map(np.asarray, tree))
    return flat.view(np.float32)


def _as_f32(flat_u8) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(flat_u8)).view(np.float32)


class DownlinkCodecState:
    """Server-side downlink compression state (one per server manager).

    Owns the decoded model of record, the chain of retained one-step
    encoded deltas, the per-base cumulative-blob cache (so one fan-out
    builds each distinct version-gap's blob ONCE — the object-store
    broadcast then puts one blob per gap, and the framed transports share
    one frame per gap), and the staleness histogram driving retention.
    Thread-safe: the server's receive thread, timer thread, and fan-outs
    all touch it."""

    def __init__(self, codec: Codec, model_desc: str,
                 keyframe_every: int = 8, retention: int = 4):
        if not codec.delta_domain:
            raise ValueError(
                "downlink delta coding needs a delta-domain codec; the "
                "'none' arm is the unchanged dense broadcast (pass None / "
                "resolve_downlink_codec)"
            )
        self.codec = codec
        self.model_desc = model_desc
        self.keyframe_every = max(1, int(keyframe_every))
        self.retention = max(1, int(retention))
        self._lock = threading.Lock()
        self._decoded: np.ndarray | None = None  # guarded-by: _lock
        self.version = -1  # guarded-by: _lock
        # contiguous ascending one-step deltas, each producing its "version"
        self._chain: list[dict] = []  # guarded-by: _lock
        self._blob_cache: dict[int, tuple] = {}  # guarded-by: _lock
        self._last_keyframe = -1  # guarded-by: _lock
        self._gap_counts: dict[int, int] = {}  # guarded-by: _lock
        self._retention_floor = 0  # guarded-by: _lock
        self._stats = {
            "keyframes": 0, "deltas": 0,
            "keyframes_served": 0, "chains_served": 0,
            "chain_steps_served": 0, "retired_fallbacks": 0,
        }  # guarded-by: _lock

    # -- server write path ---------------------------------------------------

    def reset(self, flat_u8, version: int) -> np.ndarray:
        """(Re)anchor on a dense keyframe — at init and after a crash
        restore, when no receiver's held version is known. Returns the
        decoded (== exact) model as wire bytes."""
        with self._lock:
            return self._keyframe(_as_f32(flat_u8), int(version))

    def _keyframe(self, new_f32: np.ndarray, version: int):  # lock-held: _lock
        self._decoded = np.array(new_f32, np.float32)
        self._chain.clear()
        self._blob_cache.clear()
        self.version = version
        self._last_keyframe = version
        self._stats["keyframes"] += 1
        return self._decoded.view(np.uint8)

    def advance(self, new_flat_u8, version: int) -> np.ndarray:
        """Encode the new global ONCE at round close / emission. Returns the
        decoded model's wire bytes — the caller REPLACES its global with
        them, so the next uplink round trains from exactly what every
        receiver reconstructs. Keyframe versions snap back to the exact
        aggregate (and reset the chain)."""
        version = int(version)
        new_f32 = _as_f32(new_flat_u8)
        with self._lock:
            if self._decoded is None or version % self.keyframe_every == 0:
                return self._keyframe(new_f32, version)
            delta = new_f32 - self._decoded
            tree = unpack_pytree(delta.view(np.uint8), self.model_desc)
            key = jax.random.fold_in(jax.random.key(0xD0DEC), version)
            enc = _encode_fn(self.codec)(tree, key)
            dec = _decode_flat(self.codec, enc)
            # sequential f32 adds are THE canonical order (clients replay it)
            self._decoded = self._decoded + dec
            flat, desc = pack_encoded_update(enc)
            self._chain.append({"version": version, "flat": flat,
                                "desc": desc})
            keep = max(self.retention, self._staleness_floor())
            while len(self._chain) > keep:
                self._chain.pop(0)
            self._blob_cache.clear()
            self.version = version
            self._stats["deltas"] += 1
            return self._decoded.view(np.uint8)

    # -- staleness-driven retention ------------------------------------------

    def observe_staleness(self, gap: int) -> None:
        """Feed one observed version lag (the async server calls this per
        fold): the retention floor tracks the p99 of the distribution so a
        deliberately slow client keeps finding its delta base."""
        gap = int(gap)
        if gap <= 0:
            return
        with self._lock:
            self._gap_counts[gap] = self._gap_counts.get(gap, 0) + 1

    def _staleness_floor(self) -> int:  # lock-held: _lock
        total = sum(self._gap_counts.values())
        if total:
            cum = 0
            for g in sorted(self._gap_counts):
                cum += self._gap_counts[g]
                if cum >= 0.99 * total:
                    # never shrinks: a once-slow client stays coverable
                    self._retention_floor = max(self._retention_floor, g + 1)
                    break
        return self._retention_floor

    def retention_effective(self) -> int:
        with self._lock:
            return max(self.retention, self._staleness_floor())

    # -- serve-by-version ----------------------------------------------------

    def serve(self, base_version) -> tuple:
        """Payload for a receiver holding ``base_version``:
        ``("delta", flat_u8, desc_json)`` — the cumulative chain from base
        to the current version (cached per distinct gap, so every receiver
        of a fan-out with the same base shares ONE blob object) — or
        ``("keyframe", reason, retired)`` where ``retired`` flags a base
        that retention trimmed away (the caller warns loudly; a base merely
        predating the last keyframe is the designed cadence, not a
        defect)."""
        with self._lock:
            if base_version is None:
                self._stats["keyframes_served"] += 1
                return ("keyframe", "no echoed base version", False)
            base = int(base_version)
            if base >= self.version:
                self._stats["keyframes_served"] += 1
                return ("keyframe", f"base {base} already current", False)
            blob = self._blob_for(base)
            if blob is not None:
                self._stats["chains_served"] += 1
                self._stats["chain_steps_served"] += self.version - base
                return ("delta", blob[0], blob[1])
            retired = base >= self._last_keyframe
            self._stats["keyframes_served"] += 1
            if retired:
                self._stats["retired_fallbacks"] += 1
                reason = (f"base {base} retired (chain starts at "
                          f"{self._chain[0]['version'] if self._chain else '-'},"
                          f" retention {max(self.retention, self._retention_floor)})")
            else:
                reason = (f"base {base} predates keyframe "
                          f"{self._last_keyframe}")
            return ("keyframe", reason, retired)

    def _blob_for(self, base: int):  # lock-held: _lock
        cached = self._blob_cache.get(base)
        if cached is not None:
            return cached
        steps = [e for e in self._chain if e["version"] > base]
        if (not steps or steps[0]["version"] != base + 1
                or steps[-1]["version"] != self.version):
            return None
        if len(steps) == 1:
            flat = steps[0]["flat"]  # zero-copy: the stored segment itself
        else:
            flat = np.concatenate([s["flat"] for s in steps])
        desc = json.dumps({
            "kind": DOWNLINK_CHAIN_KIND,
            "scheme": self.codec.name,
            "version": int(self.version),
            "base": int(base),
            "steps": [{"version": int(s["version"]),
                       "nbytes": int(s["flat"].size),
                       "desc": json.loads(s["desc"])} for s in steps],
        })
        self._blob_cache[base] = (flat, desc)
        return self._blob_cache[base]

    def stats_snapshot(self) -> dict:
        with self._lock:
            return dict(self._stats)


class DownlinkDecoder:
    """Client-side held-model state: the mutable f32 copy of the decoded
    global and the version it represents. Keyframes replace it; delta
    chains apply step-by-step with the server's exact f32 add sequence, so
    reconstruction is bit-exact (steps at or below the held version are
    skipped — the server may conservatively serve a chain from an older
    echo than the client's true state)."""

    def __init__(self, codec: Codec):
        self.codec = codec
        self.held: np.ndarray | None = None  # f32, this decoder's own copy
        self.version: int | None = None

    def apply_keyframe(self, flat_u8, version) -> np.ndarray:
        self.held = np.array(_as_f32(flat_u8), np.float32)
        self.version = int(version)
        return self.held

    def apply_chain(self, chain_flat_u8, chain_desc: str, base_version,
                    target_version) -> np.ndarray:
        spec = json.loads(chain_desc)
        if spec.get("kind") != DOWNLINK_CHAIN_KIND:
            raise RuntimeError(
                f"downlink payload descriptor kind {spec.get('kind')!r} is "
                f"not {DOWNLINK_CHAIN_KIND!r} — misrouted payload"
            )
        if spec.get("scheme") != self.codec.name:
            raise RuntimeError(
                f"downlink chain was encoded with {spec.get('scheme')!r} but "
                f"this client decodes {self.codec.name!r} — server and "
                "clients must be armed with the same --downlink_compressor"
            )
        if self.held is None or self.version is None:
            raise RuntimeError(
                "delta-coded sync before any keyframe: this client holds no "
                "base model to apply the chain onto (protocol bug — the "
                "init sync is always a dense keyframe)"
            )
        if base_version is not None and int(base_version) > self.version:
            raise RuntimeError(
                f"delta chain base {int(base_version)} is ahead of the held "
                f"version {self.version}: this client missed a sync the "
                "server thinks it received"
            )
        chain = np.ascontiguousarray(np.asarray(chain_flat_u8, np.uint8))
        held, ver = self.held, self.version
        off = 0
        for step in spec["steps"]:
            n = int(step["nbytes"])
            seg = chain[off:off + n]
            off += n
            sv = int(step["version"])
            if sv <= ver:
                continue  # already held (server served from an older echo)
            if sv != ver + 1:
                raise RuntimeError(
                    f"delta chain step {sv} does not continue held version "
                    f"{ver}: missing step {ver + 1} — cannot reconstruct"
                )
            enc = unpack_encoded_update(seg, json.dumps(step["desc"]))
            held = held + _decode_flat(self.codec, enc)
            ver = sv
        if ver != int(spec["version"]):
            raise RuntimeError(
                f"delta chain ends at version {int(spec['version'])} but "
                f"application stopped at {ver}"
            )
        if target_version is not None and ver != int(target_version):
            # a fan-out racing a round close can stamp the header with a
            # version one ahead of/behind the chain (the chain itself is
            # internally validated and bit-exact, and the version echo
            # self-corrects on the next upload) — log, don't kill the
            # client thread
            logging.warning(
                "delta chain reconstructs version %d but the sync header is "
                "stamped %d (fan-out raced a round close; the echo "
                "self-corrects)", ver, int(target_version),
            )
        self.held, self.version = held, ver
        return held
