"""Update-compression subsystem: codecs, error feedback, wire + engine
integration, and bytes-on-wire accounting (docs/COMPRESSION.md)."""

from fedml_tpu.compress.codec import (
    Bf16Codec,
    ChainCodec,
    Codec,
    EncodedUpdate,
    NoneCodec,
    QuantizeCodec,
    TopKCodec,
    make_codec,
    tree_bytes,
)

__all__ = [
    "Bf16Codec",
    "ChainCodec",
    "Codec",
    "EncodedUpdate",
    "NoneCodec",
    "QuantizeCodec",
    "TopKCodec",
    "make_codec",
    "tree_bytes",
]
