"""Update-compression subsystem: codecs, error feedback, wire + engine
integration, downlink delta coding, and bytes-on-wire accounting
(docs/COMPRESSION.md)."""

from fedml_tpu.compress.codec import (
    Bf16Codec,
    ChainCodec,
    Codec,
    EncodedUpdate,
    NoneCodec,
    QuantizeCodec,
    TopKCodec,
    make_codec,
    tree_bytes,
)
from fedml_tpu.compress.downlink import (
    DownlinkCodecState,
    DownlinkDecoder,
    resolve_downlink_codec,
)

__all__ = [
    "Bf16Codec",
    "ChainCodec",
    "Codec",
    "DownlinkCodecState",
    "DownlinkDecoder",
    "EncodedUpdate",
    "NoneCodec",
    "QuantizeCodec",
    "TopKCodec",
    "make_codec",
    "resolve_downlink_codec",
    "tree_bytes",
]
