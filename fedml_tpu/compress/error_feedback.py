"""Error feedback for lossy update compression.

A biased compressor (top-k, deterministic rounding) silently discards update
mass every round; error feedback (EF-SGD / 1-bit Adam lineage; Konečný et
al.'s sketched-update fix) keeps the discarded residual on the client and
adds it back into the *next* round's update before encoding, so the dropped
mass is delayed, never lost — the property that preserves convergence.

Semantics (all pure pytree functions, jit/vmap-compatible):

    compensated_r = delta_r + residual_{r-1}          (compensate)
    wire_r        = encode(compensated_r)
    residual_r    = compensated_r - decode(wire_r)    (residual)

State lives wherever the client identity lives: one pytree per client thread
on the message-passing path (algorithms/fedavg_distributed.py), a stacked
[C, ...] pytree inside the aggregator state on the sim path
(compress/aggregate.py).
"""

from __future__ import annotations

from typing import Any

import jax

from fedml_tpu.core import tree as treelib

Pytree = Any


def init(like: Pytree) -> Pytree:
    """Zero residual shaped like one client's update."""
    return treelib.tree_zeros_like(like)


def compensate(delta: Pytree, residual: Pytree | None) -> Pytree:
    """Add the carried residual into this round's update before encoding."""
    if residual is None:
        return delta
    return treelib.tree_add(delta, residual)


def residual(compensated: Pytree, decoded: Pytree) -> Pytree:
    """What the codec dropped this round — carried to the next round."""
    return jax.tree.map(
        lambda c, d: (c - d.astype(c.dtype)), compensated, decoded
    )


def encode_with_feedback(codec, compensated: Pytree, rng: jax.Array):
    """One EF step after compensation: returns ``(encoded, decoded,
    new_residual)``. Factored so the trainer path, the sim aggregator, and
    the wire client all run the identical encode/residual arithmetic."""
    enc = codec.encode(compensated, rng)
    dec = codec.decode(enc)
    return enc, dec, residual(compensated, dec)
