"""Update-compression codecs.

Cross-device FL is uplink-bound: the reference ships every client update as
dense float32 state_dicts (fedavg/utils.py transform_tensor_to_list — dense
JSON is *worse* than dense binary), so bandwidth, not compute, caps cohort
size. Konečný et al. 2016 and QSGD (Alistarh et al. 2017) show sketched /
quantized updates with error feedback preserve convergence while cutting
uplink bytes 10-100x. This module is the codec layer of that subsystem:

- :class:`EncodedUpdate` — a registered JAX pytree carrying named *planes*
  (pytrees of arrays, e.g. ``values``/``indices``/``scale``) plus static JSON
  metadata. Byte accounting is derived from plane shapes/dtypes, so it is
  available at trace time and on the wire alike.
- :class:`Codec` implementations, all jit/vmap-compatible pure functions over
  pytrees (via the same canonical leaf order as ``core/tree.py``):
  :class:`NoneCodec` (identity), :class:`Bf16Codec` (cast), :class:`TopKCodec`
  (per-leaf magnitude top-k; int32 index + bf16 value planes),
  :class:`QuantizeCodec` (QSGD-style stochastic uniform quantization, 8/4
  bit), and :class:`ChainCodec` (stage composition, e.g. top-k then 4-bit).
- :func:`make_codec` — the config-string registry behind ``--compressor``.

Delta-domain contract: every codec except ``none`` encodes the *model delta*
(local minus global), which is what error feedback (error_feedback.py)
compensates; ``none`` encodes the model itself so the uncompressed wire path
stays bit-identical to the dense protocol (``delta_domain`` flag).
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


def tree_bytes(tree: Pytree) -> int:
    """Total bytes of all array leaves (shape/dtype only — works on tracers,
    numpy arrays, and jax arrays alike)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        n = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
        total += n * np.dtype(leaf.dtype).itemsize
    return total


def tree_spec(tree: Pytree) -> list[dict]:
    """Per-leaf (shape, dtype) spec in canonical traversal order — the static
    decode metadata every codec stores in ``EncodedUpdate.meta``."""
    return [
        {"shape": list(np.shape(leaf)), "dtype": str(jnp.result_type(leaf))}
        for leaf in jax.tree_util.tree_leaves(tree)
    ]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class EncodedUpdate:
    """A compressed update: named planes (pytrees of arrays) + static meta.

    Registered as a JAX pytree so encode/decode compose with jit and vmap
    (a vmapped encode returns one EncodedUpdate whose plane leaves carry a
    leading client axis). ``meta`` is a JSON string (hashable → usable as
    pytree aux data); ``scheme`` names the codec that can decode it.
    """

    scheme: str
    planes: dict[str, Pytree]
    meta: str = "{}"

    def tree_flatten(self):
        names = tuple(sorted(self.planes))
        return tuple(self.planes[n] for n in names), (self.scheme, names, self.meta)

    @classmethod
    def tree_unflatten(cls, aux, children):
        scheme, names, meta = aux
        return cls(scheme, dict(zip(names, children)), meta)

    @property
    def nbytes(self) -> int:
        """Encoded payload bytes (what actually crosses the wire)."""
        return tree_bytes(self.planes)

    def meta_dict(self) -> dict:
        return json.loads(self.meta)


def _leaf_meta(tree: Pytree) -> str:
    return json.dumps({"leaves": tree_spec(tree)})


def _rebuild(treedef, leaves_flat, meta: dict):
    out = []
    for leaf, spec in zip(leaves_flat, meta["leaves"]):
        out.append(leaf.reshape(spec["shape"]).astype(spec["dtype"]))
    return jax.tree_util.tree_unflatten(treedef, out)


class Codec:
    """Encode/decode contract. ``encode(tree, rng) -> EncodedUpdate`` and
    ``decode(enc) -> tree`` are pure, jit/vmap-compatible, and inverse up to
    the codec's information loss. ``delta_domain`` says whether the wire
    payload is a model delta (compensatable by error feedback) or the model
    itself (only ``none``, preserving dense-path bit-identity)."""

    name = "codec"
    delta_domain = True

    def encode(self, tree: Pytree, rng: jax.Array) -> EncodedUpdate:
        raise NotImplementedError

    def decode(self, enc: EncodedUpdate) -> Pytree:
        raise NotImplementedError

    def dense_bytes(self, tree: Pytree) -> int:
        return tree_bytes(tree)

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"


class NoneCodec(Codec):
    """Identity codec: dense f32 planes, bit-exact round trip. Exists so the
    compression plumbing can run end-to-end while remaining bit-identical to
    the uncompressed protocol."""

    name = "none"
    delta_domain = False

    def encode(self, tree, rng):
        return EncodedUpdate("none", {"values": tree}, _leaf_meta(tree))

    def decode(self, enc):
        return enc.planes["values"]


class Bf16Codec(Codec):
    """Cast values to bfloat16 (half the bytes; ~3 decimal digits kept)."""

    name = "bf16"

    def encode(self, tree, rng):
        vals = jax.tree.map(lambda x: x.astype(jnp.bfloat16), tree)
        return EncodedUpdate("bf16", {"values": vals}, _leaf_meta(tree))

    def decode(self, enc):
        meta = enc.meta_dict()
        leaves, treedef = jax.tree_util.tree_flatten(enc.planes["values"])
        return _rebuild(treedef, leaves, meta)


class TopKCodec(Codec):
    """Per-leaf magnitude top-k sparsification (Konečný et al. sketched
    updates): keep ``ceil(frac * n)`` entries of each flattened leaf as an
    int32 index plane + a value plane (bf16 by default — 6 bytes per kept
    entry vs 4 bytes per dense entry, so the ratio is ~ 1.5 * frac)."""

    def __init__(self, frac: float = 0.01, value_dtype=jnp.bfloat16):
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"topk frac must be in (0, 1], got {frac}")
        self.frac = float(frac)
        self.value_dtype = value_dtype
        self.name = f"topk{self.frac:g}"

    def _k(self, n: int) -> int:
        return max(1, int(math.ceil(self.frac * n)))

    def encode(self, tree, rng):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        vals, idxs = [], []
        for leaf in leaves:
            flat = jnp.ravel(leaf).astype(jnp.float32)
            n = flat.shape[0]
            _, idx = jax.lax.top_k(jnp.abs(flat), self._k(n))
            idx = idx.astype(jnp.int32)
            vals.append(flat[idx].astype(self.value_dtype))
            idxs.append(idx)
        return EncodedUpdate(
            "topk",
            {
                "values": jax.tree_util.tree_unflatten(treedef, vals),
                "indices": jax.tree_util.tree_unflatten(treedef, idxs),
            },
            _leaf_meta(tree),
        )

    def decode(self, enc):
        meta = enc.meta_dict()
        vals, treedef = jax.tree_util.tree_flatten(enc.planes["values"])
        idxs = jax.tree_util.tree_leaves(enc.planes["indices"])
        out = []
        for v, idx, spec in zip(vals, idxs, meta["leaves"]):
            n = int(np.prod(spec["shape"])) if spec["shape"] else 1
            dense = jnp.zeros((n,), jnp.float32).at[idx].set(v.astype(jnp.float32))
            out.append(dense)
        return _rebuild(treedef, out, meta)


class QuantizeCodec(Codec):
    """QSGD-style stochastic uniform quantization (Alistarh et al. 2017):
    per leaf, scale by max|x| onto ``s = 2^(bits-1) - 1`` symmetric integer
    levels with stochastic rounding (unbiased: E[decode(encode(x))] = x).
    8-bit stores int8 planes; 4-bit packs two two's-complement nibbles per
    byte, so the value plane is n/2 bytes."""

    def __init__(self, bits: int = 8):
        if bits not in (4, 8):
            raise ValueError(f"quantize bits must be 4 or 8, got {bits}")
        self.bits = bits
        self.levels = 2 ** (bits - 1) - 1
        self.name = f"q{bits}"

    def encode(self, tree, rng):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        keys = jax.random.split(rng, max(len(leaves), 1))
        qs, scales = [], []
        for leaf, key in zip(leaves, keys):
            flat = jnp.ravel(leaf).astype(jnp.float32)
            scale = jnp.max(jnp.abs(flat)) if flat.size else jnp.float32(0.0)
            safe = jnp.where(scale > 0, scale, 1.0)
            y = flat / safe * self.levels
            low = jnp.floor(y)
            q = low + (jax.random.uniform(key, flat.shape) < (y - low))
            q = jnp.clip(q, -self.levels, self.levels).astype(jnp.int8)
            qs.append(self._pack(q))
            scales.append(scale.astype(jnp.float32))
        return EncodedUpdate(
            f"q{self.bits}",
            {
                "values": jax.tree_util.tree_unflatten(treedef, qs),
                "scale": jax.tree_util.tree_unflatten(treedef, scales),
            },
            _leaf_meta(tree),
        )

    def _pack(self, q: jnp.ndarray) -> jnp.ndarray:
        if self.bits == 8:
            return q
        n = q.shape[0]
        pad = (-n) % 2
        nib = (jnp.pad(q, (0, pad)).astype(jnp.int32)) & 0xF
        return (nib[0::2] | (nib[1::2] << 4)).astype(jnp.uint8)

    def _unpack(self, packed: jnp.ndarray, n: int) -> jnp.ndarray:
        if self.bits == 8:
            return packed.astype(jnp.float32)
        p = packed.astype(jnp.int32)
        nib = jnp.stack([p & 0xF, (p >> 4) & 0xF], axis=-1).reshape(-1)[:n]
        return jnp.where(nib >= 8, nib - 16, nib).astype(jnp.float32)

    def decode(self, enc):
        meta = enc.meta_dict()
        vals, treedef = jax.tree_util.tree_flatten(enc.planes["values"])
        scales = jax.tree_util.tree_leaves(enc.planes["scale"])
        out = []
        for v, scale, spec in zip(vals, scales, meta["leaves"]):
            n = int(np.prod(spec["shape"])) if spec["shape"] else 1
            out.append(self._unpack(v, n) / self.levels * scale)
        return _rebuild(treedef, out, meta)


class ChainCodec(Codec):
    """Stage composition: each later stage re-encodes the previous stage's
    ``values`` plane (itself a pytree), e.g. ``topk+q4`` sparsifies then
    quantizes the kept values. The nested stage rides inside the outer
    EncodedUpdate as a pytree child, so jit/vmap and the wire format see one
    ordinary encoded update."""

    def __init__(self, stages: Sequence[Codec]):
        if len(stages) < 2:
            raise ValueError("ChainCodec needs at least two stages")
        if any(not s.delta_domain for s in stages):
            raise ValueError("'none' cannot be a chain stage")
        self.stages = list(stages)
        self.name = "+".join(s.name for s in stages)

    def encode(self, tree, rng):
        keys = jax.random.split(rng, len(self.stages))
        encs, cur = [], tree
        for stage, key in zip(self.stages, keys):
            e = stage.encode(cur, key)
            encs.append(e)
            cur = e.planes["values"]
        nested = encs[-1]
        for e in reversed(encs[:-1]):
            nested = EncodedUpdate(e.scheme, {**e.planes, "values": nested}, e.meta)
        return nested

    def decode(self, enc):
        # unfold the nesting outermost -> innermost (one level per stage)
        layers, e = [], enc
        while isinstance(e.planes.get("values"), EncodedUpdate):
            layers.append(e)
            e = e.planes["values"]
        layers.append(e)
        if len(layers) != len(self.stages):
            raise ValueError(
                f"chain {self.name} has {len(self.stages)} stages but the "
                f"encoded update nests {len(layers)}"
            )
        values = None
        for layer, stage in zip(reversed(layers), reversed(self.stages)):
            if values is not None:
                layer = EncodedUpdate(
                    layer.scheme, {**layer.planes, "values": values}, layer.meta
                )
            values = stage.decode(layer)
        return values


_BASE = ("none", "bf16", "topk", "q4", "q8", "quantize", "qsgd")


def make_codec(spec: str, topk_frac: float = 0.01, quantize_bits: int = 8) -> Codec:
    """Build a codec from a ``--compressor`` config string.

    Base names: ``none``, ``bf16``, ``topk`` (uses ``topk_frac``),
    ``q8``/``q4``, ``quantize``/``qsgd`` (use ``quantize_bits``). Stages
    compose with ``+`` (applied left to right): ``topk+q4`` sparsifies then
    4-bit-quantizes the kept values. In a chain, ``topk`` keeps f32 values so
    the downstream stage sees full precision.
    """
    parts = [p.strip() for p in spec.split("+") if p.strip()]
    if not parts:
        raise ValueError(f"empty compressor spec {spec!r}")
    unknown = [p for p in parts if p not in _BASE]
    if unknown:
        raise ValueError(
            f"unknown compressor {unknown} in {spec!r}; expected names from "
            f"{_BASE} composed with '+'"
        )

    def base(name: str, in_chain: bool) -> Codec:
        if name == "none":
            return NoneCodec()
        if name == "bf16":
            return Bf16Codec()
        if name == "topk":
            return TopKCodec(
                topk_frac,
                value_dtype=jnp.float32 if in_chain else jnp.bfloat16,
            )
        if name in ("quantize", "qsgd"):
            return QuantizeCodec(quantize_bits)
        return QuantizeCodec(int(name[1:]))

    if len(parts) == 1:
        return base(parts[0], in_chain=False)
    if "none" in parts:
        raise ValueError("'none' cannot appear in a compressor chain")
    return ChainCodec(
        [base(p, in_chain=(i < len(parts) - 1)) for i, p in enumerate(parts)]
    )
