"""Per-file analysis facts: the cacheable projection every rule consumes.

fedlint v1 handed each rule the raw AST and every rule re-walked it; the
interprocedural rules (lock-order, blocking-under-lock, thread-entry) need a
WHOLE-PROGRAM view — a function/method index and a resolved call graph — and
the tier-1 gate needs warm re-runs to skip parsing entirely (the suite runs
near its timeout budget). Both land here: one extraction pass per file
produces a :class:`FileFacts` — classes, functions (methods, nested defs,
lambdas), every call site with the lock set syntactically held at it, every
``self``-attribute touch, ``with self.<lock>:`` acquisitions, thread-entry
registrations (``threading.Thread``/``Timer``/send-pool dispatch), lowering
registrations (``jax.jit`` & co.), wire-key and metric-constant sites — that
is JSON-serializable, so ``.fedlint_cache/`` can key it on
``(path, mtime, size)`` and a warm run never re-parses an unchanged file.

Extraction is config-independent by design: which calls count as blocking,
which lock names alias, which metric prefixes are canonical are all matched
at RULE time over the facts, so one cache serves every rule selection.

Lock-tracking semantics (shared with the v1 guarded-by rule): ``held`` at a
site is the set of ``self.<attr>`` locks acquired by lexically enclosing
``with`` statements INSIDE the same function body. A nested ``def`` or
``lambda`` starts with an empty held set — it runs later, on whatever thread
calls it. ``# lock-held:`` annotations are recorded but NOT folded into
``held``: they are caller-side assumptions the interprocedural rules must
check, not facts.
"""

from __future__ import annotations

import ast
import dataclasses
import re

_UPPER_RE = re.compile(r"^[A-Z][A-Z0-9_]+$")
_KEY_RE = re.compile(r"^MSG_ARG_KEY_\w+$")

# schema version of the serialized facts: bump on ANY change to the
# dataclasses below or to extraction semantics — the cache discards
# mismatched entries wholesale
FACTS_SCHEMA_VERSION = 2

# call names that register their callable arguments as THREAD ENTRIES:
# the callable runs later on another thread, with no locks held
_THREAD_CTORS = frozenset({"threading.Thread", "Thread"})
_TIMER_CTORS = frozenset({"threading.Timer", "Timer"})
# method names whose callable-bearing arguments are dispatched to worker
# threads (SendWorkerPool.run_all tasks, executor.submit)
_POOL_DISPATCH_ATTRS = frozenset({"run_all", "submit"})

# attr names that lower their first argument through a compile path
# (traced-purity scope — mirrors parallel/dispatch + compat.shard_map)
_LOWERING_ATTRS = frozenset({
    "jit", "shard_map", "lower", "jit_under_mesh", "pallas_call",
})

# builtin coercions are value plumbing, not construction (the
# overwrite-after-super seam targets real constructions)
_COERCIONS = frozenset({
    "bool", "int", "float", "str", "bytes", "tuple", "list", "dict", "set",
    "frozenset",
})


def dotted_name(func: ast.expr) -> str | None:
    """`a.b.c` -> "a.b.c" (Name/Attribute chains only)."""
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(expr: ast.expr) -> bool:
    """`jax.jit`, `jit`, `partial(jax.jit, ...)`, `functools.partial(...)`."""
    dotted = dotted_name(expr)
    if dotted in ("jax.jit", "jit"):
        return True
    if isinstance(expr, ast.Call):
        fn = dotted_name(expr.func)
        if fn in ("partial", "functools.partial") and expr.args:
            return _is_jit_expr(expr.args[0])
    return False


def _self_attr_target(node: ast.stmt) -> str | None:
    """`self.X = ...` / `self.X: T = ...` -> X (single-target only)."""
    if isinstance(node, ast.Assign) and len(node.targets) == 1:
        target = node.targets[0]
    elif isinstance(node, ast.AnnAssign):
        target = node.target
    else:
        return None
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return target.attr
    return None


def _is_construction(value: ast.expr | None) -> bool:
    if not isinstance(value, ast.Call):
        return False
    func = value.func
    if isinstance(func, ast.Name) and func.id in _COERCIONS:
        return False
    return True


def _is_super_init_call(node: ast.stmt) -> bool:
    """`super().__init__(...)` or `SomeClass.__init__(self, ...)`."""
    if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
        return False
    func = node.value.func
    if not (isinstance(func, ast.Attribute) and func.attr == "__init__"):
        return False
    owner = func.value
    if (isinstance(owner, ast.Call) and isinstance(owner.func, ast.Name)
            and owner.func.id == "super"):
        return True
    # explicit-base form used by the diamond tips (Buffered* variants)
    return isinstance(owner, (ast.Name, ast.Attribute))


def _base_name(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


@dataclasses.dataclass
class CallFact:
    """One call site: where, what (dotted chain), a resolution hint, and the
    locks syntactically held around it."""

    line: int
    col: int
    dotted: str | None
    func: int          # owning FuncFact index, -1 for module scope
    target: tuple[str, str] | None   # ("self", m) | ("name", n) | None
    held: tuple[str, ...]

    def to_list(self) -> list:
        return [self.line, self.col, self.dotted, self.func,
                list(self.target) if self.target else None, list(self.held)]

    @staticmethod
    def from_list(row: list) -> "CallFact":
        return CallFact(row[0], row[1], row[2], row[3],
                        tuple(row[4]) if row[4] else None, tuple(row[5]))


@dataclasses.dataclass
class FuncFact:
    """One function-like body: method, module function, nested def, lambda."""

    index: int
    name: str
    qualname: str
    line: int
    col: int
    cls: int            # ClassFact index when a direct method, else -1
    parent: int         # enclosing FuncFact index, -1 at module/class level
    kind: str           # "def" | "async" | "lambda"
    lock_held: tuple[str, ...]          # `# lock-held:` annotation
    jit_decorated: bool
    calls: list[int] = dataclasses.field(default_factory=list)
    # (attr, line, col, held) — every `self.<attr>` touch in this body
    touches: list[tuple[str, int, int, tuple[str, ...]]] = dataclasses.field(
        default_factory=list)
    # (lock, line, held_before) — `with self.<lock>:` acquisitions
    acquires: list[tuple[str, int, tuple[str, ...]]] = dataclasses.field(
        default_factory=list)
    lowered_via: str | None = None      # lambda handed to a lowering call

    def to_dict(self) -> dict:
        return {
            "i": self.index, "n": self.name, "q": self.qualname,
            "l": self.line, "c": self.col, "k": self.cls, "p": self.parent,
            "t": self.kind, "lh": list(self.lock_held),
            "j": self.jit_decorated, "ca": self.calls,
            "to": [[a, l, c, list(h)] for a, l, c, h in self.touches],
            "aq": [[lk, l, list(h)] for lk, l, h in self.acquires],
            "lv": self.lowered_via,
        }

    @staticmethod
    def from_dict(d: dict) -> "FuncFact":
        return FuncFact(
            d["i"], d["n"], d["q"], d["l"], d["c"], d["k"], d["p"], d["t"],
            tuple(d["lh"]), d["j"], list(d["ca"]),
            [(a, l, c, tuple(h)) for a, l, c, h in d["to"]],
            [(lk, l, tuple(h)) for lk, l, h in d["aq"]],
            d["lv"],
        )


@dataclasses.dataclass
class ClassFact:
    """Per-class facts: base chain, what ``__init__`` constructs/assigns,
    concurrency annotations, and the method table."""

    index: int
    name: str
    bases: tuple[str, ...]
    line: int
    init_constructed: dict[str, int] = dataclasses.field(default_factory=dict)
    init_assigned: set[str] = dataclasses.field(default_factory=set)
    # (attr, line, col, top_stmt_line) — every self.X assignment in __init__
    init_assigns: list[tuple[str, int, int, int]] = dataclasses.field(
        default_factory=list)
    super_call_line: int | None = None
    guarded: dict[str, str] = dataclasses.field(default_factory=dict)
    guard_decl_lines: set[int] = dataclasses.field(default_factory=set)
    lock_held: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict)
    methods: dict[str, int] = dataclasses.field(default_factory=dict)
    # class-level MSG_ARG_KEY_* string constants: name -> (value, line, col,
    # value_line, value_col)
    wire_defs: dict[str, tuple[str, int, int, int, int]] = dataclasses.field(
        default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "i": self.index, "n": self.name, "b": list(self.bases),
            "l": self.line, "ic": self.init_constructed,
            "ia": sorted(self.init_assigned),
            "ias": [list(t) for t in self.init_assigns],
            "s": self.super_call_line, "g": self.guarded,
            "gd": sorted(self.guard_decl_lines),
            "lh": {k: list(v) for k, v in self.lock_held.items()},
            "m": self.methods,
            "w": {k: list(v) for k, v in self.wire_defs.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "ClassFact":
        return ClassFact(
            d["i"], d["n"], tuple(d["b"]), d["l"],
            dict(d["ic"]), set(d["ia"]),
            [tuple(t) for t in d["ias"]], d["s"], dict(d["g"]),
            set(d["gd"]), {k: tuple(v) for k, v in d["lh"].items()},
            dict(d["m"]), {k: tuple(v) for k, v in d["w"].items()},
        )


@dataclasses.dataclass
class WaiverFact:
    line: int
    rules: tuple[str, ...]
    reason: str | None


@dataclasses.dataclass
class FileFacts:
    """Everything the rules need to know about one module."""

    path: str
    classes: list[ClassFact] = dataclasses.field(default_factory=list)
    functions: list[FuncFact] = dataclasses.field(default_factory=list)
    calls: list[CallFact] = dataclasses.field(default_factory=list)
    # (via, ref, line, owner func index) — callables handed to thread ctors
    thread_entries: list[tuple[str, tuple[str, str], int, int]] = (
        dataclasses.field(default_factory=list))
    # function NAMES passed to a lowering call (jax.jit(f), shard_map(f, ..))
    lowered_names: list[tuple[str, str]] = dataclasses.field(
        default_factory=list)         # (name, via)
    # whitespace-free string constants: (value, line, col)
    str_consts: list[tuple[str, int, int]] = dataclasses.field(
        default_factory=list)
    # uppercase identifiers referenced anywhere (metric emission check)
    upper_refs: set[str] = dataclasses.field(default_factory=set)
    # wire-contract usage tallies (MSG_ARG_KEY_* names)
    wire_written: set[str] = dataclasses.field(default_factory=set)
    wire_read: set[str] = dataclasses.field(default_factory=set)
    # add_params("literal", ...) sites: (value, line, col)
    add_params_literals: list[tuple[str, int, int]] = dataclasses.field(
        default_factory=list)
    # value-constant positions of wire definitions (skipped by dup scan)
    wire_def_sites: set[tuple[int, int]] = dataclasses.field(
        default_factory=set)
    # module-level UPPER = "str" constants: (name, value, line, col)
    module_consts: list[tuple[str, str, int, int]] = dataclasses.field(
        default_factory=list)
    waivers: dict[int, WaiverFact] = dataclasses.field(default_factory=dict)
    standalone_comments: set[int] = dataclasses.field(default_factory=set)

    # -- waiver resolution (same grammar as SourceFile) ----------------------

    def waiver_fact_for(self, rule: str, line: int) -> WaiverFact | None:
        for candidate in (line, line - 1):
            w = self.waivers.get(candidate)
            if w is None:
                continue
            if (candidate == line - 1
                    and candidate not in self.standalone_comments):
                continue
            if rule in w.rules:
                return w
        return None

    # -- (de)serialization ---------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "classes": [c.to_dict() for c in self.classes],
            "functions": [f.to_dict() for f in self.functions],
            "calls": [c.to_list() for c in self.calls],
            "thread_entries": [[v, list(r), l, f]
                               for v, r, l, f in self.thread_entries],
            "lowered_names": [list(t) for t in self.lowered_names],
            "str_consts": [list(t) for t in self.str_consts],
            "upper_refs": sorted(self.upper_refs),
            "wire_written": sorted(self.wire_written),
            "wire_read": sorted(self.wire_read),
            "add_params_literals": [list(t) for t in self.add_params_literals],
            "wire_def_sites": [list(t) for t in sorted(self.wire_def_sites)],
            "module_consts": [list(t) for t in self.module_consts],
            "waivers": {
                str(line): [w.line, list(w.rules), w.reason]
                for line, w in self.waivers.items()
            },
            "standalone_comments": sorted(self.standalone_comments),
        }

    @staticmethod
    def from_dict(d: dict) -> "FileFacts":
        return FileFacts(
            path=d["path"],
            classes=[ClassFact.from_dict(c) for c in d["classes"]],
            functions=[FuncFact.from_dict(f) for f in d["functions"]],
            calls=[CallFact.from_list(c) for c in d["calls"]],
            thread_entries=[(v, tuple(r), l, f)
                            for v, r, l, f in d["thread_entries"]],
            lowered_names=[tuple(t) for t in d["lowered_names"]],
            str_consts=[tuple(t) for t in d["str_consts"]],
            upper_refs=set(d["upper_refs"]),
            wire_written=set(d["wire_written"]),
            wire_read=set(d["wire_read"]),
            add_params_literals=[tuple(t) for t in d["add_params_literals"]],
            wire_def_sites={tuple(t) for t in d["wire_def_sites"]},
            module_consts=[tuple(t) for t in d["module_consts"]],
            waivers={
                int(line): WaiverFact(row[0], tuple(row[1]), row[2])
                for line, row in d["waivers"].items()
            },
            standalone_comments=set(d["standalone_comments"]),
        )


class _Extractor(ast.NodeVisitor):
    """One pass over a parsed module, emitting a FileFacts."""

    def __init__(self, source_file):
        self.sf = source_file
        self.facts = FileFacts(path=source_file.path)
        self.class_stack: list[int] = []
        self.func_stack: list[int] = []
        self.held: tuple[str, ...] = ()
        # id(lambda node) -> via, for lambdas handed to lowering calls
        self._lambda_via: dict[int, str] = {}

    # -- helpers -------------------------------------------------------------

    def _cur_func(self) -> int:
        return self.func_stack[-1] if self.func_stack else -1

    def _qual_prefix(self) -> str:
        parts: list[str] = []
        for ci in self.class_stack:
            parts.append(self.facts.classes[ci].name)
        for fi in self.func_stack:
            parts.append(self.facts.functions[fi].name)
        return ".".join(parts)

    def _ref_of(self, expr: ast.expr) -> tuple[str, str] | None:
        """A callable reference we can resolve: self.<m> or a bare name."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return ("self", expr.attr)
        if isinstance(expr, ast.Name):
            return ("name", expr.id)
        return None

    # -- classes -------------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        cf = ClassFact(
            index=len(self.facts.classes),
            name=node.name,
            bases=tuple(b for b in map(_base_name, node.bases) if b),
            line=node.lineno,
        )
        self.facts.classes.append(cf)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                held = self.sf.lock_held_annotation(item.lineno)
                if held:
                    cf.lock_held[item.name] = tuple(held)
                if item.name == "__init__":
                    self._index_init(cf, item)
            elif (isinstance(item, ast.Assign) and len(item.targets) == 1
                    and isinstance(item.targets[0], ast.Name)
                    and _KEY_RE.match(item.targets[0].id)
                    and isinstance(item.value, ast.Constant)
                    and isinstance(item.value.value, str)):
                cf.wire_defs.setdefault(item.targets[0].id, (
                    item.value.value, item.lineno, item.col_offset,
                    item.value.lineno, item.value.col_offset,
                ))
                self.facts.wire_def_sites.add(
                    (item.value.lineno, item.value.col_offset))
        # methods register as functions are visited (class on top of stack)
        self.class_stack.append(cf.index)
        saved_funcs, self.func_stack = self.func_stack, []
        saved_held, self.held = self.held, ()
        self.generic_visit(node)
        self.func_stack = saved_funcs
        self.held = saved_held
        self.class_stack.pop()

    def _index_init(self, cf: ClassFact, item: ast.FunctionDef) -> None:
        for stmt in item.body:
            if _is_super_init_call(stmt):
                if cf.super_call_line is None:
                    cf.super_call_line = stmt.lineno
                continue
            for sub in ast.walk(stmt):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                attr = _self_attr_target(sub)
                if attr is None:
                    continue
                cf.init_assigned.add(attr)
                cf.init_assigns.append(
                    (attr, sub.lineno, sub.col_offset, stmt.lineno))
                if _is_construction(sub.value):
                    cf.init_constructed.setdefault(attr, sub.lineno)

    # -- functions -----------------------------------------------------------

    def _enter_function(self, node, name: str, kind: str) -> FuncFact:
        direct_method = (bool(self.class_stack) and not self.func_stack)
        prefix = self._qual_prefix()
        ff = FuncFact(
            index=len(self.facts.functions),
            name=name,
            qualname=f"{prefix}.{name}" if prefix else name,
            line=node.lineno,
            col=node.col_offset,
            cls=self.class_stack[-1] if direct_method else -1,
            parent=self._cur_func(),
            kind=kind,
            lock_held=tuple(self.sf.lock_held_annotation(node.lineno)),
            jit_decorated=(
                kind != "lambda"
                and any(_is_jit_expr(d) for d in node.decorator_list)
            ),
            lowered_via=self._lambda_via.get(id(node)),
        )
        self.facts.functions.append(ff)
        if direct_method:
            self.facts.classes[ff.cls].methods.setdefault(name, ff.index)
        return ff

    def _visit_function(self, node, name: str, kind: str) -> None:
        ff = self._enter_function(node, name, kind)
        self.func_stack.append(ff.index)
        # the body runs later: enclosing with-blocks do NOT protect it
        saved_held, self.held = self.held, ()
        self.generic_visit(node)
        self.held = saved_held
        self.func_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function(node, node.name, "def")

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_function(node, node.name, "async")

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_function(node, "<lambda>", "lambda")

    # -- guarded-by declarations ---------------------------------------------

    def _note_guard_decl(self, node) -> None:
        if not self.class_stack:
            return
        attr = _self_attr_target(node)
        if attr is None:
            return
        lock = self.sf.guarded_annotation(node.lineno)
        if lock is not None:
            cf = self.facts.classes[self.class_stack[-1]]
            cf.guarded.setdefault(attr, lock)
            cf.guard_decl_lines.add(node.lineno)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._note_guard_decl(node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._note_guard_decl(node)
        self.generic_visit(node)

    # -- lock tracking -------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            expr = item.context_expr
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                fi = self._cur_func()
                if fi >= 0:
                    self.facts.functions[fi].acquires.append(
                        (expr.attr, expr.lineno, self.held))
                if expr.attr not in self.held:
                    acquired.append(expr.attr)
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        saved = self.held
        self.held = tuple([*self.held, *acquired])
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_AsyncWith = visit_With

    # -- leaf facts ----------------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            fi = self._cur_func()
            if fi >= 0:
                self.facts.functions[fi].touches.append(
                    (node.attr, node.lineno, node.col_offset, self.held))
        if _UPPER_RE.match(node.attr):
            self.facts.upper_refs.add(node.attr)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if _UPPER_RE.match(node.id):
            self.facts.upper_refs.add(node.id)

    def visit_Constant(self, node: ast.Constant) -> None:
        v = node.value
        if (isinstance(v, str) and v and len(v) <= 200
                and not any(ch.isspace() for ch in v)):
            self.facts.str_consts.append((v, node.lineno, node.col_offset))

    # -- wire-contract marks -------------------------------------------------

    def _wire_key_name(self, node: ast.expr) -> str | None:
        if isinstance(node, ast.Attribute) and _KEY_RE.match(node.attr):
            return node.attr
        if isinstance(node, ast.Name) and _KEY_RE.match(node.id):
            return node.id
        return None

    def _wire_mark(self, node: ast.expr, read: bool = False,
                   written: bool = False) -> None:
        name = self._wire_key_name(node)
        if name is None:
            return
        if read:
            self.facts.wire_read.add(name)
        if written:
            self.facts.wire_written.add(name)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        self._wire_mark(node.slice, read=True, written=True)
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict) -> None:
        for key in node.keys:
            if key is not None:
                self._wire_mark(key, written=True)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        for comp in [node.left, *node.comparators]:
            self._wire_mark(comp, read=True, written=True)
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        dotted = dotted_name(node.func)
        target = self._ref_of(node.func)
        call = CallFact(
            line=node.lineno, col=node.col_offset, dotted=dotted,
            func=self._cur_func(), target=target, held=self.held,
        )
        idx = len(self.facts.calls)
        self.facts.calls.append(call)
        if call.func >= 0:
            self.facts.functions[call.func].calls.append(idx)

        # wire-contract usage marks (MyMessage.add_params(KEY, v), .get(KEY))
        if isinstance(node.func, ast.Attribute) and node.args:
            if node.func.attr == "add_params":
                self._wire_mark(node.args[0], written=True)
                arg0 = node.args[0]
                if (isinstance(arg0, ast.Constant)
                        and isinstance(arg0.value, str)):
                    self.facts.add_params_literals.append(
                        (arg0.value, arg0.lineno, arg0.col_offset))
            elif node.func.attr in ("get", "pop"):
                self._wire_mark(node.args[0], read=True)
            else:
                for arg in node.args:
                    self._wire_mark(arg, read=True, written=True)

        # thread-entry registrations
        self._note_thread_entry(node, dotted)

        # lowering registrations (traced-purity)
        is_lowering = (
            dotted in ("jax.jit", "jit")
            or (isinstance(node.func, ast.Attribute)
                and node.func.attr in _LOWERING_ATTRS)
        )
        if is_lowering and node.args:
            via = dotted or node.func.attr
            arg0 = node.args[0]
            if isinstance(arg0, ast.Name):
                self.facts.lowered_names.append((arg0.id, via))
            elif isinstance(arg0, ast.Attribute):
                # method handles lowered by reference — the engine's packed/
                # sharded program constructors pass bound methods to
                # dispatch.lower (``displib.lower(self._packed_agg_impl,
                # ...)``); record the terminal attr so traced-purity scans
                # the method body like any lowered function
                self.facts.lowered_names.append((arg0.attr, via))
            elif isinstance(arg0, ast.Lambda):
                self._lambda_via[id(arg0)] = via

        self.generic_visit(node)

    def _note_thread_entry(self, node: ast.Call, dotted: str | None) -> None:
        refs: list[tuple[str, tuple[str, str], int]] = []
        if dotted in _THREAD_CTORS:
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = self._ref_of(kw.value)
                    if ref:
                        refs.append(("Thread", ref, kw.value.lineno))
        elif dotted in _TIMER_CTORS:
            cand = None
            if len(node.args) >= 2:
                cand = node.args[1]
            else:
                for kw in node.keywords:
                    if kw.arg == "function":
                        cand = kw.value
            if cand is not None:
                ref = self._ref_of(cand)
                if ref:
                    refs.append(("Timer", ref, cand.lineno))
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr in _POOL_DISPATCH_ATTRS):
            # pool dispatch: any resolvable callable reference anywhere in
            # the argument expressions runs later on a worker thread
            for arg in [*node.args, *[kw.value for kw in node.keywords]]:
                for sub in ast.walk(arg):
                    if isinstance(sub, (ast.Name, ast.Attribute)):
                        ref = self._ref_of(sub)
                        if ref:
                            refs.append((node.func.attr, ref, sub.lineno))
        for via, ref, line in refs:
            self.facts.thread_entries.append(
                (via, ref, line, self._cur_func()))


def extract_facts(source_file) -> FileFacts:
    """Produce the FileFacts for a parsed :class:`core.SourceFile`."""
    ex = _Extractor(source_file)
    ex.visit(source_file.tree)
    facts = ex.facts
    # module-level UPPER = "str" constants (metric-keys dead-metric check)
    for stmt in source_file.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and _UPPER_RE.match(stmt.targets[0].id)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            facts.module_consts.append((
                stmt.targets[0].id, stmt.value.value,
                stmt.lineno, stmt.col_offset,
            ))
    # waivers + standalone comment lines (waiver application is facts-side)
    for line, w in source_file.waivers.items():
        facts.waivers[line] = WaiverFact(w.line, w.rules, w.reason)
    facts.standalone_comments = set(source_file.standalone_comments)
    return facts
