"""``[tool.fedlint]`` configuration (pyproject.toml).

Python 3.10 has no ``tomllib``; ``tomli`` is preferred when present and a
minimal line-oriented fallback parses just this section otherwise (string
scalars, booleans, and one-line string arrays — all the section uses), so
the gate never grows a dependency the container may lack.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

DEFAULT_RULES = (
    "guarded-by",
    "overwrite-after-super",
    "wire-contract",
    "traced-purity",
    "metric-keys",
    "lock-order",
    "blocking-under-lock",
    "thread-entry",
)


@dataclasses.dataclass(frozen=True)
class FedlintConfig:
    """Resolved rule selection + scan scope."""

    paths: tuple[str, ...] = ("fedml_tpu", "tools")
    select: tuple[str, ...] = DEFAULT_RULES
    exclude: tuple[str, ...] = ()
    # metric-keys: canonical prefixes and the module(s) allowed to define
    # literals under them
    # fedlint: disable=metric-keys -- the prefix grammar the rule enforces, not record keys
    metric_prefixes: tuple[str, ...] = ("Comm/", "Robust/", "Async/", "Fleet/")
    metric_modules: tuple[str, ...] = ("fedml_tpu/obs/metrics.py",)
    # metric-keys dead-metric arm: the tools that CONSUME the canonical
    # keys, and the docs trees whose tables count as consumers — a key no
    # emitter references, or one no reader/doc names, is a finding
    metric_reader_modules: tuple[str, ...] = (
        "tools/fleet_report.py", "tools/trace_report.py",
    )
    metric_doc_paths: tuple[str, ...] = ("docs",)
    # traced-purity: banned host-call patterns inside lowered functions
    banned_traced_calls: tuple[str, ...] = (
        "time.time", "np.random.*", "numpy.random.*", "print",
        "datetime.now", "datetime.datetime.now",
    )
    # traced-purity, module-wide arm: "<path-prefix>:<pattern>" entries ban
    # a call pattern EVERYWHERE in matching modules (not just traced
    # functions). The population subsystem's replay determinism rests on
    # every draw flowing through its seeded rng (population/prng.py), so
    # np.random.* is banned module-wide there — machine-checked instead of
    # review-checked.
    banned_module_calls: tuple[str, ...] = (
        "fedml_tpu/population/:np.random.*",
        "fedml_tpu/population/:numpy.random.*",
    )
    # blocking-under-lock: fnmatch patterns over the dotted call chain
    # ("a.b.c"); a match is a call that can block the thread — banned while
    # any lock is held along the call chain (PR 8 "checkpoint written
    # outside the lock", PR 11 "trace events emitted after release").
    # A `.wait` on the HELD lock itself is exempt in-rule (Condition.wait
    # releases it).
    blocking_calls: tuple[str, ...] = (
        "time.sleep",
        "np.savez*", "numpy.savez*", "json.dump", "pickle.dump",
        "*.send_message", "*.broadcast_message", "*.send_init_msg",
        "*.run_all", "*.save_server",
        "*.result", "*.wait", "*.join",
    )
    # lock-order / thread-entry: lock-name aliases, "<from>=<to>" — merges
    # two attr spellings (or two qualified Class.attr ids) that reference
    # ONE runtime lock object, so the acquisition graph sees one node
    lock_aliases: tuple[str, ...] = ()


def _parse_fallback(text: str) -> dict:
    """Line-oriented ``[tool.fedlint]`` extraction for stdlibs without a
    TOML parser: handles `key = "str"`, `key = true/false`, and one-line
    `key = ["a", "b"]` arrays."""
    section: dict = {}
    in_section = False
    for line in text.splitlines():
        stripped = line.strip()
        if stripped.startswith("["):
            in_section = stripped == "[tool.fedlint]"
            continue
        if not in_section or not stripped or stripped.startswith("#"):
            continue
        m = re.match(r"([\w\-]+)\s*=\s*(.+)$", stripped)
        if not m:
            continue
        key, raw = m.group(1), m.group(2).strip()
        if raw.startswith("["):
            section[key] = re.findall(r'"([^"]*)"', raw)
        elif raw.startswith('"'):
            section[key] = raw.strip('"')
        elif raw in ("true", "false"):
            section[key] = raw == "true"
    return section


def _load_section(pyproject: Path) -> dict:
    text = pyproject.read_text()
    try:
        import tomli

        return tomli.loads(text).get("tool", {}).get("fedlint", {})
    except ImportError:
        try:
            import tomllib  # py3.11+

            return tomllib.loads(text).get("tool", {}).get("fedlint", {})
        except ImportError:
            return _parse_fallback(text)


def load_config(start: str | Path | None = None) -> FedlintConfig:
    """Resolve ``[tool.fedlint]`` from the nearest pyproject.toml at or
    above ``start`` (default: cwd). Missing file/section -> defaults."""
    here = Path(start) if start is not None else Path.cwd()
    if here.is_file():
        here = here.parent
    for candidate in (here, *here.resolve().parents):
        pyproject = candidate / "pyproject.toml"
        if pyproject.exists():
            section = _load_section(pyproject)
            break
    else:
        section = {}
    defaults = FedlintConfig()

    def tup(key: str, fallback: tuple[str, ...]) -> tuple[str, ...]:
        value = section.get(key)
        return tuple(value) if value is not None else fallback

    return FedlintConfig(
        paths=tup("paths", defaults.paths),
        select=tup("select", defaults.select),
        exclude=tup("exclude", defaults.exclude),
        metric_prefixes=tup("metric-prefixes", defaults.metric_prefixes),
        metric_modules=tup("metric-modules", defaults.metric_modules),
        metric_reader_modules=tup("metric-reader-modules",
                                  defaults.metric_reader_modules),
        metric_doc_paths=tup("metric-doc-paths", defaults.metric_doc_paths),
        banned_traced_calls=tup("banned-traced-calls",
                                defaults.banned_traced_calls),
        banned_module_calls=tup("banned-module-calls",
                                defaults.banned_module_calls),
        blocking_calls=tup("blocking-calls", defaults.blocking_calls),
        lock_aliases=tup("lock-aliases", defaults.lock_aliases),
    )
