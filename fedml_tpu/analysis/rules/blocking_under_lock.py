"""blocking-under-lock: no blocking call while any lock is held.

Provenance: two hard-won disciplines this repo already enforces by prose
and review. PR 8: "the server snapshot is taken under the round lock but
WRITTEN outside it — full-model disk I/O never blocks the upload/heartbeat
handlers". PR 11: "trace events emitted after release"; and the tree
re-broadcast fix — "_on_sync_from_parent snapshots round under _edge_lock
and re-broadcasts outside it" (a lock held across a fan-out serializes
every child behind one receiver's timeout). The rule machine-checks them:

- a call matching a configured blocking pattern (``blocking-calls``:
  file/npz writes, ``send_message``/``broadcast_message``, ``time.sleep``,
  ``queue.join``, ``.result()``, ``.wait()``) is a finding when ANY lock
  is held at the call site — syntactically (``with self.<lock>:``) or by
  ``# lock-held:`` contract;
- interprocedurally: a call made while holding a lock that RESOLVES to a
  function which transitively reaches a blocking call is the same finding,
  naming the chain — this is the edge v1's one-function-at-a-time view
  could not see.

Exemption: ``<lock>.wait()`` on the very lock that is held is the
Condition pattern — ``Condition.wait`` releases the lock while waiting —
so it only fires when OTHER locks are also held across the wait.
"""

from __future__ import annotations

import fnmatch

from fedml_tpu.analysis.core import Finding, Project, Rule
from fedml_tpu.analysis.rules._concurrency import (
    LockNames,
    annotation_locks,
    build_call_index,
)


class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    description = ("no configured blocking call (I/O, sends, sleeps, "
                   "joins, futures) while any lock is held along the "
                   "resolved call chain — snapshot under the lock, do the "
                   "slow work outside")

    def __init__(self, config):
        self.config = config
        self.patterns = tuple(getattr(config, "blocking_calls", ()))
        self.names = LockNames(getattr(config, "lock_aliases", ()))

    def _blocking_pattern(self, dotted: str | None) -> str | None:
        if dotted is None:
            return None
        for pattern in self.patterns:
            if fnmatch.fnmatchcase(dotted, pattern):
                return pattern
        return None

    @staticmethod
    def _wait_receiver(dotted: str) -> str | None:
        """`self._cv.wait` -> `_cv` (the Condition exemption)."""
        parts = dotted.split(".")
        if len(parts) >= 2 and parts[-1] == "wait":
            return parts[-2]
        return None

    def finalize(self, project: Project) -> list[Finding]:
        names = self.names
        findings: list[Finding] = []
        index = build_call_index(project)

        # nearest transitively-reachable blocking call per function:
        # fk -> (chain description, dotted, pattern, wait_lock). wait_lock
        # is the qualified lock a `.wait()` leaf waits ON (None otherwise):
        # callers holding ONLY that lock are exempt — Condition.wait
        # releases it — however deep the wait sits in the chain.
        reach: dict[tuple, tuple[str, str, str, str | None] | None] = {}
        for fk, (file, func) in index.funcs.items():
            direct = None
            for call_idx in func.calls:
                call = file.calls[call_idx]
                pattern = self._blocking_pattern(call.dotted)
                if pattern is None:
                    continue
                recv = self._wait_receiver(call.dotted)
                if recv is None:
                    # a non-wait leaf is the strongest witness (no lock
                    # exempts it): it must never be masked by an earlier
                    # wait leaf whose wait_lock a caller happens to hold
                    direct = (
                        f"{func.qualname} ({file.path}:{call.line})",
                        call.dotted, pattern, None,
                    )
                    break
                if direct is None:
                    direct = (
                        f"{func.qualname} ({file.path}:{call.line})",
                        call.dotted, pattern,
                        names.qualify(
                            project, project.owner_class(file, func), recv),
                    )
            reach[fk] = direct
        changed = True
        while changed:
            changed = False
            for fk, resolved in index.resolved.items():
                mine = reach[fk]
                if mine is not None and mine[3] is None:
                    continue  # already holds an unexemptable witness
                file, func = index.funcs[fk]
                for call, callee_fk in resolved:
                    sub = reach.get(callee_fk)
                    if sub is None:
                        continue
                    if mine is not None and sub[3] is not None:
                        continue  # never downgrade / sideways-swap waits
                    # adopt: first witness found, or upgrade a wait-witness
                    # to a non-wait one (a savez behind one callee must not
                    # be masked by a Condition-wait behind another)
                    reach[fk] = mine = (
                        f"{func.qualname} ({file.path}:{call.line}) "
                        f"-> {sub[0]}",
                        sub[1], sub[2], sub[3],
                    )
                    changed = True
                    if mine[3] is None:
                        break

        for fk in sorted(index.funcs):
            file, func = index.funcs[fk]
            view = project.owner_class(file, func)
            held0 = annotation_locks(project, names, file, func)
            resolved_at = {id(call): callee_fk
                           for call, callee_fk in index.resolved[fk]}
            for call_idx in func.calls:
                call = file.calls[call_idx]
                held = names.qualify_all(project, view, call.held) | held0
                if not held:
                    continue
                pattern = self._blocking_pattern(call.dotted)
                if pattern is not None:
                    recv = self._wait_receiver(call.dotted)
                    if recv is not None:
                        held = held - {names.qualify(project, view, recv)}
                        if not held:
                            continue  # Condition.wait releases the lock
                    findings.append(Finding(
                        self.name, file.path, call.line, call.col,
                        f"blocking call {call.dotted}() (matches "
                        f"{pattern!r}) while holding "
                        f"{', '.join(sorted(held))} — blocking inside a "
                        "critical section stalls every thread contending "
                        "for the lock; snapshot under the lock and do the "
                        "slow work after release",
                    ))
                    continue
                callee_fk = resolved_at.get(id(call))
                if callee_fk is None:
                    continue
                sub = reach.get(callee_fk)
                if sub is None:
                    continue
                chain, dotted, pattern, wait_lock = sub
                effective = held - {wait_lock} if wait_lock else held
                if not effective:
                    continue  # only the waited-on Condition is held
                findings.append(Finding(
                    self.name, file.path, call.line, call.col,
                    f"call chain from `{func.qualname}` while holding "
                    f"{', '.join(sorted(effective))} reaches blocking "
                    f"{dotted}() (matches {pattern!r}): {chain} — "
                    "the lock stays held across the whole chain; "
                    "move the call outside the critical section",
                ))
        return findings
