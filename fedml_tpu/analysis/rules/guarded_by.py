"""guarded-by: lock-discipline checking for annotated fields.

Provenance: the ``_round_lock`` critical-section contract in
``fedavg_distributed.FedAvgServerManager`` (CHANGES.md PR 5/8/9 — "
staleness/exclusion checks and the tally are one critical section") and the
``_edge_lock`` discipline in ``async_agg/tree.py`` whose absence caused the
real cross-silo deadlock fixed in PR 10. The prose contract becomes
machine-checked:

- a field DECLARED ``self.x = ...  # guarded-by: <lock>`` may only be
  read/written on ``self`` inside ``with self.<lock>:`` or in a method
  annotated ``# lock-held: <lock>`` (the callee side of "caller holds the
  lock" docstrings);
- declarations inherit: a subclass touching a base-declared field in
  another file is held to the same lock (the class index resolves bases by
  name across every scanned file);
- ``__init__`` and the declaration lines themselves are exempt (the object
  is not shared during construction), as are deferred closures' bodies —
  no: closures are checked with NO locks held, because they run later, on
  whatever thread calls them (facts extraction resets the held set at
  every nested def/lambda boundary; see facts.py).
"""

from __future__ import annotations

from fedml_tpu.analysis.core import Finding, Project, Rule
from fedml_tpu.analysis.facts import FileFacts


class GuardedByRule(Rule):
    name = "guarded-by"
    description = ("fields annotated `# guarded-by: <lock>` are only "
                   "touched under `with self.<lock>:` or in `# lock-held:` "
                   "methods")

    def __init__(self, config):
        self.config = config

    def check(self, file: FileFacts, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for cf in file.classes:
            view = project.view_of(file, cf.index)
            guarded = project.effective_guarded(view)
            if not guarded:
                continue
            ancestors = project.ancestors(view)
            # every DIRECT method def (duplicate names included — property
            # setter pairs must both be checked), not the name table
            for method in file.functions:
                if method.cls != cf.index:
                    continue
                if method.name == "__init__":
                    continue  # construction: the object is not shared yet
                held0 = set(project.effective_lock_held(view, method.name))
                for func in project.subtree(file, method):
                    # nested defs/lambdas run later, on arbitrary threads:
                    # neither the method's annotation nor its with-blocks
                    # protect them (their own with-blocks still count)
                    base_held = held0 if func.index == method.index else set()
                    for attr, line, col, held in func.touches:
                        if attr not in guarded:
                            continue
                        if line in cf.guard_decl_lines:
                            continue
                        lock = guarded[attr]
                        if lock in held or lock in base_held:
                            continue
                        findings.append(Finding(
                            self.name, file.path, line, col,
                            f"self.{attr} is guarded by self.{lock} "
                            f"(declared in "
                            f"{self._decl_site(view, ancestors, attr)}) "
                            "but is touched without it — wrap in `with self."
                            f"{lock}:` or annotate the method "
                            f"`# lock-held: {lock}`",
                        ))
        return findings

    @staticmethod
    def _decl_site(view, ancestors, attr: str) -> str:
        # nearest declaring class in the chain, for the message only
        for info in [view, *ancestors]:
            if attr in info.guarded:
                return info.name
        return view.name
