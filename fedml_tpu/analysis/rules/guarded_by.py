"""guarded-by: lock-discipline checking for annotated fields.

Provenance: the ``_round_lock`` critical-section contract in
``fedavg_distributed.FedAvgServerManager`` (CHANGES.md PR 5/8/9 — "
staleness/exclusion checks and the tally are one critical section") and the
``_edge_lock`` discipline in ``async_agg/tree.py`` whose absence caused the
real cross-silo deadlock fixed in PR 10. The prose contract becomes
machine-checked:

- a field DECLARED ``self.x = ...  # guarded-by: <lock>`` may only be
  read/written on ``self`` inside ``with self.<lock>:`` or in a method
  annotated ``# lock-held: <lock>`` (the callee side of "caller holds the
  lock" docstrings);
- declarations inherit: a subclass touching a base-declared field in
  another file is held to the same lock (the class index resolves bases by
  name across every scanned file);
- ``__init__`` and the declaration lines themselves are exempt (the object
  is not shared during construction), as are deferred closures' bodies —
  no: closures are checked with NO locks held, because they run later, on
  whatever thread calls them.
"""

from __future__ import annotations

import ast

from fedml_tpu.analysis.core import ClassInfo, Finding, Project, Rule, SourceFile


def _with_locks(node: ast.With) -> set[str]:
    """Lock names acquired by ``with self.<name>[, ...]:`` items."""
    out: set[str] = set()
    for item in node.items:
        expr = item.context_expr
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            out.add(expr.attr)
    return out


class _MethodWalk(ast.NodeVisitor):
    def __init__(self, rule: str, file: SourceFile, info: ClassInfo,
                 guarded: dict[str, str], held: set[str],
                 ancestors: list[ClassInfo]):
        self.rule = rule
        self.file = file
        self.info = info
        self.guarded = guarded
        self.held = held
        self.ancestors = ancestors
        self.findings: list[Finding] = []

    def visit_With(self, node: ast.With) -> None:
        added = _with_locks(node) - self.held
        for item in node.items:
            self.visit(item.context_expr)
        self.held |= added
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    visit_AsyncWith = visit_With

    def _deferred(self, node: ast.AST) -> None:
        # a nested def/lambda runs later on an arbitrary thread: whatever
        # locks the enclosing method holds will NOT be held then
        inner = _MethodWalk(self.rule, self.file, self.info, self.guarded,
                            set(), self.ancestors)
        for child in ast.iter_child_nodes(node):
            inner.visit(child)
        self.findings.extend(inner.findings)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._deferred(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._deferred(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._deferred(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and node.attr in self.guarded
                and node.lineno not in self.info.guard_decl_lines):
            lock = self.guarded[node.attr]
            if lock not in self.held:
                self.findings.append(Finding(
                    "guarded-by", self.file.path, node.lineno,
                    node.col_offset,
                    f"self.{node.attr} is guarded by self.{lock} "
                    f"(declared in {self._decl_site(node.attr)}) but is "
                    "touched without it — wrap in `with self."
                    f"{lock}:` or annotate the method `# lock-held: {lock}`",
                ))
        self.generic_visit(node)

    def _decl_site(self, attr: str) -> str:
        # nearest declaring class in the chain, for the message only
        for info in [self.info, *self.ancestors]:
            if attr in info.guarded:
                return info.name
        return self.info.name


class GuardedByRule(Rule):
    name = "guarded-by"
    description = ("fields annotated `# guarded-by: <lock>` are only "
                   "touched under `with self.<lock>:` or in `# lock-held:` "
                   "methods")

    def __init__(self, config):
        self.config = config

    def check(self, file: SourceFile, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for info in project.all_classes:
            if info.file is not file:
                continue
            guarded = project.effective_guarded(info)
            if not guarded:
                continue
            ancestors = project.ancestors(info)
            for item in info.node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if item.name == "__init__":
                    continue  # construction: the object is not shared yet
                held = set(project.effective_lock_held(info, item.name))
                walk = _MethodWalk(self.name, file, info, guarded, held,
                                   ancestors)
                for stmt in item.body:
                    walk.visit(stmt)
                findings.extend(walk.findings)
        return findings
