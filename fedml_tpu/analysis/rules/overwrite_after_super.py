"""overwrite-after-super: the construct-then-overwrite __init__ seam.

Provenance: ROADMAP open item 1 — "today every async/tree/robust/
compressed subclass construct-then-overwrites the base aggregator, which
is exactly why composition is hard". A subclass ``__init__`` that
reassigns an attribute the base ``__init__`` already CONSTRUCTED (assigned
from a real call, not a builtin coercion) wastes the base's construction
and forks the configuration seam: the base can never learn the subclass's
config, so every new plane multiplies the diamond. The fix shape is a
factory method (``_make_aggregator``) the base calls once, with subclass
config hoisted ABOVE ``super().__init__``.
"""

from __future__ import annotations

from fedml_tpu.analysis.core import Finding, Project, Rule
from fedml_tpu.analysis.facts import FileFacts


class OverwriteAfterSuperRule(Rule):
    name = "overwrite-after-super"
    description = ("a subclass __init__ must not reassign an attribute a "
                   "base __init__ already constructed — use a factory seam")

    def __init__(self, config):
        self.config = config

    def check(self, file: FileFacts, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for cf in file.classes:
            if cf.super_call_line is None:
                continue
            view = project.view_of(file, cf.index)
            constructed: dict[str, tuple[str, int]] = {}
            for ancestor in project.ancestors(view):
                for attr, line in ancestor.facts.init_constructed.items():
                    constructed.setdefault(attr, (ancestor.name, line))
            if not constructed:
                continue
            for attr, line, col, stmt_line in cf.init_assigns:
                if stmt_line <= cf.super_call_line:
                    continue
                if attr not in constructed:
                    continue
                base, base_line = constructed[attr]
                findings.append(Finding(
                    self.name, file.path, line, col,
                    f"self.{attr} reassigned after super().__init__, "
                    f"but {base}.__init__ (line {base_line}) already "
                    "constructs it — construct-then-overwrite; hoist "
                    "the config above super().__init__ and build once "
                    "through a factory method",
                ))
        return findings
