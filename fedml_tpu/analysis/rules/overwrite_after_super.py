"""overwrite-after-super: the construct-then-overwrite __init__ seam.

Provenance: ROADMAP open item 1 — "today every async/tree/robust/
compressed subclass construct-then-overwrites the base aggregator, which
is exactly why composition is hard". A subclass ``__init__`` that
reassigns an attribute the base ``__init__`` already CONSTRUCTED (assigned
from a real call, not a builtin coercion) wastes the base's construction
and forks the configuration seam: the base can never learn the subclass's
config, so every new plane multiplies the diamond. The fix shape is a
factory method (``_make_aggregator``) the base calls once, with subclass
config hoisted ABOVE ``super().__init__``.
"""

from __future__ import annotations

import ast

from fedml_tpu.analysis.core import Finding, Project, Rule, SourceFile, _self_attr_target


class OverwriteAfterSuperRule(Rule):
    name = "overwrite-after-super"
    description = ("a subclass __init__ must not reassign an attribute a "
                   "base __init__ already constructed — use a factory seam")

    def __init__(self, config):
        self.config = config

    def check(self, file: SourceFile, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for info in project.all_classes:
            if info.file is not file or info.init_node is None:
                continue
            if info.super_call_line is None:
                continue
            constructed: dict[str, tuple[str, int]] = {}
            for ancestor in project.ancestors(info):
                for attr, line in ancestor.init_constructed.items():
                    constructed.setdefault(attr, (ancestor.name, line))
            if not constructed:
                continue
            for stmt in info.init_node.body:
                if stmt.lineno <= info.super_call_line:
                    continue
                for sub in ast.walk(stmt):
                    if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        continue
                    attr = _self_attr_target(sub)
                    if attr is None or attr not in constructed:
                        continue
                    base, base_line = constructed[attr]
                    findings.append(Finding(
                        self.name, file.path, sub.lineno, sub.col_offset,
                        f"self.{attr} reassigned after super().__init__, "
                        f"but {base}.__init__ (line {base_line}) already "
                        "constructs it — construct-then-overwrite; hoist "
                        "the config above super().__init__ and build once "
                        "through a factory method",
                    ))
        return findings
