"""lock-order: deadlock-shaped cycles in the lock-acquisition graph.

Provenance: the PR 10 tier-1 deadlock (concurrently dispatched in-silo
executables wedging each other) and the PR 11 review pass, which found
seven real lock bugs BY HAND across the tree/async/faults managers — every
one a variant of "lock B taken while holding lock A in one thread, A while
holding B in another". The rule builds the whole-program acquisition
graph:

- a ``with self.B:`` lexically inside ``with self.A:`` adds edge A -> B;
- a method annotated ``# lock-held: A`` that acquires B adds A -> B (the
  caller holds A by contract);
- a call made while holding A, resolving (self-methods through the class
  diamond, bare names to nested/module functions) to a function that
  TRANSITIVELY acquires B, adds A -> B — the interprocedural edge v1 could
  not see.

Lock identity is the root-most declaring class (core.Project.lock_id), so
one diamond's shared lock is one node while unrelated ``_lock`` attrs stay
distinct; ``lock-aliases`` merges spellings of one runtime lock. Findings:

- any CYCLE in the graph names the full path (A -> B -> A) with one
  example acquisition site per edge — two threads walking the cycle from
  different entry points deadlock;
- acquiring a lock ALREADY HELD along the chain (directly or through
  calls) is a self-deadlock: ``threading.Lock`` is not reentrant.
"""

from __future__ import annotations

from fedml_tpu.analysis.core import Finding, Project, Rule
from fedml_tpu.analysis.rules._concurrency import (
    LockNames,
    annotation_locks,
    build_call_index,
)


class LockOrderRule(Rule):
    name = "lock-order"
    description = ("no cycles in the whole-program lock-acquisition order "
                   "(with-blocks, # lock-held: contracts, and resolved "
                   "call chains); no re-acquisition of a held lock")

    def __init__(self, config):
        self.config = config
        self.names = LockNames(getattr(config, "lock_aliases", ()))

    def finalize(self, project: Project) -> list[Finding]:
        names = self.names
        findings: list[Finding] = []

        index = build_call_index(project)

        # per-function: qualified direct acquisitions + annotation set
        acquires: dict[tuple, list[tuple[str, int, frozenset[str]]]] = {}
        ann: dict[tuple, frozenset[str]] = {}
        for fk, (file, func) in index.funcs.items():
            view = project.owner_class(file, func)
            ann[fk] = annotation_locks(project, names, file, func)
            acquires[fk] = [
                (names.qualify(project, view, lock), line,
                 names.qualify_all(project, view, held))
                for lock, line, held in func.acquires
            ]

        # transitive acquisition sets with one witness site per lock
        trans: dict[tuple, dict[str, str]] = {
            fk: {
                lock: f"{index.funcs[fk][1].qualname} ({fk[0]}:{line})"
                for lock, line, _held in sorted(acq, key=lambda t: t[1])
            }
            for fk, acq in acquires.items()
        }
        changed = True
        while changed:
            changed = False
            for fk, resolved in index.resolved.items():
                mine = trans[fk]
                for call, callee_fk in resolved:
                    for lock, wit in trans.get(callee_fk, {}).items():
                        if lock not in mine:
                            mine[lock] = wit
                            changed = True

        # edges + self-deadlocks: (from, to) -> (desc, path, line)
        edges: dict[tuple[str, str], tuple[str, str, int]] = {}
        for fk in sorted(index.funcs):
            file, func = index.funcs[fk]
            held0 = ann[fk]
            for lock, line, held_before in acquires[fk]:
                held_all = held_before | held0
                for h in sorted(held_all):
                    if h == lock:
                        findings.append(Finding(
                            self.name, file.path, line, 0,
                            f"{lock} acquired in `{func.qualname}` while "
                            "already held along this chain — "
                            "threading.Lock is not reentrant; this "
                            "deadlocks the thread against itself",
                        ))
                    else:
                        edges.setdefault((h, lock), (
                            f"{func.qualname} ({file.path}:{line})",
                            file.path, line,
                        ))
            for call, callee_fk in index.resolved[fk]:
                view = project.owner_class(file, func)
                held_at = names.qualify_all(project, view, call.held) | held0
                if not held_at:
                    continue
                for lock, wit in trans.get(callee_fk, {}).items():
                    if lock in held_at:
                        findings.append(Finding(
                            self.name, file.path, call.line, call.col,
                            f"call from `{func.qualname}` while holding "
                            f"{lock} reaches its re-acquisition at {wit} — "
                            "threading.Lock is not reentrant; this "
                            "deadlocks the thread against itself",
                        ))
                    else:
                        for h in sorted(held_at):
                            edges.setdefault((h, lock), (
                                f"{func.qualname} "
                                f"({file.path}:{call.line}) -> {wit}",
                                file.path, call.line,
                            ))

        findings.extend(self._cycle_findings(edges))
        return findings

    def _cycle_findings(
            self, edges: dict[tuple[str, str], tuple[str, str, int]],
    ) -> list[Finding]:
        """One finding per distinct cycle, naming the full lock path and an
        example acquisition site per edge."""
        graph: dict[str, list[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, []).append(b)
        for a in graph:
            graph[a].sort()

        findings: list[Finding] = []
        seen_cycles: set[tuple[str, ...]] = set()
        for start in sorted(graph):
            cycle = self._find_cycle(graph, start)
            if cycle is None:
                continue
            # canonical rotation: start at the smallest lock name
            pivot = cycle.index(min(cycle))
            canon = tuple(cycle[pivot:] + cycle[:pivot])
            if canon in seen_cycles:
                continue
            seen_cycles.add(canon)
            path = [*canon, canon[0]]
            steps = []
            for a, b in zip(path, path[1:]):
                steps.append(f"{a} -> {b} at {edges[(a, b)][0]}")
            _desc, loc_path, loc_line = edges[(path[0], path[1])]
            findings.append(Finding(
                self.name, loc_path, loc_line, 0,
                "lock-order cycle " + " -> ".join(path) + " — two threads "
                "acquiring these locks from different ends deadlock; "
                "acquisition sites: " + "; ".join(steps),
            ))
        return findings

    @staticmethod
    def _find_cycle(graph: dict[str, list[str]],
                    start: str) -> list[str] | None:
        """Shortest cycle through ``start`` (BFS back to itself)."""
        queue: list[list[str]] = [[start]]
        visited = {start}
        while queue:
            path = queue.pop(0)
            for nxt in graph.get(path[-1], ()):
                if nxt == start:
                    return path
                if nxt not in visited:
                    visited.add(nxt)
                    queue.append(path + [nxt])
        return None

