"""traced-purity: no host calls inside jit/pjit/shard_map-lowered code.

Provenance: every engine program lowers through ``parallel/dispatch.lower``
(or ``jax.jit`` / ``compat.shard_map`` directly — sim/engine.py, PR 7), and
a host call inside a traced body is a classic silent bug: ``time.time()``
burns ONE timestamp into the compiled graph forever, ``np.random`` draws
once at trace time and replays the same "random" numbers every call,
``print`` fires at trace time only (then never again), ``datetime.now``
likewise. jax.debug.print / jax.random are the traced-safe counterparts.

Scope: per module — functions (a) decorated with ``jax.jit`` /
``partial(jax.jit, ...)``, or (b) passed by NAME as the first argument to
``jax.jit`` / ``compat.shard_map`` / ``dispatch.lower`` /
``jit_under_mesh`` / ``pallas_call``, plus every ``def`` nested inside
them. No interprocedural analysis: a helper called from a traced body is
only scanned if it is itself lowered — the rule catches the direct form.
"""

from __future__ import annotations

import ast

from fedml_tpu.analysis.core import Finding, Project, Rule, SourceFile

_LOWERING_ATTRS = frozenset({
    "jit", "shard_map", "lower", "jit_under_mesh", "pallas_call",
})


def _dotted(func: ast.expr) -> str | None:
    """`a.b.c` -> "a.b.c" (Name/Attribute chains only)."""
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(expr: ast.expr) -> bool:
    """`jax.jit`, `jit`, `partial(jax.jit, ...)`, `functools.partial(...)`."""
    dotted = _dotted(expr)
    if dotted in ("jax.jit", "jit"):
        return True
    if isinstance(expr, ast.Call):
        fn = _dotted(expr.func)
        if fn in ("partial", "functools.partial") and expr.args:
            return _is_jit_expr(expr.args[0])
    return False


class TracedPurityRule(Rule):
    name = "traced-purity"
    description = ("banned host calls (time.time, np.random.*, print, "
                   "datetime.now) inside jit/pjit/shard_map-lowered "
                   "functions; banned-module-calls entries ban a pattern "
                   "module-wide (e.g. np.random.* anywhere under "
                   "fedml_tpu/population/ — replay determinism)")

    def __init__(self, config):
        self.config = config
        self.banned = tuple(config.banned_traced_calls)
        # "<path-prefix>:<pattern>" module-wide bans (config.py)
        self.module_banned: list[tuple[str, str]] = []
        for entry in getattr(config, "banned_module_calls", ()):
            prefix, sep, pattern = entry.partition(":")
            if not sep or not prefix or not pattern:
                raise ValueError(
                    f"banned-module-calls entry {entry!r}: expected "
                    "'<path-prefix>:<call-pattern>'"
                )
            self.module_banned.append((prefix, pattern))

    @staticmethod
    def _match(dotted: str, pattern: str) -> bool:
        if pattern.endswith(".*"):
            return dotted.startswith(pattern[:-1])
        return dotted == pattern

    def _banned_match(self, dotted: str) -> str | None:
        for pattern in self.banned:
            if self._match(dotted, pattern):
                return pattern
        return None

    def check(self, file: SourceFile, project: Project) -> list[Finding]:
        traced_names: set[str] = set()
        lambdas: list[tuple[ast.Lambda, str]] = []
        defs: dict[str, list[ast.FunctionDef]] = {}
        for node in ast.walk(file.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                if any(_is_jit_expr(d) for d in node.decorator_list):
                    traced_names.add(node.name)
            elif isinstance(node, ast.Call):
                fn = _dotted(node.func)
                is_lowering = (
                    fn in ("jax.jit", "jit")
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _LOWERING_ATTRS)
                )
                if is_lowering and node.args:
                    target = node.args[0]
                    if isinstance(target, ast.Name):
                        traced_names.add(target.id)
                    elif isinstance(target, ast.Lambda):
                        lambdas.append((target, fn or node.func.attr))

        findings: list[Finding] = []

        def scan(body_node: ast.AST, owner: str) -> None:
            for sub in ast.walk(body_node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func)
                if dotted is None:
                    continue
                pattern = self._banned_match(dotted)
                if pattern is not None:
                    findings.append(Finding(
                        self.name, file.path, sub.lineno, sub.col_offset,
                        f"host call {dotted}() inside traced function "
                        f"`{owner}` (matches banned pattern {pattern!r}) — "
                        "traced programs must be pure: the value burns "
                        "into the compiled graph at trace time",
                    ))

        for name in sorted(traced_names):
            for fn_def in defs.get(name, []):
                scan(fn_def, name)
        for lam, via in lambdas:
            scan(lam, f"<lambda via {via}>")

        # module-wide bans: in files under a configured path prefix, the
        # banned pattern is illegal at ANY scope, not just traced bodies —
        # the population subsystem's replay-determinism contract (every
        # draw through its seeded rng, population/prng.py)
        module_patterns = [
            pat for prefix, pat in self.module_banned
            if file.path.replace("\\", "/").startswith(prefix)
        ]
        if module_patterns:
            seen = {(f.line, f.col) for f in findings}
            for sub in ast.walk(file.tree):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func)
                if dotted is None:
                    continue
                for pattern in module_patterns:
                    if not self._match(dotted, pattern):
                        continue
                    if (sub.lineno, sub.col_offset) in seen:
                        break
                    findings.append(Finding(
                        self.name, file.path, sub.lineno, sub.col_offset,
                        f"call {dotted}() matches pattern {pattern!r} "
                        f"banned module-wide under this path "
                        "(banned-module-calls) — draws here must flow "
                        "through the subsystem's seeded rng so trace "
                        "replay stays deterministic",
                    ))
                    break
        return findings
