"""traced-purity: no host calls inside jit/pjit/shard_map-lowered code.

Provenance: every engine program lowers through ``parallel/dispatch.lower``
(or ``jax.jit`` / ``compat.shard_map`` directly — sim/engine.py, PR 7), and
a host call inside a traced body is a classic silent bug: ``time.time()``
burns ONE timestamp into the compiled graph forever, ``np.random`` draws
once at trace time and replays the same "random" numbers every call,
``print`` fires at trace time only (then never again), ``datetime.now``
likewise. jax.debug.print / jax.random are the traced-safe counterparts.

Scope: per module — functions (a) decorated with ``jax.jit`` /
``partial(jax.jit, ...)``, or (b) passed by NAME as the first argument to
``jax.jit`` / ``compat.shard_map`` / ``dispatch.lower`` /
``jit_under_mesh`` / ``pallas_call``, plus every ``def`` nested inside
them. No interprocedural analysis: a helper called from a traced body is
only scanned if it is itself lowered — the rule catches the direct form.
"""

from __future__ import annotations

from fedml_tpu.analysis.core import Finding, Project, Rule
from fedml_tpu.analysis.facts import FileFacts


class TracedPurityRule(Rule):
    name = "traced-purity"
    description = ("banned host calls (time.time, np.random.*, print, "
                   "datetime.now) inside jit/pjit/shard_map-lowered "
                   "functions; banned-module-calls entries ban a pattern "
                   "module-wide (e.g. np.random.* anywhere under "
                   "fedml_tpu/population/ — replay determinism)")

    def __init__(self, config):
        self.config = config
        self.banned = tuple(config.banned_traced_calls)
        # "<path-prefix>:<pattern>" module-wide bans (config.py)
        self.module_banned: list[tuple[str, str]] = []
        for entry in getattr(config, "banned_module_calls", ()):
            prefix, sep, pattern = entry.partition(":")
            if not sep or not prefix or not pattern:
                raise ValueError(
                    f"banned-module-calls entry {entry!r}: expected "
                    "'<path-prefix>:<call-pattern>'"
                )
            self.module_banned.append((prefix, pattern))

    @staticmethod
    def _match(dotted: str, pattern: str) -> bool:
        if pattern.endswith(".*"):
            return dotted.startswith(pattern[:-1])
        return dotted == pattern

    def _banned_match(self, dotted: str) -> str | None:
        for pattern in self.banned:
            if self._match(dotted, pattern):
                return pattern
        return None

    def check(self, file: FileFacts, project: Project) -> list[Finding]:
        traced_names = {name for name, _via in file.lowered_names}
        findings: list[Finding] = []

        def scan(root_func, owner: str) -> None:
            for func in project.subtree(file, root_func):
                for call_idx in func.calls:
                    call = file.calls[call_idx]
                    if call.dotted is None:
                        continue
                    pattern = self._banned_match(call.dotted)
                    if pattern is None:
                        continue
                    findings.append(Finding(
                        self.name, file.path, call.line, call.col,
                        f"host call {call.dotted}() inside traced function "
                        f"`{owner}` (matches banned pattern {pattern!r}) — "
                        "traced programs must be pure: the value burns "
                        "into the compiled graph at trace time",
                    ))

        for func in file.functions:
            if func.kind == "lambda":
                if func.lowered_via is not None:
                    scan(func, f"<lambda via {func.lowered_via}>")
            elif func.jit_decorated or func.name in traced_names:
                scan(func, func.name)

        # module-wide bans: in files under a configured path prefix, the
        # banned pattern is illegal at ANY scope, not just traced bodies —
        # the population subsystem's replay-determinism contract (every
        # draw through its seeded rng, population/prng.py)
        module_patterns = [
            pat for prefix, pat in self.module_banned
            if file.path.replace("\\", "/").startswith(prefix)
        ]
        if module_patterns:
            seen = {(f.line, f.col) for f in findings}
            for call in file.calls:
                if call.dotted is None:
                    continue
                for pattern in module_patterns:
                    if not self._match(call.dotted, pattern):
                        continue
                    if (call.line, call.col) in seen:
                        break
                    findings.append(Finding(
                        self.name, file.path, call.line, call.col,
                        f"call {call.dotted}() matches pattern {pattern!r} "
                        f"banned module-wide under this path "
                        "(banned-module-calls) — draws here must flow "
                        "through the subsystem's seeded rng so trace "
                        "replay stays deterministic",
                    ))
                    break
        return findings
