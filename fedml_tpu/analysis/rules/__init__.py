"""Built-in fedlint rules (docs/STATIC_ANALYSIS.md is the catalog).

Each rule class is self-contained and stateful per run: ``make_rules``
builds FRESH instances for a given config — rule objects accumulate
cross-file state in ``collect`` and must never be shared between runs.
"""

from __future__ import annotations

from fedml_tpu.analysis.config import FedlintConfig
from fedml_tpu.analysis.core import Rule
from fedml_tpu.analysis.rules.blocking_under_lock import BlockingUnderLockRule
from fedml_tpu.analysis.rules.guarded_by import GuardedByRule
from fedml_tpu.analysis.rules.lock_order import LockOrderRule
from fedml_tpu.analysis.rules.metric_keys import MetricKeysRule
from fedml_tpu.analysis.rules.overwrite_after_super import OverwriteAfterSuperRule
from fedml_tpu.analysis.rules.thread_entry import ThreadEntryRule
from fedml_tpu.analysis.rules.traced_purity import TracedPurityRule
from fedml_tpu.analysis.rules.wire_contract import WireContractRule

_REGISTRY = {
    cls.name: cls
    for cls in (
        GuardedByRule,
        OverwriteAfterSuperRule,
        WireContractRule,
        TracedPurityRule,
        MetricKeysRule,
        LockOrderRule,
        BlockingUnderLockRule,
        ThreadEntryRule,
    )
}


def all_rules() -> dict[str, type[Rule]]:
    """Rule name -> class, the full registry (for --list-rules)."""
    return dict(_REGISTRY)


def make_rules(config: FedlintConfig) -> list[Rule]:
    """Fresh rule instances for the config's ``select`` list, in registry
    order. Unknown names raise — a typo in pyproject must not silently
    skip a gate."""
    unknown = [name for name in config.select if name not in _REGISTRY]
    if unknown:
        raise ValueError(
            f"unknown fedlint rule(s) {unknown}; known: {sorted(_REGISTRY)}"
        )
    return [
        _REGISTRY[name](config) for name in _REGISTRY if name in config.select
    ]
