"""Shared machinery for the interprocedural concurrency rules.

Lock identity: a ``with self.<attr>:`` site names a lock by attribute; the
rules qualify it to ``<RootDeclaringClass>.<attr>`` via
:meth:`~fedml_tpu.analysis.core.Project.lock_id` so every class in one
diamond names the shared lock identically, and two unrelated classes that
both call their lock ``_lock`` stay distinct nodes in the acquisition
graph. ``[tool.fedlint] lock-aliases`` (``"<from>=<to>"`` entries) merges
spellings that alias ONE runtime lock: a bare ``attr=attr2`` entry renames
the attribute before qualification, a qualified ``Class.attr=Class2.attr2``
entry rewrites the final id.

Annotation semantics: ``# lock-held: <lock>`` on a method is a CLAIM that
every caller holds the lock — the intraprocedural rules treat it as held,
and the thread-entry rule is the one that checks the claim against real
call paths.
"""

from __future__ import annotations

import dataclasses

from fedml_tpu.analysis.core import Project
from fedml_tpu.analysis.facts import CallFact, FileFacts, FuncFact


class LockNames:
    """Qualified, alias-canonical lock naming for one rule run."""

    def __init__(self, aliases: tuple[str, ...] = ()):
        self.bare: dict[str, str] = {}
        self.full: dict[str, str] = {}
        for entry in aliases:
            src, sep, dst = entry.partition("=")
            src, dst = src.strip(), dst.strip()
            if not sep or not src or not dst:
                raise ValueError(
                    f"lock-aliases entry {entry!r}: expected '<from>=<to>'"
                )
            if "." in src:
                self.full[src] = dst
            else:
                self.bare[src] = dst

    def qualify(self, project: Project, view, attr: str) -> str:
        """Canonical lock id for ``self.<attr>`` in the given class."""
        attr = self.bare.get(attr, attr)
        if "." in attr:  # bare alias mapped straight to a qualified id
            return self.full.get(attr, attr)
        lid = project.lock_id(view, attr)
        return self.full.get(lid, lid)

    def qualify_all(self, project: Project, view,
                    attrs) -> frozenset[str]:
        return frozenset(self.qualify(project, view, a) for a in attrs)


def annotation_locks(project: Project, names: LockNames, file: FileFacts,
                     func: FuncFact) -> frozenset[str]:
    """Qualified ``# lock-held:`` locks for a function: methods inherit the
    annotation along the base chain (an un-annotated override keeps the
    contract), nested defs/lambdas carry only their own annotation."""
    view = project.owner_class(file, func)
    if func.cls != -1 and view is not None:
        attrs = project.effective_lock_held(view, func.name)
    else:
        attrs = func.lock_held
    if not attrs:
        return frozenset()
    return names.qualify_all(project, view, attrs)


def site(file: FileFacts, func: FuncFact, line: int) -> str:
    return f"{func.qualname} ({file.path}:{line})"


def func_key(file: FileFacts, func: FuncFact) -> tuple[str, int]:
    return (file.path, func.index)


@dataclasses.dataclass
class CallIndex:
    """Whole-program function table + resolved call edges, built ONCE per
    rule run — the shared scaffolding of all three concurrency rules.

    ``funcs``: function key -> (file, func). ``resolved``: function key ->
    ``(call_fact, callee_key)`` rows for every call the project can
    resolve (unresolvable calls are dropped here — the rules never see
    them, which is the documented under-approximation)."""

    funcs: dict[tuple[str, int], tuple[FileFacts, FuncFact]]
    resolved: dict[tuple[str, int], list[tuple[CallFact, tuple[str, int]]]]


def build_call_index(project: Project) -> CallIndex:
    """Memoized per Project: all three concurrency rules share one index
    (it depends only on the project, and projects are per-run)."""
    cached = getattr(project, "_call_index", None)
    if cached is not None:
        return cached
    funcs: dict[tuple[str, int], tuple[FileFacts, FuncFact]] = {}
    resolved: dict[tuple[str, int], list] = {}
    for file in project.files:
        for func in file.functions:
            fk = func_key(file, func)
            funcs[fk] = (file, func)
            rows = []
            for call_idx in func.calls:
                call = file.calls[call_idx]
                callee = project.resolve_call(file, call)
                if callee is not None:
                    rows.append((call, func_key(*callee)))
            resolved[fk] = rows
    project._call_index = CallIndex(funcs, resolved)
    return project._call_index
