"""thread-entry: thread/timer callbacks must not assume caller-held locks.

Provenance: the guarded-by rule's nested-def discipline ("closures are
checked with NO locks held, because they run later, on whatever thread
calls them") generalized interprocedurally. The wire-path runtime hands
named functions — not just closures — to ``threading.Thread`` (heartbeat
loops, client run loops, send-pool workers), ``threading.Timer`` (round
closes, share timeouts, delayed fault delivery), and pool dispatch
(``run_all``/``submit``). Those entries START WITH NO LOCKS HELD, so:

- a function reachable from a thread entry that is annotated
  ``# lock-held: <lock>`` — i.e. CLAIMS every caller holds the lock — is a
  finding unless every path from the entry actually acquires the lock
  before the call (``with self.<lock>:`` around the call site, at any
  depth along the chain). The annotation would be a lie on that path, and
  every guarded-field touch the annotation blesses is a race.

The rule walks the resolved call graph from each entry, tracking the locks
actually acquired along the path; it never guesses unresolvable calls
(dynamic dispatch, bound methods of other objects), so it UNDER-reports
rather than false-positives — see docs/STATIC_ANALYSIS.md for the limits.
"""

from __future__ import annotations

from fedml_tpu.analysis.core import Finding, Project, Rule
from fedml_tpu.analysis.rules._concurrency import (
    LockNames,
    annotation_locks,
    build_call_index,
    func_key,
)


class ThreadEntryRule(Rule):
    name = "thread-entry"
    description = ("functions reachable from thread/timer/pool entry "
                   "points must not assume caller-held locks "
                   "(# lock-held:) unless the path actually acquires them")

    def __init__(self, config):
        self.config = config
        self.names = LockNames(getattr(config, "lock_aliases", ()))

    def finalize(self, project: Project) -> list[Finding]:
        names = self.names
        findings: list[Finding] = []
        reported: set[tuple[str, int, frozenset[str]]] = set()
        index = build_call_index(project)

        entries = sorted(
            project.thread_entries(),
            key=lambda e: (e[4], e[3], e[0].path, e[1].index),
        )
        for entry_file, entry_func, via, reg_line, reg_path in entries:
            entry_desc = (
                f"{via} entry `{entry_func.qualname}` "
                f"(registered at {reg_path}:{reg_line})"
            )
            # DFS over the resolved call graph, tracking locks actually
            # acquired along the path
            stack = [(entry_file, entry_func, frozenset())]
            visited: set[tuple[str, int, frozenset[str]]] = set()
            while stack:
                file, func, held = stack.pop()
                state = (file.path, func.index, held)
                if state in visited:
                    continue
                visited.add(state)
                ann = annotation_locks(project, names, file, func)
                missing = ann - held
                report_key = (file.path, func.index, missing)
                if missing and report_key not in reported:
                    reported.add(report_key)
                    findings.append(Finding(
                        self.name, file.path, func.line, func.col,
                        f"`{func.qualname}` assumes caller-held "
                        f"{', '.join(sorted(missing))} (# lock-held:) but "
                        f"is reachable from the {entry_desc} without "
                        "acquiring it — thread entries start with no locks "
                        "held, so every guarded field the annotation "
                        "blesses races here; take the lock explicitly or "
                        "drop the annotation",
                    ))
                # continue assuming the annotation (reported once above) to
                # avoid cascading findings down the same chain
                base = held | ann
                view = project.owner_class(file, func)
                for call, callee_fk in index.resolved[func_key(file, func)]:
                    next_held = base | names.qualify_all(
                        project, view, call.held)
                    stack.append((*index.funcs[callee_fk], next_held))
        return findings
