"""metric-keys: canonical Comm/ Robust/ Async/ Fleet/ record keys only.

Provenance: ``obs/metrics.py`` is the single home of the canonical metric
namespace ("Canonical bytes-on-wire metric keys", PR 1/6/9) — the sim
engine, the wire-path servers, the smokes, and the report renderers all
join records BY these strings, so an ad-hoc literal (``"Robust/ClipFrac"``
vs ``ROBUST_CLIP_FRACTION``) silently forks the stream: the record lands,
nothing joins it, and the dashboard reads zero. Any string literal under a
canonical prefix outside the defining module(s) is a finding — spell it
``metricslib.<CONSTANT>``.

Literals containing whitespace are ignored: prose in docstrings may
mention a key family ("the Async/* totals") without naming a record key —
record keys never contain spaces.
"""

from __future__ import annotations

import ast

from fedml_tpu.analysis.core import Finding, Project, Rule, SourceFile


class MetricKeysRule(Rule):
    name = "metric-keys"
    description = ("Comm/ Robust/ Async/ Fleet/ record keys must come from "
                   "the obs.metrics constants, not ad-hoc literals")

    def __init__(self, config):
        self.config = config
        self.prefixes = tuple(config.metric_prefixes)
        self.modules = {m.replace("\\", "/") for m in config.metric_modules}

    def check(self, file: SourceFile, project: Project) -> list[Finding]:
        path = file.path.replace("\\", "/")
        if any(path.endswith(module) for module in self.modules):
            return []
        findings: list[Finding] = []
        for node in ast.walk(file.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            value = node.value
            if any(ch.isspace() for ch in value):
                continue
            if value.startswith(self.prefixes):
                findings.append(Finding(
                    self.name, file.path, node.lineno, node.col_offset,
                    f"ad-hoc metric key literal {value!r} — import the "
                    "constant from fedml_tpu.obs.metrics (records join by "
                    "these strings; a fork reads as zero downstream)",
                ))
        return findings
