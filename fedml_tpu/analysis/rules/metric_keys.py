"""metric-keys: canonical Comm/ Robust/ Async/ Fleet/ record keys only —
and no DEAD keys in the canonical namespace.

Provenance: ``obs/metrics.py`` is the single home of the canonical metric
namespace ("Canonical bytes-on-wire metric keys", PR 1/6/9) — the sim
engine, the wire-path servers, the smokes, and the report renderers all
join records BY these strings, so an ad-hoc literal (``"Robust/ClipFrac"``
vs ``ROBUST_CLIP_FRACTION``) silently forks the stream: the record lands,
nothing joins it, and the dashboard reads zero. Any string literal under a
canonical prefix outside the defining module(s) is a finding — spell it
``metricslib.<CONSTANT>``.

Dead-metric check (the other direction of the same rot): a constant
DEFINED under a canonical prefix in the defining module must be (a)
referenced by some emitting module — a key nobody emits is dead namespace
surface — and (b) consumed somewhere: referenced by a configured reader
tool (``metric-reader-modules``) or named in a docs table
(``metric-doc-paths``). A key that is emitted but never read anywhere is
exactly the silent metric rot this rule exists to kill: records land,
nothing joins them, nobody notices.

Literals containing whitespace are ignored: prose in docstrings may
mention a key family ("the Async/* totals") without naming a record key —
record keys never contain spaces.
"""

from __future__ import annotations

from pathlib import Path

from fedml_tpu.analysis.core import Finding, Project, Rule
from fedml_tpu.analysis.facts import FileFacts


class MetricKeysRule(Rule):
    name = "metric-keys"
    description = ("Comm/ Robust/ Async/ Fleet/ record keys must come from "
                   "the obs.metrics constants, not ad-hoc literals; defined "
                   "keys must be emitted somewhere and read by a report "
                   "tool or docs table (no silent metric rot)")

    def __init__(self, config):
        self.config = config
        self.prefixes = tuple(config.metric_prefixes)
        self.modules = {m.replace("\\", "/") for m in config.metric_modules}
        self.reader_modules = {
            m.replace("\\", "/")
            for m in getattr(config, "metric_reader_modules", ())
        }
        self.doc_paths = tuple(getattr(config, "metric_doc_paths", ()))
        # defining module: NAME -> (value, path, line, col)
        self.defs: dict[str, tuple[str, str, int, int]] = {}
        # NAMEs referenced outside the defining/reader modules (emitters)
        self.emitted: set[str] = set()
        # NAMEs referenced by reader modules
        self.read_by_tools: set[str] = set()

    def _is_metric_module(self, path: str) -> bool:
        path = path.replace("\\", "/")
        return any(path.endswith(m) for m in self.modules)

    def _is_reader_module(self, path: str) -> bool:
        path = path.replace("\\", "/")
        return any(path.endswith(m) for m in self.reader_modules)

    def collect(self, file: FileFacts, project: Project) -> None:
        if self._is_metric_module(file.path):
            for name, value, line, col in file.module_consts:
                if value.startswith(self.prefixes):
                    self.defs.setdefault(name, (value, file.path, line, col))
        elif self._is_reader_module(file.path):
            self.read_by_tools |= file.upper_refs
        else:
            self.emitted |= file.upper_refs

    def check(self, file: FileFacts, project: Project) -> list[Finding]:
        if self._is_metric_module(file.path):
            return []
        findings: list[Finding] = []
        for value, line, col in file.str_consts:
            if value.startswith(self.prefixes):
                findings.append(Finding(
                    self.name, file.path, line, col,
                    f"ad-hoc metric key literal {value!r} — import the "
                    "constant from fedml_tpu.obs.metrics (records join by "
                    "these strings; a fork reads as zero downstream)",
                ))
        return findings

    def finalize(self, project: Project) -> list[Finding]:
        if not self.defs:
            return []
        docs_text = self._docs_text(project)
        findings: list[Finding] = []
        for name, (value, path, line, col) in sorted(self.defs.items()):
            if name not in self.emitted:
                findings.append(Finding(
                    self.name, path, line, col,
                    f"metric key {name} ({value!r}) is defined but never "
                    "emitted — no scanned module references the constant; "
                    "dead namespace surface (delete it or emit it)",
                ))
                continue
            if name not in self.read_by_tools and value not in docs_text:
                findings.append(Finding(
                    self.name, path, line, col,
                    f"metric key {name} ({value!r}) is emitted but never "
                    "read — no report tool references it and no docs table "
                    "names it; records land and nothing joins them "
                    "(silent metric rot)",
                ))
        return findings

    def _docs_text(self, project: Project) -> str:
        """Concatenated text of the configured docs paths (markdown tables
        count as readers — dashboards are built from them)."""
        chunks: list[str] = []
        root = project.root or Path(".")
        for rel in self.doc_paths:
            p = Path(rel)
            if not p.is_absolute():
                p = Path(root) / rel
            candidates = sorted(p.rglob("*.md")) if p.is_dir() else [p]
            for doc in candidates:
                try:
                    chunks.append(doc.read_text())
                except OSError:
                    continue
        return "\n".join(chunks)
