"""wire-contract: every MSG_ARG_KEY_* is written AND read; no raw keys.

Provenance: the typed-message wire contract of ``comm/message.py`` and the
protocol classes built on it (``MyMessage``, ``TreeMessage``,
``ClientStatus``) — CHANGES.md PR 5/9 document hard-won compatibilities
(version echo vs round index, header-only telemetry scalars) that all
hang off these key constants. Three checks:

- a defined ``MSG_ARG_KEY_*`` constant must be WRITTEN somewhere
  (``add_params(KEY, ...)`` or a dict-literal key) and READ somewhere
  (``.get(KEY)`` / subscript) across the scanned tree — a write-only key
  is dead wire weight, a read-only key is a silent ``None`` at every
  receiver;
- no raw string literal may duplicate a key's VALUE — two spellings of
  one wire field drift independently (alias constants that reference
  another class's key are fine and resolve to the same canonical name);
- ``add_params`` must not take a raw string literal key at all: ad-hoc
  wire fields bypass the contract entirely.
"""

from __future__ import annotations

from fedml_tpu.analysis.core import Finding, Project, Rule
from fedml_tpu.analysis.facts import FileFacts


class WireContractRule(Rule):
    name = "wire-contract"
    description = ("MSG_ARG_KEY_* constants must be both written and read; "
                   "no raw string literal may duplicate or replace one")

    def __init__(self, config):
        self.config = config
        # canonical name -> (value, path, line, col)
        self.defs: dict[str, tuple[str, str, int, int]] = {}
        # canonical value -> canonical name (first definition wins)
        self.values: dict[str, str] = {}
        # usage tallies per key name
        self.written: set[str] = set()
        self.read: set[str] = set()

    # -- pass 1: definitions + usages ---------------------------------------

    def collect(self, file: FileFacts, project: Project) -> None:
        for cf in file.classes:
            for name, (value, line, col, _vl, _vc) in cf.wire_defs.items():
                self.defs.setdefault(name, (value, file.path, line, col))
                self.values.setdefault(value, name)
        # alias definitions (`MyMessage.K = Message.K`) need no tracking:
        # both spellings share the attribute name, so usage sites of either
        # already tally against the same canonical key
        self.written |= file.wire_written
        self.read |= file.wire_read

    # -- pass 2 -------------------------------------------------------------

    def check(self, file: FileFacts, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for value, line, col in file.str_consts:
            if value not in self.values:
                continue
            if (line, col) in file.wire_def_sites:
                continue
            findings.append(Finding(
                self.name, file.path, line, col,
                f"raw string {value!r} duplicates wire key "
                f"{self.values[value]} — use the constant (two "
                "spellings of one wire field drift independently)",
            ))
        for value, line, col in file.add_params_literals:
            if value in self.values:
                continue  # reported above as a duplicate literal
            findings.append(Finding(
                self.name, file.path, line, col,
                f"ad-hoc wire key {value!r} passed to "
                "add_params — define a MSG_ARG_KEY_* constant so the "
                "field is part of the checked contract",
            ))
        return findings

    def finalize(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for name, (value, path, line, col) in sorted(self.defs.items()):
            if name not in self.written:
                findings.append(Finding(
                    self.name, path, line, col,
                    f"wire key {name} ({value!r}) is never written "
                    "(no add_params/dict-key site in the scanned tree) — "
                    "dead contract surface",
                ))
            if name not in self.read:
                findings.append(Finding(
                    self.name, path, line, col,
                    f"wire key {name} ({value!r}) is never read "
                    "(no .get/subscript site in the scanned tree) — every "
                    "receiver sees None",
                ))
        return findings
