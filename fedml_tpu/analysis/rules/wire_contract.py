"""wire-contract: every MSG_ARG_KEY_* is written AND read; no raw keys.

Provenance: the typed-message wire contract of ``comm/message.py`` and the
protocol classes built on it (``MyMessage``, ``TreeMessage``,
``ClientStatus``) — CHANGES.md PR 5/9 document hard-won compatibilities
(version echo vs round index, header-only telemetry scalars) that all
hang off these key constants. Three checks:

- a defined ``MSG_ARG_KEY_*`` constant must be WRITTEN somewhere
  (``add_params(KEY, ...)`` or a dict-literal key) and READ somewhere
  (``.get(KEY)`` / subscript) across the scanned tree — a write-only key
  is dead wire weight, a read-only key is a silent ``None`` at every
  receiver;
- no raw string literal may duplicate a key's VALUE — two spellings of
  one wire field drift independently (alias constants that reference
  another class's key are fine and resolve to the same canonical name);
- ``add_params`` must not take a raw string literal key at all: ad-hoc
  wire fields bypass the contract entirely.
"""

from __future__ import annotations

import ast
import re

from fedml_tpu.analysis.core import Finding, Project, Rule, SourceFile

_KEY_RE = re.compile(r"^MSG_ARG_KEY_\w+$")


class WireContractRule(Rule):
    name = "wire-contract"
    description = ("MSG_ARG_KEY_* constants must be both written and read; "
                   "no raw string literal may duplicate or replace one")

    def __init__(self, config):
        self.config = config
        # canonical name -> (value, path, line, col)
        self.defs: dict[str, tuple[str, str, int, int]] = {}
        # canonical value -> canonical name (first definition wins)
        self.values: dict[str, str] = {}
        # positions of the defining Constant nodes (skipped by the
        # duplicate-literal scan): (path, line, col)
        self.def_value_sites: set[tuple[str, int, int]] = set()
        # usage tallies per key name
        self.written: set[str] = set()
        self.read: set[str] = set()

    # -- pass 1: definitions + usages ---------------------------------------

    def collect(self, file: SourceFile, project: Project) -> None:
        for node in ast.walk(file.tree):
            if isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    self._collect_def(file, stmt)
        for node in ast.walk(file.tree):
            if isinstance(node, ast.Call):
                self._collect_call(node)
            elif isinstance(node, ast.Subscript):
                self._mark(node.slice, read=True, written=True)
            elif isinstance(node, ast.Dict):
                for key in node.keys:
                    if key is not None:
                        self._mark(key, written=True)
            elif isinstance(node, ast.Compare):
                for comp in [node.left, *node.comparators]:
                    self._mark(comp, read=True, written=True)

    def _collect_def(self, file: SourceFile, stmt: ast.stmt) -> None:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
            return
        target = stmt.targets[0]
        if not (isinstance(target, ast.Name) and _KEY_RE.match(target.id)):
            return
        if isinstance(stmt.value, ast.Constant) and isinstance(
                stmt.value.value, str):
            value = stmt.value.value
            self.defs.setdefault(
                target.id, (value, file.path, stmt.lineno, stmt.col_offset)
            )
            self.values.setdefault(value, target.id)
            self.def_value_sites.add(
                (file.path, stmt.value.lineno, stmt.value.col_offset)
            )
        # alias definitions (`MyMessage.K = Message.K`) need no tracking:
        # both spellings share the attribute name, so usage sites of either
        # already tally against the same canonical key

    def _key_name(self, node: ast.expr) -> str | None:
        if isinstance(node, ast.Attribute) and _KEY_RE.match(node.attr):
            return node.attr
        if isinstance(node, ast.Name) and _KEY_RE.match(node.id):
            return node.id
        return None

    def _mark(self, node: ast.expr, read: bool = False,
              written: bool = False) -> None:
        name = self._key_name(node)
        if name is None:
            return
        if read:
            self.read.add(name)
        if written:
            self.written.add(name)

    def _collect_call(self, node: ast.Call) -> None:
        func = node.func
        if not isinstance(func, ast.Attribute) or not node.args:
            return
        if func.attr == "add_params":
            self._mark(node.args[0], written=True)
        elif func.attr in ("get", "pop"):
            self._mark(node.args[0], read=True)
        else:
            # any other call position (pack helpers, encode framing):
            # conservatively counts as both — the rule targets NEVER-used
            # directions, not exotic plumbing
            for arg in node.args:
                self._mark(arg, read=True, written=True)

    # -- pass 2 -------------------------------------------------------------

    def check(self, file: SourceFile, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(file.tree):
            if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                    and node.value in self.values):
                site = (file.path, node.lineno, node.col_offset)
                if site in self.def_value_sites:
                    continue
                findings.append(Finding(
                    self.name, file.path, node.lineno, node.col_offset,
                    f"raw string {node.value!r} duplicates wire key "
                    f"{self.values[node.value]} — use the constant (two "
                    "spellings of one wire field drift independently)",
                ))
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_params" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value not in self.values):
                findings.append(Finding(
                    self.name, file.path, node.args[0].lineno,
                    node.args[0].col_offset,
                    f"ad-hoc wire key {node.args[0].value!r} passed to "
                    "add_params — define a MSG_ARG_KEY_* constant so the "
                    "field is part of the checked contract",
                ))
        return findings

    def finalize(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for name, (value, path, line, col) in sorted(self.defs.items()):
            if name not in self.written:
                findings.append(Finding(
                    self.name, path, line, col,
                    f"wire key {name} ({value!r}) is never written "
                    "(no add_params/dict-key site in the scanned tree) — "
                    "dead contract surface",
                ))
            if name not in self.read:
                findings.append(Finding(
                    self.name, path, line, col,
                    f"wire key {name} ({value!r}) is never read "
                    "(no .get/subscript site in the scanned tree) — every "
                    "receiver sees None",
                ))
        return findings
