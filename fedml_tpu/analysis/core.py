"""fedlint core: shared facts extraction, whole-program index, waivers.

v1 gave each rule the raw per-file AST; v2 runs ONE extraction pass per
file (:mod:`fedml_tpu.analysis.facts`) and hands every rule the same
JSON-serializable :class:`~fedml_tpu.analysis.facts.FileFacts` — which is
also what the incremental cache (:mod:`fedml_tpu.analysis.cache`) persists,
so a warm run never re-parses an unchanged file. Rules still run in two
passes — ``collect`` (per file, builds cross-file state) then
``check``/``finalize`` (emit findings) — so contracts that span files (wire
keys written in one module and read in another, lock annotations inherited
across the class diamond) need no per-rule file ordering.

On top of the per-class index, :class:`Project` now carries the
whole-program machinery the concurrency rules need:

- a function/method index covering methods, module-level functions, nested
  defs, and lambdas;
- call-graph resolution for ``self.<m>()`` (through the class diamond,
  nearest override first), bare-name calls (nested defs in enclosing
  scopes, then module-level functions), with everything else — dynamic
  dispatch, ``getattr``, calls on non-``self`` objects — left UNRESOLVED by
  design (documented limit: the analysis under-approximates the call
  graph, it never guesses);
- the thread-entry set: callables handed to ``threading.Thread`` /
  ``threading.Timer`` / pool dispatch (``run_all``/``submit``), which run
  later with no locks held;
- lock identity: ``with self.<attr>:`` sites are qualified to the ROOT-most
  class in the hierarchy whose ``__init__`` assigns the attr, so a base's
  lock and a subclass's acquisition of it are the same node in the
  lock-order graph (``[tool.fedlint] lock-aliases`` can merge attr
  spellings that alias one runtime lock).

Waivers: ``# fedlint: disable=<rule>[,<rule>...] -- <justification>`` on
the finding's line (or a standalone comment on the line above) suppresses
the finding but keeps it enumerable in the report. A waiver WITHOUT a
justification is itself a finding (rule ``waiver``), as is a waiver that
suppresses nothing — waivers must stay honest and minimal.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

from fedml_tpu.analysis.facts import (
    ClassFact,
    FileFacts,
    FuncFact,
    extract_facts,
)

# annotation / directive comment grammar (docs/STATIC_ANALYSIS.md)
_WAIVER_RE = re.compile(
    r"#\s*fedlint:\s*disable=([\w\-,\s]+?)(?:\s*--\s*(.+))?\s*$"
)
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([\w]+)")
_LOCK_HELD_RE = re.compile(r"#\s*lock-held:\s*([\w,\s]+)")


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    waived: bool = False
    waiver_reason: str | None = None

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "waived": self.waived,
            "waiver_reason": self.waiver_reason,
        }


@dataclasses.dataclass
class Waiver:
    """One ``# fedlint: disable=`` directive."""

    path: str
    line: int
    rules: tuple[str, ...]
    reason: str | None
    used: bool = False

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rules": list(self.rules),
            "reason": self.reason,
            "used": self.used,
        }


class SourceFile:
    """A parsed module: tree + per-line comments + waiver directives.

    Exists only on the COLD path — :func:`run_analysis` parses a file into
    a SourceFile, extracts its :class:`~fedml_tpu.analysis.facts.FileFacts`,
    and from then on every rule (and the cache) sees facts only."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        # lineno -> full comment text (tokenize: '#' inside strings is NOT
        # a comment); a line holds at most one comment token
        self.comments: dict[int, str] = {}
        # lines whose only content is a comment (standalone): a waiver or
        # annotation here applies to the NEXT line's statement
        self.standalone_comments: set[int] = set()
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                line_no = tok.start[0]
                self.comments[line_no] = tok.string
                if tok.line[: tok.start[1]].strip() == "":
                    self.standalone_comments.add(line_no)
        self.waivers: dict[int, Waiver] = {}
        for line_no, comment in self.comments.items():
            m = _WAIVER_RE.search(comment)
            if m:
                rules = tuple(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                reason = m.group(2)
                self.waivers[line_no] = Waiver(
                    self.path, line_no, rules,
                    reason.strip() if reason else None,
                )

    def comment_on(self, line: int) -> str | None:
        return self.comments.get(line)

    def guarded_annotation(self, line: int) -> str | None:
        """``# guarded-by: <lock>`` on this line (or standalone above)."""
        return self._annotation(_GUARDED_RE, line)

    def lock_held_annotation(self, line: int) -> list[str]:
        """``# lock-held: <lock>[, <lock>...]`` on this line (or above)."""
        hit = self._annotation(_LOCK_HELD_RE, line)
        if hit is None:
            return []
        return [name.strip() for name in hit.split(",") if name.strip()]

    def _annotation(self, pattern: re.Pattern, line: int) -> str | None:
        for candidate in (line, line - 1):
            comment = self.comments.get(candidate)
            if comment is None:
                continue
            if candidate == line - 1 and candidate not in self.standalone_comments:
                continue
            m = pattern.search(comment)
            if m:
                return m.group(1)
        return None


@dataclasses.dataclass
class ClassView:
    """One class definition: its facts plus the file that holds them."""

    facts: ClassFact
    file: FileFacts

    @property
    def name(self) -> str:
        return self.facts.name

    @property
    def bases(self) -> tuple[str, ...]:
        return self.facts.bases

    @property
    def guarded(self) -> dict[str, str]:
        return self.facts.guarded

    @property
    def lock_held(self) -> dict[str, tuple[str, ...]]:
        return self.facts.lock_held


class Project:
    """Whole-program index: classes, functions, resolved call edges."""

    def __init__(self):
        self.files: list[FileFacts] = []
        self.root: Path | None = None
        # EVERY class definition — duplicate simple names included, so a
        # name collision (two flax modules called SqueezeExcite, say) can
        # never silently exempt the later class from the per-class rules
        self.all_classes: list[ClassView] = []
        # simple name -> first definition, for base resolution only
        # (deterministic because files arrive sorted)
        self.classes: dict[str, ClassView] = {}
        self._by_path: dict[str, FileFacts] = {}
        self._views: dict[tuple[str, int], ClassView] = {}
        # path -> name -> module-level function index
        self._module_funcs: dict[str, dict[str, int]] = {}
        # path -> parent func index -> name -> first child index
        self._named_children: dict[str, dict[int, dict[str, int]]] = {}
        # path -> parent func index -> all child indices (subtree walks)
        self._all_children: dict[str, dict[int, list[int]]] = {}
        # memoized whole-program call index (rules/_concurrency.py)
        self._call_index = None

    def index(self, files: list[FileFacts]) -> None:
        self.files = files
        for file in files:
            self._by_path[file.path] = file
            for cf in file.classes:
                view = ClassView(cf, file)
                self.all_classes.append(view)
                self.classes.setdefault(cf.name, view)
                self._views[(file.path, cf.index)] = view
            module_funcs: dict[str, int] = {}
            named: dict[int, dict[str, int]] = {}
            children: dict[int, list[int]] = {}
            for ff in file.functions:
                if ff.cls == -1 and ff.parent == -1 and ff.kind != "lambda":
                    module_funcs.setdefault(ff.name, ff.index)
                if ff.parent != -1:
                    named.setdefault(ff.parent, {}).setdefault(
                        ff.name, ff.index)
                    children.setdefault(ff.parent, []).append(ff.index)
            self._module_funcs[file.path] = module_funcs
            self._named_children[file.path] = named
            self._all_children[file.path] = children

    # -- class hierarchy -----------------------------------------------------

    def view_of(self, file: FileFacts, cls_index: int) -> ClassView:
        return self._views[(file.path, cls_index)]

    def ancestors(self, info: ClassView) -> list[ClassView]:
        """Transitive base classes resolvable by simple name, nearest
        first; cycles and unknown bases are skipped."""
        out: list[ClassView] = []
        seen = {info.name}
        queue = list(info.bases)
        while queue:
            base = queue.pop(0)
            if base in seen:
                continue
            seen.add(base)
            base_info = self.classes.get(base)
            if base_info is None:
                continue
            out.append(base_info)
            queue.extend(base_info.bases)
        return out

    def effective_guarded(self, info: ClassView) -> dict[str, str]:
        """A class's guarded-field map, own declarations first, then
        inherited ones (the subclass may re-declare under another lock)."""
        merged: dict[str, str] = {}
        for ci in [info, *self.ancestors(info)]:
            for attr, lock in ci.guarded.items():
                merged.setdefault(attr, lock)
        return merged

    def effective_lock_held(self, info: ClassView,
                            method: str) -> tuple[str, ...]:
        """``# lock-held:`` annotation for a method, inherited along the
        base chain (an override of a lock-held method keeps the contract
        unless it re-annotates)."""
        for ci in [info, *self.ancestors(info)]:
            if method in ci.lock_held:
                return ci.lock_held[method]
        return ()

    # -- function index / call graph -----------------------------------------

    def owner_class(self, file: FileFacts,
                    func: FuncFact) -> ClassView | None:
        """The class a function body belongs to lexically: the method's
        class, also for defs/lambdas nested inside a method."""
        f = func
        while f.cls == -1 and f.parent != -1:
            f = file.functions[f.parent]
        if f.cls != -1:
            return self.view_of(file, f.cls)
        return None

    def resolve_method(self, view: ClassView,
                       name: str) -> tuple[FileFacts, FuncFact] | None:
        """``self.<name>()`` resolution: own method table first, then the
        base chain (nearest ancestor wins — static MRO approximation)."""
        for ci in [view, *self.ancestors(view)]:
            idx = ci.facts.methods.get(name)
            if idx is not None:
                return ci.file, ci.file.functions[idx]
        return None

    def resolve_ref(self, file: FileFacts, owner_func: int,
                    ref: tuple[str, str]) -> tuple[FileFacts, FuncFact] | None:
        """Resolve a callable reference from inside ``owner_func``.

        ``("self", m)`` resolves through the lexical class's diamond;
        ``("name", n)`` resolves nested defs in enclosing scopes (nearest
        first), then module-level functions of the same file. Anything else
        is unresolved — the call graph under-approximates by design."""
        kind, name = ref
        if kind == "self":
            if owner_func < 0:
                return None
            view = self.owner_class(file, file.functions[owner_func])
            if view is None:
                return None
            return self.resolve_method(view, name)
        if kind == "name":
            named = self._named_children.get(file.path, {})
            cursor = owner_func
            while cursor != -1:
                idx = named.get(cursor, {}).get(name)
                if idx is not None:
                    return file, file.functions[idx]
                cursor = file.functions[cursor].parent
            idx = self._module_funcs.get(file.path, {}).get(name)
            if idx is not None:
                return file, file.functions[idx]
        return None

    def resolve_call(self, file: FileFacts,
                     call) -> tuple[FileFacts, FuncFact] | None:
        if call.target is None:
            return None
        return self.resolve_ref(file, call.func, call.target)

    def subtree(self, file: FileFacts, func: FuncFact):
        """``func`` plus every def/lambda nested inside it."""
        children = self._all_children.get(file.path, {})
        stack = [func.index]
        while stack:
            idx = stack.pop()
            yield file.functions[idx]
            stack.extend(children.get(idx, ()))

    def thread_entries(self):
        """Resolved thread-entry functions: ``(file, func, via, line,
        registered_in)`` for every callable handed to a thread constructor,
        timer, or pool dispatch anywhere in the project."""
        out = []
        seen: set[tuple[str, int]] = set()
        for file in self.files:
            for via, ref, line, owner in file.thread_entries:
                resolved = self.resolve_ref(file, owner, ref)
                if resolved is None:
                    continue
                tfile, tfunc = resolved
                key = (tfile.path, tfunc.index)
                if key in seen:
                    continue
                seen.add(key)
                out.append((tfile, tfunc, via, line, file.path))
        return out

    # -- lock identity -------------------------------------------------------

    def lock_id(self, view: ClassView | None, attr: str) -> str:
        """Qualified lock name for ``self.<attr>``: the ROOT-most class in
        the hierarchy whose ``__init__`` assigns the attr (so every class
        in one diamond names the shared lock identically)."""
        if view is None:
            return attr
        owner = view.name
        for ci in [view, *self.ancestors(view)]:
            if attr in ci.facts.init_assigned:
                owner = ci.name  # keep searching: root-most declarer wins
        return f"{owner}.{attr}"


class Rule:
    """One pluggable invariant. Subclasses set ``name``/``description`` and
    implement any of the three hooks (all operate on FileFacts)."""

    name = "rule"
    description = ""

    def collect(self, file: FileFacts, project: Project) -> None:
        """Pass 1, per file: accumulate cross-file state on ``self``."""

    def check(self, file: FileFacts, project: Project) -> list[Finding]:
        """Pass 2, per file: emit this file's findings."""
        return []

    def finalize(self, project: Project) -> list[Finding]:
        """Pass 2, once: emit cross-file findings (e.g. never-read keys)."""
        return []


def discover_files(paths: list[str], exclude: tuple[str, ...] = ()) -> list[Path]:
    """``.py`` files under the given files/directories, sorted, minus
    ``__pycache__`` and any path whose POSIX form matches an exclude glob."""
    out: set[Path] = set()
    for raw in paths:
        p = Path(raw)
        if p.is_file() and p.suffix == ".py":
            out.add(p)
        elif p.is_dir():
            out.update(f for f in p.rglob("*.py")
                       if "__pycache__" not in f.parts)
    kept = []
    for f in sorted(out):
        posix = f.as_posix()
        if any(Path(posix).match(pattern) for pattern in exclude):
            continue
        kept.append(f)
    return kept


def run_analysis(
    paths: list[str],
    rules: list[Rule],
    exclude: tuple[str, ...] = (),
    root: str | Path | None = None,
    cache_dir: str | Path | None = None,
    use_cache: bool = True,
) -> tuple[list[Finding], list[Waiver], list[str]]:
    """Run ``rules`` over every ``.py`` under ``paths``.

    Returns ``(findings, waivers, scanned)``: ALL findings (waived ones
    flagged, unjustified/unused waivers surfaced as rule ``waiver``
    findings), every waiver directive seen, and the scanned file list.
    Paths in findings are relative to ``root`` when given.

    With ``use_cache`` (default), per-file facts are served from the
    ``(path, mtime, size)``-keyed sidecar under ``cache_dir`` (default
    ``<root>/.fedlint_cache``) and re-extracted only for changed files."""
    from fedml_tpu.analysis.cache import FactsCache

    root = Path(root) if root is not None else None
    cache = None
    if use_cache:
        if cache_dir is None and root is not None:
            cache_dir = root / ".fedlint_cache"
        if cache_dir is not None:
            cache = FactsCache(cache_dir)

    files: list[FileFacts] = []
    findings: list[Finding] = []
    for path in discover_files(paths, exclude):
        display = str(path)
        if root is not None:
            try:
                display = str(path.resolve().relative_to(root.resolve()))
            except ValueError:
                pass
        stat = path.stat()
        facts = None
        if cache is not None:
            facts = cache.get(display, stat.st_mtime_ns, stat.st_size)
        if facts is None:
            try:
                source = SourceFile(display, path.read_text())
            except SyntaxError as e:
                findings.append(Finding(
                    "parse-error", display, e.lineno or 0, e.offset or 0,
                    f"unparseable module: {e.msg}",
                ))
                continue
            facts = extract_facts(source)
            if cache is not None:
                cache.put(display, stat.st_mtime_ns, stat.st_size, facts)
        files.append(facts)
    if cache is not None:
        cache.save()

    project = Project()
    project.root = root
    project.index(files)
    for rule in rules:
        for file in files:
            rule.collect(file, project)
    for rule in rules:
        for file in files:
            findings.extend(rule.check(file, project))
        findings.extend(rule.finalize(project))

    # waiver application: suppress (but keep) matching findings
    by_path = {f.path: f for f in files}
    waiver_objs: dict[tuple[str, int], Waiver] = {}
    for file in files:
        for line, wf in file.waivers.items():
            waiver_objs[(file.path, line)] = Waiver(
                file.path, wf.line, wf.rules, wf.reason)
    active = {rule.name for rule in rules}
    for finding in findings:
        file = by_path.get(finding.path)
        if file is None:
            continue
        wf = file.waiver_fact_for(finding.rule, finding.line)
        if wf is None:
            continue
        waiver = waiver_objs[(file.path, wf.line)]
        if waiver.reason is not None:
            finding.waived = True
            finding.waiver_reason = waiver.reason
            waiver.used = True
        else:
            # matched but unjustified: the finding stays live and the
            # directive is reported below
            waiver.used = True

    waivers = [waiver_objs[key] for key in sorted(waiver_objs)]
    for waiver in waivers:
        if waiver.reason is None:
            findings.append(Finding(
                "waiver", waiver.path, waiver.line, 0,
                f"waiver for {', '.join(waiver.rules)} has no justification "
                "(write `# fedlint: disable=<rule> -- <why>`)",
            ))
        elif not waiver.used and any(r in active for r in waiver.rules):
            findings.append(Finding(
                "waiver", waiver.path, waiver.line, 0,
                f"waiver for {', '.join(waiver.rules)} suppresses nothing — "
                "remove it",
            ))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, waivers, [f.path for f in files]
