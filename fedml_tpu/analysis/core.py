"""fedlint core: shared AST walk, cross-file project index, waivers.

One :class:`SourceFile` per ``.py`` file carries the parsed tree plus the
comment map (extracted with :mod:`tokenize`, so ``#`` inside string
literals never reads as an annotation). Rules run in two passes —
``collect`` (per file, builds cross-file state) then ``check``/``finalize``
(emit findings) — so contracts that span files (wire keys written in one
module and read in another, lock annotations inherited across the class
diamond) need no per-rule file ordering.

Waivers: ``# fedlint: disable=<rule>[,<rule>...] -- <justification>`` on
the finding's line (or a standalone comment on the line above) suppresses
the finding but keeps it enumerable in the report. A waiver WITHOUT a
justification is itself a finding (rule ``waiver``), as is a waiver that
suppresses nothing — waivers must stay honest and minimal.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

# annotation / directive comment grammar (docs/STATIC_ANALYSIS.md)
_WAIVER_RE = re.compile(
    r"#\s*fedlint:\s*disable=([\w\-,\s]+?)(?:\s*--\s*(.+))?\s*$"
)
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([\w]+)")
_LOCK_HELD_RE = re.compile(r"#\s*lock-held:\s*([\w,\s]+)")

# builtin coercions are value plumbing, not construction: a subclass
# re-coercing `self.x = bool(x)` is not the construct-then-overwrite seam
_COERCIONS = frozenset({
    "bool", "int", "float", "str", "bytes", "tuple", "list", "dict", "set",
    "frozenset",
})


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    waived: bool = False
    waiver_reason: str | None = None

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "waived": self.waived,
            "waiver_reason": self.waiver_reason,
        }


@dataclasses.dataclass
class Waiver:
    """One ``# fedlint: disable=`` directive."""

    path: str
    line: int
    rules: tuple[str, ...]
    reason: str | None
    used: bool = False

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rules": list(self.rules),
            "reason": self.reason,
            "used": self.used,
        }


class SourceFile:
    """A parsed module: tree + per-line comments + waiver directives."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        # lineno -> full comment text (tokenize: '#' inside strings is NOT
        # a comment); a line holds at most one comment token
        self.comments: dict[int, str] = {}
        # lines whose only content is a comment (standalone): a waiver or
        # annotation here applies to the NEXT line's statement
        self.standalone_comments: set[int] = set()
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                line_no = tok.start[0]
                self.comments[line_no] = tok.string
                if tok.line[: tok.start[1]].strip() == "":
                    self.standalone_comments.add(line_no)
        self.waivers: dict[int, Waiver] = {}
        for line_no, comment in self.comments.items():
            m = _WAIVER_RE.search(comment)
            if m:
                rules = tuple(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                reason = m.group(2)
                self.waivers[line_no] = Waiver(
                    self.path, line_no, rules,
                    reason.strip() if reason else None,
                )

    def comment_on(self, line: int) -> str | None:
        return self.comments.get(line)

    def guarded_annotation(self, line: int) -> str | None:
        """``# guarded-by: <lock>`` on this line (or standalone above)."""
        return self._annotation(_GUARDED_RE, line)

    def lock_held_annotation(self, line: int) -> list[str]:
        """``# lock-held: <lock>[, <lock>...]`` on this line (or above)."""
        hit = self._annotation(_LOCK_HELD_RE, line)
        if hit is None:
            return []
        return [name.strip() for name in hit.split(",") if name.strip()]

    def _annotation(self, pattern: re.Pattern, line: int) -> str | None:
        for candidate in (line, line - 1):
            comment = self.comments.get(candidate)
            if comment is None:
                continue
            if candidate == line - 1 and candidate not in self.standalone_comments:
                continue
            m = pattern.search(comment)
            if m:
                return m.group(1)
        return None

    def waiver_for(self, rule: str, line: int) -> Waiver | None:
        """Waiver applying to a finding of ``rule`` at ``line``: same line,
        or a standalone directive comment on the line directly above."""
        for candidate in (line, line - 1):
            w = self.waivers.get(candidate)
            if w is None:
                continue
            if candidate == line - 1 and candidate not in self.standalone_comments:
                continue
            if rule in w.rules:
                return w
        return None


@dataclasses.dataclass
class ClassInfo:
    """Per-class facts the cross-file rules need: the base-name chain, what
    ``__init__`` constructs, and the concurrency annotations."""

    name: str
    bases: tuple[str, ...]
    file: SourceFile
    node: ast.ClassDef
    init_node: ast.FunctionDef | None = None
    # attrs `self.X = <call>`-constructed in __init__ -> assignment line
    init_constructed: dict[str, int] = dataclasses.field(default_factory=dict)
    # every `self.X = ...` in __init__ (constructed or not)
    init_assigned: set[str] = dataclasses.field(default_factory=set)
    # first line of the `super().__init__(...)` call in __init__, if any
    super_call_line: int | None = None
    # `# guarded-by:` declarations: attr -> lock name
    guarded: dict[str, str] = dataclasses.field(default_factory=dict)
    # lines carrying a guarded-by declaration (the declaration is exempt)
    guard_decl_lines: set[int] = dataclasses.field(default_factory=set)
    # `# lock-held:` method annotations: method name -> lock names
    lock_held: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )


def _base_name(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _self_attr_target(node: ast.stmt) -> str | None:
    """`self.X = ...` / `self.X: T = ...` -> X (single-target only)."""
    if isinstance(node, ast.Assign) and len(node.targets) == 1:
        target = node.targets[0]
    elif isinstance(node, ast.AnnAssign):
        target = node.target
    else:
        return None
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return target.attr
    return None


def _is_construction(value: ast.expr | None) -> bool:
    """True for `self.X = <call>` where the call is a real construction
    (not a builtin coercion of an argument)."""
    if not isinstance(value, ast.Call):
        return False
    func = value.func
    if isinstance(func, ast.Name) and func.id in _COERCIONS:
        return False
    return True


def _is_super_init_call(node: ast.stmt) -> bool:
    """`super().__init__(...)` or `SomeClass.__init__(self, ...)`."""
    if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
        return False
    func = node.value.func
    if not (isinstance(func, ast.Attribute) and func.attr == "__init__"):
        return False
    owner = func.value
    if (isinstance(owner, ast.Call) and isinstance(owner.func, ast.Name)
            and owner.func.id == "super"):
        return True
    # explicit-base form used by the diamond tips (Buffered* variants)
    return isinstance(owner, (ast.Name, ast.Attribute))


def _index_class(file: SourceFile, node: ast.ClassDef) -> ClassInfo:
    info = ClassInfo(
        name=node.name,
        bases=tuple(b for b in map(_base_name, node.bases) if b),
        file=file,
        node=node,
    )
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        held = file.lock_held_annotation(item.lineno)
        if held:
            info.lock_held[item.name] = tuple(held)
        for stmt in ast.walk(item):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            attr = _self_attr_target(stmt)
            if attr is None:
                continue
            lock = file.guarded_annotation(stmt.lineno)
            if lock is not None:
                info.guarded.setdefault(attr, lock)
                info.guard_decl_lines.add(stmt.lineno)
        if item.name != "__init__":
            continue
        info.init_node = item
        for stmt in item.body:
            if _is_super_init_call(stmt):
                if info.super_call_line is None:
                    info.super_call_line = stmt.lineno
                continue
            for sub in ast.walk(stmt):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                attr = _self_attr_target(sub)
                if attr is None:
                    continue
                info.init_assigned.add(attr)
                if _is_construction(sub.value):
                    info.init_constructed.setdefault(attr, sub.lineno)
    return info


class Project:
    """Cross-file index: every class, with by-name ancestor resolution."""

    def __init__(self):
        self.files: list[SourceFile] = []
        # EVERY class definition — duplicate simple names included, so a
        # name collision (two flax modules called SqueezeExcite, say) can
        # never silently exempt the later class from the per-class rules
        self.all_classes: list[ClassInfo] = []
        # simple name -> first definition, for base resolution only
        # (deterministic because files arrive sorted)
        self.classes: dict[str, ClassInfo] = {}

    def index(self, files: list[SourceFile]) -> None:
        self.files = files
        for file in files:
            for node in ast.walk(file.tree):
                if isinstance(node, ast.ClassDef):
                    info = _index_class(file, node)
                    self.all_classes.append(info)
                    self.classes.setdefault(node.name, info)

    def ancestors(self, info: ClassInfo) -> list[ClassInfo]:
        """Transitive base classes resolvable by simple name, nearest
        first; cycles and unknown bases are skipped."""
        out: list[ClassInfo] = []
        seen = {info.name}
        queue = list(info.bases)
        while queue:
            base = queue.pop(0)
            if base in seen:
                continue
            seen.add(base)
            base_info = self.classes.get(base)
            if base_info is None:
                continue
            out.append(base_info)
            queue.extend(base_info.bases)
        return out

    def effective_guarded(self, info: ClassInfo) -> dict[str, str]:
        """A class's guarded-field map, own declarations first, then
        inherited ones (the subclass may re-declare under another lock)."""
        merged: dict[str, str] = {}
        for ci in [info, *self.ancestors(info)]:
            for attr, lock in ci.guarded.items():
                merged.setdefault(attr, lock)
        return merged

    def effective_lock_held(self, info: ClassInfo,
                            method: str) -> tuple[str, ...]:
        """``# lock-held:`` annotation for a method, inherited along the
        base chain (an override of a lock-held method keeps the contract
        unless it re-annotates)."""
        for ci in [info, *self.ancestors(info)]:
            if method in ci.lock_held:
                return ci.lock_held[method]
        return ()


class Rule:
    """One pluggable invariant. Subclasses set ``name``/``description`` and
    implement any of the three hooks."""

    name = "rule"
    description = ""

    def collect(self, file: SourceFile, project: Project) -> None:
        """Pass 1, per file: accumulate cross-file state on ``self``."""

    def check(self, file: SourceFile, project: Project) -> list[Finding]:
        """Pass 2, per file: emit this file's findings."""
        return []

    def finalize(self, project: Project) -> list[Finding]:
        """Pass 2, once: emit cross-file findings (e.g. never-read keys)."""
        return []


def discover_files(paths: list[str], exclude: tuple[str, ...] = ()) -> list[Path]:
    """``.py`` files under the given files/directories, sorted, minus
    ``__pycache__`` and any path whose POSIX form matches an exclude glob."""
    out: set[Path] = set()
    for raw in paths:
        p = Path(raw)
        if p.is_file() and p.suffix == ".py":
            out.add(p)
        elif p.is_dir():
            out.update(f for f in p.rglob("*.py")
                       if "__pycache__" not in f.parts)
    kept = []
    for f in sorted(out):
        posix = f.as_posix()
        if any(Path(posix).match(pattern) for pattern in exclude):
            continue
        kept.append(f)
    return kept


def run_analysis(
    paths: list[str],
    rules: list[Rule],
    exclude: tuple[str, ...] = (),
    root: str | Path | None = None,
) -> tuple[list[Finding], list[Waiver], list[str]]:
    """Run ``rules`` over every ``.py`` under ``paths``.

    Returns ``(findings, waivers, scanned)``: ALL findings (waived ones
    flagged, unjustified/unused waivers surfaced as rule ``waiver``
    findings), every waiver directive seen, and the scanned file list.
    Paths in findings are relative to ``root`` when given."""
    root = Path(root) if root is not None else None
    files: list[SourceFile] = []
    findings: list[Finding] = []
    for path in discover_files(paths, exclude):
        display = str(path)
        if root is not None:
            try:
                display = str(path.resolve().relative_to(root.resolve()))
            except ValueError:
                pass
        try:
            files.append(SourceFile(display, path.read_text()))
        except SyntaxError as e:
            findings.append(Finding(
                "parse-error", display, e.lineno or 0, e.offset or 0,
                f"unparseable module: {e.msg}",
            ))
    project = Project()
    project.index(files)
    for rule in rules:
        for file in files:
            rule.collect(file, project)
    for rule in rules:
        for file in files:
            findings.extend(rule.check(file, project))
        findings.extend(rule.finalize(project))

    # waiver application: suppress (but keep) matching findings
    by_path = {f.path: f for f in files}
    active = {rule.name for rule in rules}
    for finding in findings:
        file = by_path.get(finding.path)
        if file is None:
            continue
        waiver = file.waiver_for(finding.rule, finding.line)
        if waiver is not None and waiver.reason is not None:
            finding.waived = True
            finding.waiver_reason = waiver.reason
            waiver.used = True
        elif waiver is not None:
            # matched but unjustified: the finding stays live and the
            # directive is reported below
            waiver.used = True

    waivers = [w for f in files for w in f.waivers.values()]
    for waiver in waivers:
        if waiver.reason is None:
            findings.append(Finding(
                "waiver", waiver.path, waiver.line, 0,
                f"waiver for {', '.join(waiver.rules)} has no justification "
                "(write `# fedlint: disable=<rule> -- <why>`)",
            ))
        elif not waiver.used and any(r in active for r in waiver.rules):
            findings.append(Finding(
                "waiver", waiver.path, waiver.line, 0,
                f"waiver for {', '.join(waiver.rules)} suppresses nothing — "
                "remove it",
            ))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, waivers, [f.path for f in files]
