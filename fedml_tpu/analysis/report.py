"""fedlint reporting: text for humans/CI logs, json for tooling, SARIF
2.1.0 for CI annotation surfaces, and baseline diffing.

Both full renderers receive the FULL finding list (waived included) so
every report enumerates the active waivers next to the live findings — a
waiver that hides a violation silently would defeat the gate's point. In
SARIF, waived findings ride along as suppressed results (``suppressions``
with the in-source justification), which annotation UIs hide by default
but auditors can still enumerate.

Baseline mode (``tools/fedlint.py --baseline report.json``) compares the
current run against a previously saved ``--format json`` report and keeps
only NEW live findings. Findings match on ``(rule, path, message)`` — not
line numbers, which shift under unrelated edits — so CI can annotate only
what a PR introduced. Exit-code semantics: the gate fails on new findings
only; pre-existing baseline findings are reported as carried.
"""

from __future__ import annotations

import json

from fedml_tpu.analysis.core import Finding, Waiver

REPORT_SCHEMA_VERSION = 1

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def live_findings(findings: list[Finding]) -> list[Finding]:
    return [f for f in findings if not f.waived]


def render_text(findings: list[Finding], waivers: list[Waiver],
                scanned: list[str], rule_names: list[str]) -> str:
    lines: list[str] = []
    live = live_findings(findings)
    for f in live:
        lines.append(f"{f.location()}: {f.rule}: {f.message}")
    waived = [f for f in findings if f.waived]
    if waived:
        lines.append("")
        lines.append(f"waived ({len(waived)}):")
        for f in waived:
            lines.append(
                f"  {f.location()}: {f.rule}: {f.message} "
                f"[waived: {f.waiver_reason}]"
            )
    lines.append("")
    lines.append(
        f"fedlint: {len(live)} finding(s), {len(waived)} waived, "
        f"{len(scanned)} file(s), rules: {', '.join(rule_names)}"
    )
    return "\n".join(lines)


def render_json(findings: list[Finding], waivers: list[Waiver],
                scanned: list[str], rule_names: list[str]) -> str:
    live = live_findings(findings)
    return json.dumps(
        {
            "schema_version": REPORT_SCHEMA_VERSION,
            "findings": [f.to_dict() for f in findings],
            "waivers": [w.to_dict() for w in waivers],
            "files_scanned": scanned,
            "rules": rule_names,
            "summary": {
                "findings": len(live),
                "waived": len(findings) - len(live),
                "files": len(scanned),
            },
        },
        indent=2,
    )


def render_sarif(findings: list[Finding], waivers: list[Waiver],
                 scanned: list[str], rule_names: list[str],
                 rule_descriptions: dict[str, str] | None = None) -> str:
    """Minimal valid SARIF 2.1.0: one run, one result per finding (waived
    findings become suppressed results with their justification)."""
    descriptions = rule_descriptions or {}
    # results may fire for rules outside the selection (parse-error, waiver)
    rule_ids = sorted({*rule_names, *(f.rule for f in findings)})
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": max(1, f.line),
                        "startColumn": max(1, f.col + 1),
                    },
                },
            }],
        }
        if f.waived:
            result["suppressions"] = [{
                "kind": "inSource",
                "justification": f.waiver_reason or "",
            }]
        results.append(result)
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "fedlint",
                    "informationUri": "docs/STATIC_ANALYSIS.md",
                    "rules": [
                        {
                            "id": rid,
                            "shortDescription": {
                                "text": descriptions.get(rid, rid),
                            },
                        }
                        for rid in rule_ids
                    ],
                },
            },
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


RENDERERS = {
    "text": render_text,
    "json": render_json,
    "sarif": render_sarif,
}


def finding_key(finding: Finding | dict) -> tuple[str, str, str]:
    """Baseline identity: (rule, path, message). Line/col shift under
    unrelated edits, the message text pins the actual defect."""
    if isinstance(finding, dict):
        return (finding["rule"], finding["path"], finding["message"])
    return (finding.rule, finding.path, finding.message)


def load_baseline(path: str) -> set[tuple[str, str, str]]:
    """LIVE finding keys of a previously saved ``--format json`` report.

    Raises ``ValueError`` on a file that is not a fedlint JSON report — a
    malformed baseline must fail the gate loudly, not silently match
    nothing and annotate every finding as new."""
    from pathlib import Path

    try:
        doc = json.loads(Path(path).read_text())
        findings = doc["findings"]
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise ValueError(
            f"baseline {path!r} is not a fedlint --format json report: {e}"
        ) from e
    return {finding_key(f) for f in findings if not f.get("waived")}


def split_by_baseline(
    findings: list[Finding], baseline: set[tuple[str, str, str]],
) -> tuple[list[Finding], list[Finding]]:
    """(new, carried) LIVE findings relative to a baseline key set; waived
    findings are never diffed (they are enumerable in the full report)."""
    new: list[Finding] = []
    carried: list[Finding] = []
    for f in live_findings(findings):
        (carried if finding_key(f) in baseline else new).append(f)
    return new, carried
