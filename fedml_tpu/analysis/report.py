"""fedlint reporting: text for humans/CI logs, json for tooling.

Both renderers receive the FULL finding list (waived included) so every
report enumerates the active waivers next to the live findings — a waiver
that hides a violation silently would defeat the gate's point.
"""

from __future__ import annotations

import json

from fedml_tpu.analysis.core import Finding, Waiver

REPORT_SCHEMA_VERSION = 1


def live_findings(findings: list[Finding]) -> list[Finding]:
    return [f for f in findings if not f.waived]


def render_text(findings: list[Finding], waivers: list[Waiver],
                scanned: list[str], rule_names: list[str]) -> str:
    lines: list[str] = []
    live = live_findings(findings)
    for f in live:
        lines.append(f"{f.location()}: {f.rule}: {f.message}")
    waived = [f for f in findings if f.waived]
    if waived:
        lines.append("")
        lines.append(f"waived ({len(waived)}):")
        for f in waived:
            lines.append(
                f"  {f.location()}: {f.rule}: {f.message} "
                f"[waived: {f.waiver_reason}]"
            )
    lines.append("")
    lines.append(
        f"fedlint: {len(live)} finding(s), {len(waived)} waived, "
        f"{len(scanned)} file(s), rules: {', '.join(rule_names)}"
    )
    return "\n".join(lines)


def render_json(findings: list[Finding], waivers: list[Waiver],
                scanned: list[str], rule_names: list[str]) -> str:
    live = live_findings(findings)
    return json.dumps(
        {
            "schema_version": REPORT_SCHEMA_VERSION,
            "findings": [f.to_dict() for f in findings],
            "waivers": [w.to_dict() for w in waivers],
            "files_scanned": scanned,
            "rules": rule_names,
            "summary": {
                "findings": len(live),
                "waived": len(findings) - len(live),
                "files": len(scanned),
            },
        },
        indent=2,
    )
