"""fedlint: AST-based invariant checker for this repo's documented contracts.

The hardest-won correctness rules in this codebase used to live only in
prose — the ``_round_lock``/``_edge_lock`` discipline (a missed lock caused
the real cross-silo deadlock fixed in PR 10), the ``MSG_ARG_KEY_*`` wire
contract, the construct-then-overwrite aggregator seam ROADMAP item 1 named
as the composition blocker, the jit-purity requirements of the engine's
lowered programs, and the canonical ``Comm/``/``Robust/``/``Async/``
metric-key namespace. This package machine-checks them on every PR:

- :mod:`fedml_tpu.analysis.core` — one shared AST walk per file, the
  :class:`~fedml_tpu.analysis.core.Rule` plugin surface, the cross-file
  :class:`~fedml_tpu.analysis.core.Project` index (class hierarchy,
  annotations), and ``# fedlint: disable=<rule> -- <why>`` waivers that
  REQUIRE a justification.
- :mod:`fedml_tpu.analysis.rules` — the built-in rule set (see
  docs/STATIC_ANALYSIS.md for the catalog and each rule's provenance).
- :mod:`fedml_tpu.analysis.config` — ``[tool.fedlint]`` pyproject section.
- :mod:`fedml_tpu.analysis.report` — text | json rendering.

``tools/fedlint.py`` is the CLI; tier-1 runs it as a zero-findings gate
over ``fedml_tpu/`` and ``tools/`` (tests/test_static_analysis.py).
"""

from fedml_tpu.analysis.config import FedlintConfig, load_config
from fedml_tpu.analysis.core import Finding, Project, Rule, Waiver, run_analysis
from fedml_tpu.analysis.report import render_json, render_sarif, render_text
from fedml_tpu.analysis.rules import all_rules, make_rules

__all__ = [
    "FedlintConfig",
    "Finding",
    "Project",
    "Rule",
    "Waiver",
    "all_rules",
    "load_config",
    "make_rules",
    "render_json",
    "render_sarif",
    "render_text",
    "run_analysis",
]
