"""Incremental facts cache: one JSON sidecar under ``.fedlint_cache/``.

The tier-1 zero-findings gate re-analyzes the whole tree on every run, and
the suite already sits near its timeout budget — parsing + extraction is
the dominant cost for files that have not changed since the last run. The
cache keys each file's serialized :class:`~fedml_tpu.analysis.facts.FileFacts`
on ``(path, mtime_ns, size)``: a warm run loads facts straight from JSON and
never re-parses an unchanged file, while ANY content change (mtime or size
moves) falls back to a fresh parse+extract. Because extraction is
config-independent (see facts.py), one cache serves every rule selection.

Safety properties:

- the whole sidecar is versioned on ``FACTS_SCHEMA_VERSION`` — a schema or
  extraction-semantics change discards the cache wholesale, never mixing
  old and new facts;
- writes are atomic (tmp + ``os.replace``), so a crash mid-save leaves the
  previous sidecar intact;
- a corrupt/unreadable sidecar degrades to an empty cache (cold run), never
  to an error.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from fedml_tpu.analysis.facts import FACTS_SCHEMA_VERSION, FileFacts

_SIDECAR = "facts.json"


class FactsCache:
    """``(path, mtime_ns, size)``-keyed FileFacts store in one JSON file."""

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.sidecar = self.directory / _SIDECAR
        self._entries: dict[str, dict] = {}
        # paths served or stored THIS run: save() prunes everything else,
        # so deleted/renamed files never accumulate dead entries
        self._seen: set[str] = set()
        self._dirty = False
        self.hits = 0
        self.misses = 0
        try:
            doc = json.loads(self.sidecar.read_text())
            if doc.get("version") == FACTS_SCHEMA_VERSION:
                self._entries = doc.get("entries", {})
        except (OSError, ValueError):
            self._entries = {}

    def get(self, path: str, mtime_ns: int, size: int) -> FileFacts | None:
        self._seen.add(path)
        entry = self._entries.get(path)
        if (entry is None or entry.get("mtime") != mtime_ns
                or entry.get("size") != size):
            self.misses += 1
            return None
        try:
            facts = FileFacts.from_dict(entry["facts"])
        except (KeyError, TypeError, ValueError):
            # entry shape drifted (hand-edited / truncated): treat as miss
            self.misses += 1
            del self._entries[path]
            self._dirty = True
            return None
        self.hits += 1
        return facts

    def put(self, path: str, mtime_ns: int, size: int,
            facts: FileFacts) -> None:
        self._seen.add(path)
        self._entries[path] = {
            "mtime": mtime_ns, "size": size, "facts": facts.to_dict(),
        }
        self._dirty = True

    def save(self) -> None:
        """Atomically persist the sidecar, pruned to the files this run
        actually scanned (no-op when nothing changed). A narrower scan
        (explicit CLI paths) shrinks the sidecar to its scope — cheap to
        repopulate — rather than letting dead entries grow it forever."""
        stale = set(self._entries) - self._seen
        if stale:
            for path in stale:
                del self._entries[path]
            self._dirty = True
        if not self._dirty:
            return
        self.directory.mkdir(parents=True, exist_ok=True)
        tmp = self.sidecar.with_suffix(".json.tmp")
        tmp.write_text(json.dumps({
            "version": FACTS_SCHEMA_VERSION,
            "entries": self._entries,
        }))
        os.replace(tmp, self.sidecar)
        self._dirty = False
