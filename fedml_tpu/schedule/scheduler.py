"""Heterogeneous workload scheduler.

Reference: fedml_core/distributed/schedule/scheduler.py — branch-and-bound /
DP assignment of per-client workloads to compute resources under memory
constraints (``scheduler``:3, ``DP_schedule``:109, ``assign_a_workload``:13,54)
— used for silo/GPU packing experiments.

TPU framing: workloads = per-client costs (sample counts × model FLOPs),
resources = chips/hosts with HBM budgets. Greedy-LPT (longest processing time)
and the DP optimal makespan split are provided; LPT is the one the cohort
stager can use to balance multi-client-per-chip packing.
"""

from __future__ import annotations

import itertools

import numpy as np


def lpt_schedule(workloads: np.ndarray, n_resources: int,
                 capacities: np.ndarray | None = None) -> list[list[int]]:
    """Longest-processing-time greedy: sort desc, place each on the least-
    loaded resource with remaining capacity. Returns resource -> workload idxs.
    """
    workloads = np.asarray(workloads, dtype=np.float64)
    caps = (
        np.full(n_resources, np.inf)
        if capacities is None
        else np.asarray(capacities, dtype=np.float64)
    )
    loads = np.zeros(n_resources)
    used = np.zeros(n_resources)
    assignment: list[list[int]] = [[] for _ in range(n_resources)]
    for idx in np.argsort(-workloads):
        order = np.argsort(loads)
        for r in order:
            if used[r] + workloads[idx] <= caps[r]:
                assignment[r].append(int(idx))
                loads[r] += workloads[idx]
                used[r] += workloads[idx]
                break
        else:
            raise ValueError("workload does not fit any resource capacity")
    return assignment


def dp_schedule(workloads: np.ndarray, n_resources: int, max_items: int = 20) -> tuple[list[list[int]], float]:
    """Optimal makespan assignment by DP over subsets (reference
    DP_schedule:109 — exact for small instances). Exponential in the number
    of workloads; guarded by ``max_items``. Returns (assignment, makespan)."""
    w = np.asarray(workloads, dtype=np.float64)
    n = len(w)
    if n > max_items:
        raise ValueError(f"DP schedule is exact/exponential; {n} > {max_items} items")
    subset_sum = np.zeros(1 << n)
    for mask in range(1 << n):
        s = 0.0
        m = mask
        i = 0
        while m:
            if m & 1:
                s += w[i]
            m >>= 1
            i += 1
        subset_sum[mask] = s

    full = (1 << n) - 1
    INF = float("inf")
    best = np.full((n_resources + 1, 1 << n), INF)
    choice = np.zeros((n_resources + 1, 1 << n), dtype=np.int64)
    best[0, 0] = 0.0
    for r in range(1, n_resources + 1):
        for mask in range(1 << n):
            sub = mask
            while True:
                if best[r - 1, mask ^ sub] < INF:
                    cand = max(best[r - 1, mask ^ sub], subset_sum[sub])
                    if cand < best[r, mask]:
                        best[r, mask] = cand
                        choice[r, mask] = sub
                if sub == 0:
                    break
                sub = (sub - 1) & mask

    assignment: list[list[int]] = []
    mask = full
    for r in range(n_resources, 0, -1):
        sub = int(choice[r, mask])
        assignment.append([i for i in range(n) if sub >> i & 1])
        mask ^= sub
    assignment.reverse()
    return assignment, float(best[n_resources, full])


def balance_cohort_packing(client_sizes: np.ndarray, n_slots: int) -> list[list[int]]:
    """Pack cohort clients into device slots minimizing the max per-slot
    sample count — the multi-client-per-chip layout for small slices
    (SURVEY §7 'non-divisible client counts vs. device mesh')."""
    return lpt_schedule(client_sizes, n_slots)
