"""Compile dispatcher: pjit when sharded, shard_map when purely mapped.

The Titanax pattern (SNIPPETS [3]) adapted to this engine: every compiled
program in the simulator is lowered through :func:`lower`, which inspects
the program's in/out PartitionSpecs and picks the lowering —

- **pjit** (``jax.jit`` with explicit ``in_shardings``/``out_shardings``)
  when any spec partitions an axis beyond the mapped (client) axes. The
  program body is then *global-view*: GSPMD partitions the math, honoring
  ``with_sharding_constraint`` pins, and buffer donation rides the modern
  jit path (the legacy shard_map donation bug, sim/engine.py, does not
  apply here). Calls run under the mesh context so bare-PartitionSpec
  constraints inside model code (models/transformer.py ``mp_axis``)
  resolve.
- **shard_map** (the engine's existing manual lowering via
  parallel/compat.py) when the plan is purely client-mapped — per-device
  bodies with explicit collectives, which sidesteps the XLA SPMD
  limitation on vmapped grouped convolutions.

The two lowerings expect different bodies (manual bodies read
``lax.axis_index``; global bodies index with ``jnp.arange``), so the
caller passes the body matching the specs it built — the dispatcher's job
is picking the compilation pipeline and normalizing specs to shardings,
not rewriting the program.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
from jax.sharding import PartitionSpec as P

from fedml_tpu.parallel import compat
from fedml_tpu.parallel.mesh import CLIENT_AXIS, named_sharding

Pytree = Any

MAPPED_AXES = frozenset({CLIENT_AXIS})


def _spec_leaves(specs):
    return jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )


def spec_is_sharded(spec: P, mapped_axes=MAPPED_AXES) -> bool:
    """True iff the spec partitions an axis beyond the mapped axes."""
    for entry in spec:
        for ax in (entry if isinstance(entry, tuple) else (entry,)):
            if ax is not None and ax not in mapped_axes:
                return True
    return False


def plan_is_sharded(*spec_trees, mapped_axes=MAPPED_AXES) -> bool:
    """True iff any PartitionSpec leaf in the given trees is sharded
    beyond the mapped (client) axes — the pjit-vs-shard_map switch."""
    return any(
        spec_is_sharded(s, mapped_axes)
        for tree in spec_trees
        for s in _spec_leaves(tree)
    )


def to_shardings(mesh, specs):
    """PartitionSpec (sub)trees -> NamedSharding trees (specs are pytree
    leaves, so prefix trees pass through with their structure intact).
    The ONE spec->sharding conversion — the engine's sharded-at-rest
    placement uses it too."""
    return jax.tree_util.tree_map(
        lambda s: named_sharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


@dataclasses.dataclass
class Lowered:
    """A compiled step function plus how it was lowered.

    ``mode`` is ``"pjit"`` or ``"shard_map"``; ``donate_argnums`` records
    the donation actually passed to the compiler. pjit calls enter the
    mesh context so bare-PartitionSpec ``with_sharding_constraint`` pins
    inside the traced body resolve against the plan's mesh."""

    fn: Any
    mode: str
    mesh: Any
    donate_argnums: tuple

    def __call__(self, *args):
        if self.mode == "pjit":
            with self.mesh:
                return self.fn(*args)
        return self.fn(*args)


def lower(
    fn,
    *,
    mesh,
    in_specs,
    out_specs,
    donate_argnums: tuple = (),
    mapped_axes=MAPPED_AXES,
    check_vma: bool | None = False,
) -> Lowered:
    """Lower ``fn`` for ``mesh`` according to its PartitionSpecs.

    pjit iff any in/out spec is sharded beyond ``mapped_axes``; the
    engine's shard_map manual lowering otherwise. ``donate_argnums`` is
    honored on both paths (on pjit via jit's native donation; on
    shard_map via the jit wrapper exactly as the engine built by hand
    before this dispatcher existed).

    Every round program constructor routes through here — the padded
    pass/aggregate pair AND the packed-lane trio (buffer init, lane pass,
    aggregate; ``sim/engine.py`` ``_packed_*_impl``) — so packed cohorts
    are served by whichever lowering the specs pick: pjit plans when
    ``shard_rules`` shards the model, the shard_map fallback otherwise
    (docs/PERFORMANCE.md "Packed lanes on sharded plans").
    """
    if plan_is_sharded(in_specs, out_specs, mapped_axes=mapped_axes):
        jitted = jax.jit(
            fn,
            in_shardings=to_shardings(mesh, in_specs),
            out_shardings=to_shardings(mesh, out_specs),
            donate_argnums=donate_argnums,
        )
        return Lowered(jitted, "pjit", mesh, tuple(donate_argnums))
    mapped = compat.shard_map(
        fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        axis_names=frozenset(mapped_axes) & set(mesh.axis_names),
        check_vma=check_vma,
    )
    jitted = jax.jit(mapped, donate_argnums=donate_argnums)
    return Lowered(jitted, "shard_map", mesh, tuple(donate_argnums))


def jit_sharded(fn, mesh, donate_argnums: tuple = ()) -> Lowered:
    """Plain ``jax.jit`` that runs under the mesh context (auto sharding
    propagation from the arguments) — for auxiliary programs like eval
    that consume whatever layout the round program left the model in."""
    return Lowered(
        jax.jit(fn, donate_argnums=donate_argnums), "pjit", mesh,
        tuple(donate_argnums),
    )


def replicate(x, mesh):
    """Pin a (pytree of) value(s) to fully-replicated layout inside a
    traced program — the gather-for-compute step of the FSDP-style plans
    (parallel/rules.py ``gather_compute``): one all-gather per leaf, after
    which every arithmetic op sees exactly the tensors the unsharded
    program sees. Uses NamedSharding, so it is mesh-context-free and safe
    in plain-jit programs too."""
    rep = named_sharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda leaf: jax.lax.with_sharding_constraint(leaf, rep), x
    )
