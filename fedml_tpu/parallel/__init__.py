"""Parallelism plane: device meshes, partition rules, compile dispatch.

- :mod:`fedml_tpu.parallel.mesh` — mesh constructors (clients / silo /
  clients x model) and sharding helpers.
- :mod:`fedml_tpu.parallel.rules` — regex partition rules -> PartitionSpec
  plans for model + optimizer pytrees (docs/PERFORMANCE.md "Sharded client
  models").
- :mod:`fedml_tpu.parallel.dispatch` — pjit-when-sharded /
  shard_map-when-mapped compile dispatcher.
- :mod:`fedml_tpu.parallel.compat` — jax.shard_map API shim for legacy
  runtimes.
"""

from fedml_tpu.parallel.dispatch import lower, plan_is_sharded  # noqa: F401
from fedml_tpu.parallel.mesh import (  # noqa: F401
    CLIENT_AXIS,
    MODEL_AXIS,
    SILO_AXIS,
    client_mesh,
    named_sharding,
    shard_mesh,
    silo_mesh,
)
from fedml_tpu.parallel.rules import (  # noqa: F401
    RULE_SETS,
    RuleSet,
    match_partition_rules,
    rule_set,
)
