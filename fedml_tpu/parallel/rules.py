"""Partition rules: regex over param paths -> PartitionSpec (SNIPPETS [2]).

The reference has no model-parallel plane at all — a client model must fit
one worker. Here a *rule set* maps every leaf of a variables (or optimizer
state) pytree to a :class:`~jax.sharding.PartitionSpec` by regex-matching the
leaf's ``/``-joined tree path, the fmengine ``match_partition_rules``
pattern: scalars are always replicated, the first matching rule wins, and an
unmatched non-scalar leaf raises naming the offending path — a silently
replicated tensor on a model that needs sharding is an OOM at full shape,
so the matcher fails loudly at plan time instead.

Because optax optimizer states embed the param tree under their own
prefixes (``0/trace/<param path>`` for SGD momentum, ``0/mu/<param path>``
for Adam), the SAME rules match both: rules are written against param-path
*suffixes* (``re.search``, not ``fullmatch``), and the states' scalar
bookkeeping leaves (step counts) fall under the scalar-replication rule.

Built-in rule sets (:func:`rule_set`) cover the model zoo's two families:

- ``transformer_tp`` / ``transformer_fsdp`` — TransformerLM
  (models/transformer.py). TP is the Megatron split (qkv/MLP-in
  column-parallel, proj/MLP-out row-parallel, embed/head over the model
  axis); FSDP shards every matrix over the model axis *at rest* and
  gathers for compute (``gather_compute=True``), which keeps the round
  bit-identical to the unsharded program (all cross-shard movement is
  concat/slice, never a reassociated reduction).
- ``cnn_tp`` / ``cnn_fsdp`` — the conv zoo (CNN/ResNet/VGG): conv kernels
  shard their output-channel axis, dense kernels their output-feature
  axis; BN parameters and statistics stay replicated (they are small and
  federate as ordinary weights). ``cnn_fsdp`` gathers for compute, which
  also sidesteps the XLA SPMD limitation on vmapped grouped convolutions
  (sim/engine.py's shard_map rationale). Note the gather-compute
  bit-identity contract below is guarded for the transformer path; BN
  models' own batch-statistic reductions fuse differently across the two
  programs and match the unsharded round to ~1 ULP, not bitwise
  (measured: 16/287 ResNet-56 leaves, all ``batch_stats/*/mean``).

Rules are COHORT-LAYOUT-AGNOSTIC: a spec names only model axes, never the
``clients`` axis, so the same rule set serves the padded cohort vmap and
the packed-lane programs unchanged — the engine supplies the client-axis
dimension (cohort slots or lanes) outside the spec, and the planner's
per-shard lane binning never consults the rules (docs/PERFORMANCE.md
"Packed lanes on sharded plans").
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from fedml_tpu.parallel.mesh import MODEL_AXIS

Pytree = Any


def _key_name(entry) -> str:
    """One path entry -> its string name (Dict/Attr/Sequence keys alike)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def tree_paths(tree) -> list[tuple[str, Any]]:
    """``[(joined '/' path, leaf), ...]`` in tree-flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [("/".join(_key_name(k) for k in kp), leaf) for kp, leaf in flat]


def match_partition_rules(rules, tree) -> Pytree:
    """Pytree of PartitionSpec matching ``tree``'s structure.

    ``rules`` is a sequence of ``(regex, PartitionSpec)`` pairs tried in
    order against each leaf's ``/``-joined path (``re.search``). Scalar
    leaves (rank 0, or a single element) are replicated without consulting
    the rules. A non-scalar leaf no rule matches raises ``ValueError``
    naming the path; end a rule list with ``(".*", P())`` for an explicit
    replicate-the-rest default. A matched spec longer than the leaf's rank
    also raises naming both — a silent rank mismatch would fail much later
    inside XLA with the param name lost.

    Works on concrete arrays and on ``jax.eval_shape`` output alike (only
    ``.shape`` is consulted), and on optax optimizer states (their leaves
    carry the param-path suffix; their scalar counters replicate).
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)

    def spec_for(name: str, leaf) -> P:
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()  # scalars are never partitioned
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                if len(spec) > len(shape):
                    raise ValueError(
                        f"partition rule {rule!r} assigns spec {spec} "
                        f"(rank {len(spec)}) to param '{name}' of shape "
                        f"{shape} (rank {len(shape)})"
                    )
                return spec
        raise ValueError(
            f"no partition rule matched param '{name}' (shape {shape}); "
            "add a rule or end the rule list with ('.*', PartitionSpec()) "
            "to replicate unmatched leaves explicitly"
        )

    specs = [
        spec_for("/".join(_key_name(k) for k in kp), leaf)
        for kp, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, specs)


@dataclasses.dataclass(frozen=True)
class RuleSet:
    """A named partition plan: the regex rules plus how to compute with it.

    ``gather_compute=True`` is the FSDP-style contract: parameters are
    sharded over the model axis *at rest* (between rounds: global model,
    new-global output) but replicated for the training math itself — the
    engine inserts one gather at program entry, so every arithmetic op sees
    exactly the tensors the unsharded program sees and the round stays
    bit-identical (guarded by tools/shard_smoke.py for the TransformerLM
    path; BN models match to ~1 ULP, see the module note). ``False`` is true
    tensor parallelism: GSPMD partitions the matmuls themselves, trading
    bit-identity (cross-shard reductions reassociate, ~1 ULP) for sharded
    compute and activations.

    ``act_spec`` names the block-boundary activation constraint axes
    (unbatched rank, e.g. ``(None, None, None)`` for [B, T, D]); the engine
    threads it onto modules exposing an ``mp_axis`` field
    (models/transformer.py).
    """

    name: str
    rules: tuple
    gather_compute: bool = False
    act_spec: tuple | None = None


def _transformer_tp_rules():
    # Megatron split: column-parallel into the block, row-parallel out.
    return (
        (r"qkv/kernel$", P(None, MODEL_AXIS)),
        (r"proj/kernel$", P(MODEL_AXIS, None)),
        (r"Dense_0/kernel$", P(None, MODEL_AXIS)),
        (r"Dense_0/bias$", P(MODEL_AXIS)),
        (r"Dense_1/kernel$", P(MODEL_AXIS, None)),
        (r"tok_embed/embedding$", P(None, MODEL_AXIS)),
        (r"pos_embed$", P(None, MODEL_AXIS)),
        (r"head/kernel$", P(None, MODEL_AXIS)),
        (r"head/bias$", P(MODEL_AXIS)),
        (r".*", P()),  # norms, remaining biases: replicated
    )


def _transformer_fsdp_rules():
    # every matrix sharded on its output/embedding axis at rest; 1-D
    # params stay replicated (negligible storage, always divisible-safe)
    return (
        (r"(kernel|embedding)$", P(None, MODEL_AXIS)),
        (r"pos_embed$", P(None, MODEL_AXIS)),
        (r".*", P()),
    )


def _cnn_rules():
    # conv kernels [kh, kw, cin, cout]: shard output channels; dense
    # kernels [in, out]: shard output features; BN params/stats replicated
    return (
        (r"Conv_\d+/kernel$", P(None, None, None, MODEL_AXIS)),
        (r"(Dense_\d+|fc|head|classifier)/kernel$", P(None, MODEL_AXIS)),
        (r".*", P()),
    )


RULE_SETS: dict[str, RuleSet] = {
    "transformer_tp": RuleSet(
        "transformer_tp", _transformer_tp_rules(), gather_compute=False,
        act_spec=(None, None, None),
    ),
    "transformer_fsdp": RuleSet(
        "transformer_fsdp", _transformer_fsdp_rules(), gather_compute=True,
    ),
    "cnn_tp": RuleSet("cnn_tp", _cnn_rules(), gather_compute=False),
    "cnn_fsdp": RuleSet("cnn_fsdp", _cnn_rules(), gather_compute=True),
}
# the conv rules fit the ResNet/VGG zoo unchanged; keep the names the
# models are asked for by
RULE_SETS["resnet_tp"] = dataclasses.replace(
    RULE_SETS["cnn_tp"], name="resnet_tp")
RULE_SETS["resnet_fsdp"] = dataclasses.replace(
    RULE_SETS["cnn_fsdp"], name="resnet_fsdp")


def rule_set(name: str) -> RuleSet:
    """Look up a built-in rule set; unknown names raise listing the options."""
    try:
        return RULE_SETS[name]
    except KeyError:
        raise ValueError(
            f"unknown shard rule set {name!r}; built-ins: "
            f"{sorted(RULE_SETS)}"
        ) from None


def constrain(x, axes: tuple | None):
    """Block-boundary activation constraint: ``with_sharding_constraint``
    with the given PartitionSpec axes (unbatched rank — under
    ``vmap(spmd_axis_name=...)`` the mapped axis is prepended
    automatically). ``None`` is the no-op so modules can thread an optional
    ``mp_axis`` without branching. Must trace under a mesh context (the
    dispatcher's pjit wrapper provides one); outside a trace (eager model
    init) the constraint is semantically a no-op and is skipped, so module
    construction never requires a mesh."""
    if axes is None or not isinstance(x, jax.core.Tracer):
        return x
    return jax.lax.with_sharding_constraint(x, P(*axes))
