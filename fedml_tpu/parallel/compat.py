"""JAX API compatibility seams.

The engine targets the current ``jax.shard_map`` API (``axis_names`` names
the manual axes, ``check_vma`` gates the varying-manual-axes check). Older
jax (< 0.5) ships the same primitive as ``jax.experimental.shard_map`` with
the inverse parameterization (``auto`` names the NON-manual axes,
``check_rep`` gates the replication check). This shim presents the new
surface on either runtime so every shard_mapped program in the repo compiles
against whichever jax the container bakes in.
"""

from __future__ import annotations

from typing import Any

import jax


def current_mesh():
    """The :class:`jax.sharding.Mesh` of the innermost active mesh context,
    or ``None`` when no mesh is active.

    This is how a traced op discovers the mesh the surrounding program is
    being lowered under (``parallel/dispatch.py`` enters the mesh context
    around every pjit trace) — e.g. the head-parallel flash wrap in
    ``ops/attention.py`` decides at trace time whether to nest a per-rank
    ``shard_map`` over the model axis. The thread-local lives in different
    homes across jax versions; probe them in order.
    """
    try:
        from jax.interpreters import pxla

        m = pxla.thread_resources.env.physical_mesh
    except AttributeError:
        try:
            from jax._src import mesh as mesh_lib

            m = mesh_lib.thread_resources.env.physical_mesh
        except (ImportError, AttributeError):
            return None
    return None if m is None or m.empty else m


def shard_map(f, *, mesh, in_specs, out_specs,
              axis_names: Any = None, check_vma: bool | None = None):
    """``jax.shard_map`` signature, runnable on old and new jax alike.

    ``axis_names=None`` means manual over every mesh axis (both APIs'
    default); ``check_vma=None`` keeps the runtime's default check.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = frozenset(axis_names)
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    # Full-manual always: the legacy lowering's partial-manual mode (auto =
    # the non-named axes) trips an XLA SPMD partitioner CHECK
    # (spmd_partitioner.cc "IsManualSubgroup" mismatch → SIGABRT) on real
    # round programs. Running the would-be-auto axes manual is semantically
    # identical — the body cannot reference an unnamed axis, so each device
    # just computes its block's program replicated along those axes — at the
    # cost of losing auto-sharded data parallelism over them on this
    # (legacy-jax) runtime only.
    kwargs = {}
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
