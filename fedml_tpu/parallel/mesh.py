"""Device-mesh construction for federated simulation.

The reference's process topology (one MPI rank per client + one server rank,
fedml_api/distributed/fedavg/FedAvgAPI.py:13-17) maps onto a JAX device mesh:
the ``clients`` axis carries cohort/client parallelism (the FL analogue of DP),
and an optional ``silo`` axis carries intra-client data parallelism — the
analogue of the reference's intra-silo DDP (fedavg_cross_silo/
process_group_manager.py:23-27, NCCL) riding ICI instead.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENT_AXIS = "clients"
SILO_AXIS = "silo"


def client_mesh(devices=None) -> Mesh:
    """1-D mesh: every device is a client slot."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (CLIENT_AXIS,))


def silo_mesh(num_silos: int, devices=None) -> Mesh:
    """2-D mesh [clients, silo]: cohort parallelism × intra-silo DP."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % num_silos:
        raise ValueError(f"{n} devices not divisible into {num_silos} silo groups")
    arr = np.asarray(devices).reshape(num_silos, n // num_silos)
    return Mesh(arr, (CLIENT_AXIS, SILO_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh) -> NamedSharding:
    """Shard the leading (client) axis of every leaf over the clients axis."""
    return NamedSharding(mesh, P(CLIENT_AXIS))


def cohort_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [C, S, B, ...] cohort stacks: client axis over ``clients``;
    on a 2-D mesh the within-client batch axis additionally shards over
    ``silo`` — intra-silo data parallelism, the reference's in-silo DDP
    (fedavg_cross_silo/DistWorker.py:53) as a mesh axis with XLA inserting the
    gradient all-reduce over ICI."""
    if SILO_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(CLIENT_AXIS, None, SILO_AXIS))
    return NamedSharding(mesh, P(CLIENT_AXIS))
