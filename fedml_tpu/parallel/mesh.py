"""Device-mesh construction for federated simulation.

The reference's process topology (one MPI rank per client + one server rank,
fedml_api/distributed/fedavg/FedAvgAPI.py:13-17) maps onto a JAX device mesh:
the ``clients`` axis carries cohort/client parallelism (the FL analogue of DP),
and an optional ``silo`` axis carries intra-client data parallelism — the
analogue of the reference's intra-silo DDP (fedavg_cross_silo/
process_group_manager.py:23-27, NCCL) riding ICI instead.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENT_AXIS = "clients"
SILO_AXIS = "silo"
# model-parallel axis: tensor/FSDP sharding WITHIN one client's model
# (parallel/rules.py partition rules name it) — orthogonal to the client
# axis that carries cohort parallelism
MODEL_AXIS = "model"


def client_mesh(devices=None) -> Mesh:
    """1-D mesh: every device is a client slot."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (CLIENT_AXIS,))


def silo_mesh(num_silos: int, devices=None) -> Mesh:
    """2-D mesh [clients, silo]: cohort parallelism × intra-silo DP."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % num_silos:
        raise ValueError(
            f"silo_mesh(num_silos={num_silos}): {n} available devices do "
            f"not divide evenly into {num_silos} silo groups "
            f"({n} % {num_silos} = {n % num_silos})"
        )
    arr = np.asarray(devices).reshape(num_silos, n // num_silos)
    return Mesh(arr, (CLIENT_AXIS, SILO_AXIS))


def shard_mesh(mesh_shape, devices=None) -> Mesh:
    """2-D mesh [clients, model]: cohort parallelism × within-client model
    parallelism (docs/PERFORMANCE.md "Sharded client models").

    ``mesh_shape`` is ``(n_client_shards, n_model_shards)``. The product
    must divide the available device count evenly — validated here with an
    error naming both numbers, instead of the opaque numpy reshape failure
    a bad shape used to produce. When the product is a proper divisor of
    the device count (e.g. a 2x2 mesh on 8 devices), the first
    ``clients * model`` devices are used — a deterministic subset, so
    repeated constructions agree; non-divisor products are rejected
    rather than silently stranding a remainder of the mesh."""
    devices = list(devices if devices is not None else jax.devices())
    try:
        clients, model = (int(x) for x in mesh_shape)
    except (TypeError, ValueError):
        raise ValueError(
            f"mesh_shape must be a (clients, model) pair, got {mesh_shape!r}"
        ) from None
    if clients < 1 or model < 1:
        raise ValueError(
            f"mesh_shape axes must be >= 1, got {(clients, model)}"
        )
    n, want = len(devices), clients * model
    if want > n or n % want:
        raise ValueError(
            f"mesh_shape {(clients, model)} requires {want} devices "
            f"(clients x model) but {n} are available, and {want} does "
            f"not divide {n} evenly ({n} % {want} = {n % want})"
            if want <= n else
            f"mesh_shape {(clients, model)} requires {want} devices "
            f"(clients x model) but only {n} are available"
        )
    arr = np.asarray(devices[:want]).reshape(clients, model)
    return Mesh(arr, (CLIENT_AXIS, MODEL_AXIS))


def parse_mesh_shape(text: str | None):
    """CLI spelling of a (clients, model) mesh shape: ``'2x4'`` or
    ``'2,4'`` -> ``(2, 4)``; None/empty passes through (no 2-D mesh)."""
    if not text:
        return None
    parts = text.lower().replace("x", ",").split(",")
    try:
        clients, model = (int(p) for p in parts)
    except ValueError:
        raise ValueError(
            f"--mesh_shape expects 'CLIENTSxMODEL' (e.g. 2x4), got {text!r}"
        ) from None
    return (clients, model)


def named_sharding(mesh: Mesh, spec) -> NamedSharding:
    """Build a NamedSharding from a PartitionSpec on ``mesh``, validating
    that every axis the spec names exists on the mesh — a typo'd axis name
    otherwise surfaces as a deep XLA lowering error with the spec lost."""
    unknown = [
        ax
        for entry in spec
        for ax in (entry if isinstance(entry, tuple) else (entry,))
        if ax is not None and ax not in mesh.axis_names
    ]
    if unknown:
        raise ValueError(
            f"PartitionSpec {spec} names mesh axes {unknown} not present "
            f"on this mesh (axes: {list(mesh.axis_names)})"
        )
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh) -> NamedSharding:
    """Shard the leading (client) axis of every leaf over the clients axis."""
    return NamedSharding(mesh, P(CLIENT_AXIS))


def cohort_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [C, S, B, ...] cohort stacks: client axis over ``clients``;
    on a 2-D mesh the within-client batch axis additionally shards over
    ``silo`` — intra-silo data parallelism, the reference's in-silo DDP
    (fedavg_cross_silo/DistWorker.py:53) as a mesh axis with XLA inserting the
    gradient all-reduce over ICI."""
    if SILO_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(CLIENT_AXIS, None, SILO_AXIS))
    return NamedSharding(mesh, P(CLIENT_AXIS))
