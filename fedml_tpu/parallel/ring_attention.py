"""Ring attention: exact attention over a sequence-parallel mesh axis.

Long-context is first-class here even though the reference has none (SURVEY
§5.7: max workload is 20-token StackOverflow NWP). Sequences are sharded over
the ``sp`` mesh axis; each device holds its local Q/K/V chunk ``[B, H, T/P, D]``
and K/V chunks rotate around the ring via ``lax.ppermute`` (XLA lowers this to
ICI neighbor exchange) while every device accumulates its queries' attention
with the same online-softmax update the pallas kernel uses
(fedml_tpu/ops/attention.py). After P steps every query has seen every key —
exact attention, O(T/P) memory per chip, compute/communication overlapped by
XLA's async collectives.

Usable only inside ``shard_map`` (it calls collectives on ``axis_name``). The
TransformerLM picks it via ``attn_impl="ring"`` and
fedml_tpu/parallel/sequence.py builds the surrounding sharded train step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = False,
    sm_scale: float | None = None,
) -> jnp.ndarray:
    """Exact attention with K/V rotating around the ``axis_name`` ring.

    q, k, v: local chunks ``[B, H, T_local, D]`` of a sequence sharded over
    ``axis_name``. Returns the local output chunk ``[B, H, T_local, D]``.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, t_loc, d = q.shape

    qf = q.astype(jnp.float32) * sm_scale
    q_pos = my_idx * t_loc + jax.lax.broadcasted_iota(jnp.int32, (t_loc, t_loc), 0)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, i):
        o, l, m, k_cur, v_cur = carry
        # after i rotations this device holds the block originally on my_idx - i
        blk = (my_idx - i) % axis_size
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            k_pos = blk * t_loc + jax.lax.broadcasted_iota(
                jnp.int32, (t_loc, t_loc), 1
            )
            s = jnp.where((k_pos <= q_pos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o = o * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        # the last iteration's rotation would be discarded — skip the ICI hop
        k_nxt, v_nxt = jax.lax.cond(
            i < axis_size - 1,
            lambda kv: (
                jax.lax.ppermute(kv[0], axis_name, perm),
                jax.lax.ppermute(kv[1], axis_name, perm),
            ),
            lambda kv: kv,
            (k_cur, v_cur),
        )
        return (o, l, m_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, h, t_loc, d), jnp.float32)
    l0 = jnp.zeros((b, h, t_loc, 1), jnp.float32)
    m0 = jnp.full((b, h, t_loc, 1), NEG_INF, jnp.float32)
    (o, l, _, _, _), _ = jax.lax.scan(
        step, (o0, l0, m0, k, v), jnp.arange(axis_size)
    )
    return (o / jnp.maximum(l, 1e-20)).astype(q.dtype)
