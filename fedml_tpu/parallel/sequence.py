"""Sequence/context parallelism: the sharded long-context train step.

No reference equivalent (SURVEY §5.7 — absent there; first-class here). The
recipe follows the standard JAX scaling pattern: pick a mesh with an ``sp``
axis, shard the token axis of the batch over it, keep params replicated, and
let the model's only cross-token op (attention) run as a ring over the axis
(fedml_tpu/parallel/ring_attention.py). Loss and gradients are token-local
sums, so they close over two ``psum``s — XLA lays both on ICI.

Composes with federated axes: a ``(clients, sp)`` mesh trains a cohort of
long-context clients, cohort-parallel over ``clients`` and sequence-parallel
over ``sp``.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

SP_AXIS = "sp"

Pytree = Any


def sequence_mesh(num_sp: int | None = None, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if num_sp is None:
        num_sp = len(devices)
    return Mesh(np.asarray(devices[:num_sp]), (SP_AXIS,))


def make_sp_lm_train_step(model, optimizer: optax.GradientTransformation, mesh: Mesh,
                          sp_axis: str = SP_AXIS):
    """Returns ``step(params, opt_state, batch, rng) -> (params, opt_state, loss)``.

    ``batch = {"x": [B, T], "y": [B, T], "mask": [B, T]}`` with the T axis
    sharded over ``sp_axis``; params/opt_state/rng replicated (the dropout rng
    is folded with the shard index so shards draw independent masks). The
    model must be built with ``attn_impl="ring"`` and the same ``sp_axis``.
    """

    def local_loss(params, batch, rng, global_count):
        # NOTE: no psum inside the differentiated function. Under full-manual
        # shard_map (check_vma=False) the transpose of psum is psum, so a psum
        # in the loss would scale gradients by the axis size. The pattern:
        # token-local masked sum over a *global* normalizer (computed outside
        # the grad), then psum the gradients once.
        x = batch["x"]
        t_loc = x.shape[1]
        idx = jax.lax.axis_index(sp_axis)
        logits = model.apply(
            {"params": params},
            x,
            train=True,
            pos_offset=idx * t_loc,
            rngs={"dropout": jax.random.fold_in(rng, idx)},
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
        return jnp.sum(ce * batch["mask"]) / global_count

    batch_spec = {"x": P(None, sp_axis), "y": P(None, sp_axis), "mask": P(None, sp_axis)}

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec, P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def step(params, opt_state, batch, rng):
        global_count = jnp.maximum(
            jax.lax.psum(jnp.sum(batch["mask"]), sp_axis), 1.0
        )
        loss, grads = jax.value_and_grad(local_loss)(params, batch, rng, global_count)
        loss = jax.lax.psum(loss, sp_axis)
        # each shard's grad covers only its tokens' contribution
        grads = jax.lax.psum(grads, sp_axis)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step)


def shard_lm_batch(batch: dict, mesh: Mesh, sp_axis: str = SP_AXIS) -> dict:
    """Device-put a [B, T] token batch with T sharded over the sp axis."""
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, P(None, sp_axis))
    return {k: jax.device_put(jnp.asarray(v), sh) for k, v in batch.items()}
