"""Multi-host (multi-controller) runtime: the jax_dcn backend.

Reference role: the reference scales past one machine with MPI worker
processes exchanging pickled state over ethernet
(fedml_core/distributed/communication/mpi/com_manager.py:13) or
tensor-native TRPC (trpc/trpc_comm_manager.py:26). The TPU-native answer
(SURVEY §5.8) is not message passing at all: ``jax.distributed`` forms ONE
logical device mesh out of every host's chips, and the engine's round
program — vmapped local SGD + aggregation all-reduce — runs unchanged over
it, with XLA routing the collectives over ICI within a host and DCN across
hosts. A federated job on N hosts is the same single program, with the
``clients`` mesh axis now spanning processes.

Each process stages only the shards it owns (``stage_global`` /
``jax.make_array_from_callback``); host-side cohort sampling and shuffling
are deterministic in (seed, round), so every controller computes identical
index maps without communicating — the multi-controller discipline.

Tested with N local CPU processes (gloo collectives) — see
tests/test_multihost.py; the same code path drives real multi-host TPU pods
where ``jax.distributed.initialize()`` picks up the TPU coordinator
automatically.
"""

from __future__ import annotations

import os

import numpy as np


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_count: int | None = None,
    platform: str | None = None,
) -> None:
    """Join (or form) the multi-controller runtime.

    On TPU pods all arguments are auto-detected. For CPU-based testing or
    bespoke clusters, pass coordinator ``host:port``, world size, and this
    process's id. ``local_device_count`` forces N virtual CPU devices per
    process and ``platform="cpu"`` pins the backend (overriding any
    site-level platform pin); both must run before first jax use.
    """
    if local_device_count is not None:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        opt = f"--xla_force_host_platform_device_count={local_device_count}"
        if "xla_force_host_platform_device_count" in flags:
            # an inherited value (e.g. a test harness's =8) must not
            # silently override the caller's explicit topology
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", opt, flags
            )
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = (flags + " " + opt).strip()

    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_client_mesh(silo: int = 1):
    """A mesh over every device in the job (all hosts), clients x silo —
    the multi-host version of parallel.mesh.client_mesh/silo_mesh (same
    axis names and argument convention: ``silo`` is the silo-group size)."""
    import jax

    from fedml_tpu.parallel import mesh as meshlib

    devices = list(jax.devices())
    if silo > 1:
        if len(devices) % silo:
            raise ValueError(f"{len(devices)} devices not divisible by silo={silo}")
        return meshlib.silo_mesh(len(devices) // silo, devices)
    return meshlib.client_mesh(devices)


def stage_global(host_array: np.ndarray, sharding):
    """Build a global (possibly cross-process) jax.Array from a host array
    every process holds identically: each process materializes only its
    addressable shards. Single-process this is equivalent to device_put."""
    import jax

    host_array = np.asarray(host_array)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx]
    )


def flatten_variables(variables) -> np.ndarray:
    """Canonical flat f32 view of a model pytree (leaf order = jax.tree
    order) — the npz exchange format used by the multihost entry/tests to
    compare controllers' results."""
    import jax

    return np.concatenate([
        np.ravel(np.asarray(l)) for l in jax.tree.leaves(variables)
    ])
