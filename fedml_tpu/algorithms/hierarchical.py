"""Hierarchical (two-level) FedAvg: clients → groups → global.

Reference: fedml_api/standalone/hierarchical_fl/ — random group assignment
(trainer.py:10-30), nested loops global_comm_round × group_comm_round ×
epochs with epoch-aligned aggregation (trainer.py:43-69, group.py:93-115).
(The reference file has a stale import and cannot actually run — SURVEY §2.3;
the capability is reproduced here, working.)

Invariant carried to tests: with full-batch E=1 and all clients, hierarchical
FL equals centralized GD for ANY grouping whose global×group round product is
fixed (CI-script-fedavg.sh:50-58).

Production analogue: cross-silo (intra-silo DP under a silo master under the
FL server) — on TPU the group level maps onto mesh axes (SURVEY §3.3).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from fedml_tpu.core import tree as treelib
from fedml_tpu.sim.cohort import FederatedArrays
from fedml_tpu.sim.engine import FedSim, SimConfig


def random_group_assignment(n_clients: int, n_groups: int, seed: int = 0) -> dict[int, np.ndarray]:
    """group id -> client ids (trainer.py:10-30 random partition)."""
    rng = np.random.RandomState(seed)
    perm = rng.permutation(n_clients)
    return {g: np.sort(part) for g, part in enumerate(np.array_split(perm, n_groups))}


@dataclasses.dataclass
class HierConfig:
    group_num: int = 2
    global_comm_round: int = 2
    group_comm_round: int = 2
    group_seed: int = 0


class HierarchicalFedAvg:
    """Two-level loop reusing the vectorized round program per group."""

    def __init__(self, sim: FedSim, hier: HierConfig):
        if sim._per_client:
            raise ValueError(
                "HierarchicalFedAvg drives the broadcast-global round program; "
                "per-client aggregators (decentralized/gossip) are not composable here"
            )
        self.sim = sim
        self.hier = hier
        self.groups = random_group_assignment(
            sim.config.client_num_in_total, hier.group_num, hier.group_seed
        )

    def run(self):
        sim, hier = self.sim, self.hier
        variables = jax.device_put(sim.init_variables(), sim._rep)
        server_state = sim.aggregator.init_state(variables)
        from fedml_tpu.core import rng as rnglib

        root = rnglib.root_key(sim.config.seed)
        history = []
        round_counter = 0
        for g_round in range(hier.global_comm_round):
            group_models, group_weights = [], []
            for gid, client_ids in self.groups.items():
                # sim._round_fn donates its params argument; give each group a
                # private copy so the global model survives all groups.
                gvars = jax.tree.map(jnp.copy, variables)
                for _ in range(hier.group_comm_round):
                    # shared staging + dispatch: straggler budgets, padding,
                    # sharding, and the on-device index-map path all behave
                    # identically to the flat engine
                    rkey = rnglib.round_key(root, round_counter)
                    gvars, server_state, _ = sim.run_cohort_round(
                        client_ids, round_counter, gvars, server_state, rkey
                    )
                    round_counter += 1
                group_models.append(gvars)
                group_weights.append(
                    float(sum(len(sim.train_data.partition[int(c)]) for c in client_ids))
                )
            stacked = treelib.tree_stack(group_models)
            variables = treelib.tree_weighted_mean(stacked, jnp.asarray(group_weights))
            rec = {"round": g_round}
            rec.update(sim.evaluate(variables))
            history.append(rec)
        return variables, history

