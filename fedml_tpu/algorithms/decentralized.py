"""Decentralized (serverless) federated optimization.

Two capabilities from the reference:
1. The decentralized_framework template (fedml_api/distributed/
   decentralized_framework/algorithm_api.py:54-65): every rank is a worker on
   a ring/random topology exchanging models with neighbors. Here: the whole
   neighbor exchange is ``mixed = W @ stacked`` — one einsum over the client
   axis, sharded by XLA over the mesh.
2. Gossip online learning (fedml_api/standalone/decentralized/): DSGD
   (client_dsgd.py:6) and Push-Sum over time-varying directed graphs
   (client_pushsum.py:7 with ω-weight bookkeeping :36-45), tracking regret on
   streaming data.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.base import Aggregator

Pytree = Any


def mix(stacked: Pytree, mixing_matrix: jnp.ndarray) -> Pytree:
    """One gossip exchange: for every leaf [C, ...], new_i = Σ_j W[i,j]·x_j.
    This single einsum replaces the reference's per-neighbor message loop
    (decentralized_worker_manager.py handlers)."""

    def _mix(leaf):
        flat = leaf.reshape(leaf.shape[0], -1).astype(jnp.float32)
        out = mixing_matrix @ flat
        # mixing_matrix may carry only a block of rows [R, C] (sharded mix)
        return out.reshape(out.shape[:1] + leaf.shape[1:]).astype(leaf.dtype)

    return jax.tree.map(_mix, stacked)


def gossip_aggregator(mixing_matrix: np.ndarray) -> Aggregator:
    """Decentralized 'aggregation': no global model — each client's next-round
    model is its neighborhood mixture of this round's locally-trained models.

    ``per_client=True``: the engine keeps the full stacked [C, ...] model set
    across rounds (each client trains from its OWN model — the property that
    distinguishes gossip from FedAvg), and this aggregate maps trained stack
    -> mixed stack. Zero-weight mesh-padding slots pass through untouched
    (identity mixing rows appended on the fly; the engine validates that real
    clients == the matrix order via ``num_clients``).

    Sharding: when the engine provides shard extras, only this shard's block
    of mixing rows is computed — W[local] @ stacked — instead of every device
    redundantly producing the full C×C mix.
    """
    W0 = np.asarray(mixing_matrix, np.float32)

    def init_state(stacked_variables):
        return ()

    def aggregate(prev_stacked, stacked, weights, state, rng, extras=None):
        C = jax.tree.leaves(stacked)[0].shape[0]
        if C > W0.shape[0]:  # mesh padding: dummy slots mix only with themselves
            W = np.eye(C, dtype=np.float32)
            W[: W0.shape[0], : W0.shape[1]] = W0
        else:
            W = W0
        # consensus disagreement of the trained models (pre-mix, computed on
        # the fully-gathered stack so the metric is shard-replicated): the
        # quantity one gossip exchange then contracts
        def _disagree(leaf):
            f = leaf.reshape(C, -1).astype(jnp.float32)[: W0.shape[0]]
            return jnp.sum((f - jnp.mean(f, axis=0, keepdims=True)) ** 2)

        dis = sum(jax.tree.leaves(jax.tree.map(_disagree, stacked)))
        metrics = {"consensus_dist": dis / W0.shape[0]}
        if extras is not None and "shard_start" in extras:
            W_rows = jax.lax.dynamic_slice_in_dim(
                jnp.asarray(W), extras["shard_start"], extras["shard_size"], 0
            )
            return mix(stacked, W_rows), state, metrics
        return mix(stacked, jnp.asarray(W)), state, metrics

    return Aggregator(
        init_state, aggregate, name="gossip", per_client=True,
        num_clients=int(W0.shape[0]),
    )


# ---------------------------------------------------------------------------
# Gossip online learning (standalone/decentralized): linear predictors on
# streaming samples, DSGD and Push-Sum, regret metric.
# ---------------------------------------------------------------------------


def dsgd_online_step(params: jnp.ndarray, x: jnp.ndarray, y: jnp.ndarray,
                     W: jnp.ndarray, lr: float):
    """One DSGD round for all N nodes at once.

    params [N, D]; x [N, D] one streaming sample per node; y [N] ±1 labels.
    Logistic loss grad then neighborhood mixing (client_dsgd.py:78-100).
    Returns (new_params, per-node losses).
    """
    def loss_fn(p):
        z = jnp.sum(p * x, axis=1) * y
        return jnp.sum(jnp.log1p(jnp.exp(-z))), jnp.log1p(jnp.exp(-z))

    (_, losses), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    stepped = params - lr * grads
    return W @ stepped, losses


def pushsum_online_step(params: jnp.ndarray, omega: jnp.ndarray, x: jnp.ndarray,
                        y: jnp.ndarray, W_col: jnp.ndarray, lr: float):
    """Push-Sum over a column-stochastic (possibly time-varying) directed
    graph (client_pushsum.py:7, ω bookkeeping :36-45).

    params [N, D] are the push-sum numerators; omega [N] the weights. The
    de-biased estimate x_i = params_i / ω_i takes the gradient step.
    """
    debiased = params / jnp.maximum(omega[:, None], 1e-12)

    def loss_fn(p):
        z = jnp.sum(p * x, axis=1) * y
        return jnp.sum(jnp.log1p(jnp.exp(-z))), jnp.log1p(jnp.exp(-z))

    (_, losses), grads = jax.value_and_grad(loss_fn, has_aux=True)(debiased)
    stepped = params - lr * grads
    new_params = W_col @ stepped
    new_omega = W_col @ omega
    return new_params, new_omega, losses


def run_online_gossip(
    xs: np.ndarray,
    ys: np.ndarray,
    n_nodes: int,
    lr: float = 0.1,
    mode: str = "dsgd",
    topology: np.ndarray | None = None,
    time_varying: bool = False,
    seed: int = 0,
):
    """Streaming gossip learning driver (decentralized_fl_api.py:11-20):
    xs [T, N, D], ys [T, N]; returns (params [N, D], cumulative regret [T])."""
    from fedml_tpu.topology.topology import ring_topology, time_varying_directed

    T, N, D = xs.shape
    params = jnp.zeros((N, D), jnp.float32)
    omega = jnp.ones((N,), jnp.float32)
    W = jnp.asarray(topology if topology is not None else ring_topology(N))

    dsgd = jax.jit(dsgd_online_step)
    push = jax.jit(pushsum_online_step)

    losses_hist = []
    for t in range(T):
        x, y = jnp.asarray(xs[t]), jnp.asarray(ys[t])
        if mode == "dsgd":
            params, losses = dsgd(params, x, y, W, lr)
        elif mode == "pushsum":
            Wt = jnp.asarray(time_varying_directed(N, t)) if time_varying else W
            params, omega, losses = push(params, omega, x, y, Wt, lr)
        else:
            raise ValueError(f"unknown gossip mode {mode!r}")
        losses_hist.append(np.asarray(losses).mean())
    regret = np.cumsum(losses_hist)
    final = params / jnp.maximum(omega[:, None], 1e-12) if mode == "pushsum" else params
    return np.asarray(final), regret
