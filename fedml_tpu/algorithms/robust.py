"""Byzantine-robust aggregation.

Reference: fedml_core/robustness/robust_aggregation.py — norm-difference
clipping of client deltas (:38-49), weak-DP gaussian noise (:51-55),
coordinate-wise median (:57-89), with BN statistics excluded from the
vectorized statistics (:4-9, 28-29); wired into FedAvg by
fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py:176-206
(clip-then-noise defense pipeline).

All defenses are pure functions over the stacked client axis — the reference's
per-client Python loops become one vectorized op. Additional defenses
(trimmed mean, Krum) are standard extensions that fall out of the same
stacked representation.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.base import Aggregator
from fedml_tpu.core import tree as treelib

Pytree = Any


def _is_norm_stat(path: str) -> bool:
    """BatchNorm statistics filter (robust_aggregation.py:28-29 skips
    num_batches_tracked; we exclude the whole batch_stats collection)."""
    return "batch_stats" in path


def clip_deltas(global_params: Pytree, stacked: Pytree, norm_bound: float) -> Pytree:
    """Norm-difference clipping (robust_aggregation.py:38-49): scale each
    client's delta so its L2 norm (over non-BN leaves) is <= norm_bound."""

    def _client_norm(client_tree):
        vec = treelib.tree_vectorize(client_tree, exclude=_is_norm_stat)
        return jnp.linalg.norm(vec)

    deltas = jax.tree.map(lambda s, g: s - g[None], stacked, global_params)
    norms = jax.vmap(lambda i: _client_norm(jax.tree.map(lambda d: d[i], deltas)))(
        jnp.arange(jax.tree_util.tree_leaves(stacked)[0].shape[0])
    )
    scale = jnp.minimum(1.0, norm_bound / jnp.maximum(norms, 1e-12))  # [C]

    def _apply(d_leaf, g_leaf):
        sb = scale.reshape((-1,) + (1,) * (d_leaf.ndim - 1))
        return g_leaf[None] + d_leaf * sb

    return jax.tree.map(_apply, deltas, global_params)


def add_weak_dp_noise(tree: Pytree, stddev: float, rng: jax.Array) -> Pytree:
    """Weak differential privacy: gaussian noise on the aggregate
    (robust_aggregation.py:51-55)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        leaf + jax.random.normal(k, leaf.shape, leaf.dtype) * stddev
        if jnp.issubdtype(leaf.dtype, jnp.floating)
        else leaf
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


def coordinate_median(stacked: Pytree) -> Pytree:
    """Coordinate-wise median over the client axis
    (robust_aggregation.py:57-89)."""
    return jax.tree.map(lambda s: jnp.median(s, axis=0).astype(s.dtype), stacked)


def trimmed_mean(stacked: Pytree, trim_ratio: float = 0.1) -> Pytree:
    """Coordinate-wise trimmed mean: drop the k highest/lowest per coordinate."""

    def _tm(s):
        c = s.shape[0]
        k = int(trim_ratio * c)
        srt = jnp.sort(s, axis=0)
        kept = srt[k : c - k] if c - 2 * k > 0 else srt
        return jnp.mean(kept, axis=0).astype(s.dtype)

    return jax.tree.map(_tm, stacked)


def krum_select(stacked: Pytree, num_byzantine: int = 1) -> jnp.ndarray:
    """Krum: index of the client whose summed distance to its closest
    C−f−2 neighbors is minimal. Returns the selected client index."""
    mat = jax.vmap(lambda i: treelib.tree_vectorize(
        jax.tree.map(lambda s: s[i], stacked), exclude=_is_norm_stat
    ))(jnp.arange(jax.tree_util.tree_leaves(stacked)[0].shape[0]))  # [C, D]
    d2 = jnp.sum((mat[:, None, :] - mat[None, :, :]) ** 2, axis=-1)  # [C, C]
    C = mat.shape[0]
    closest = C - num_byzantine - 2
    closest = max(closest, 1)
    d2 = d2 + jnp.eye(C) * jnp.inf  # exclude self
    scores = jnp.sum(jnp.sort(d2, axis=1)[:, :closest], axis=1)
    return jnp.argmin(scores)


@dataclasses.dataclass(frozen=True)
class RobustConfig:
    """Defense pipeline flags (FedAvgRobustAggregator defense_type args)."""

    norm_bound: float = 0.0  # >0 enables clipping
    stddev: float = 0.0  # >0 enables weak-DP noise
    rule: str = "mean"  # mean | median | trimmed_mean | krum
    trim_ratio: float = 0.1
    num_byzantine: int = 1


def robust_aggregator(config: RobustConfig) -> Aggregator:
    """Clip → combine (mean/median/trimmed/krum) → noise, the reference
    pipeline (FedAvgRobustAggregator.py:176-206) as one jitted function."""

    def init_state(global_variables):
        return ()

    def aggregate(global_variables, stacked, weights, state, rng, extras=None):
        if config.norm_bound > 0:
            stacked = clip_deltas(global_variables, stacked, config.norm_bound)
        if config.rule == "median":
            out = coordinate_median(stacked)
        elif config.rule == "trimmed_mean":
            out = trimmed_mean(stacked, config.trim_ratio)
        elif config.rule == "krum":
            idx = krum_select(stacked, config.num_byzantine)
            out = jax.tree.map(lambda s: s[idx], stacked)
        else:
            out = treelib.tree_weighted_mean(stacked, weights)
        if config.stddev > 0:
            out = add_weak_dp_noise(out, config.stddev, rng)
        return out, state, {}

    return Aggregator(init_state, aggregate, name=f"robust-{config.rule}")
