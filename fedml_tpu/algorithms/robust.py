"""Byzantine-robust aggregation.

Reference: fedml_core/robustness/robust_aggregation.py — norm-difference
clipping of client deltas (:38-49), weak-DP gaussian noise (:51-55),
coordinate-wise median (:57-89), with BN statistics excluded from the
vectorized statistics (:4-9, 28-29); wired into FedAvg by
fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py:176-206
(clip-then-noise defense pipeline).

All defenses are pure functions over the stacked client axis — the reference's
per-client Python loops become one vectorized op. Additional defenses
(trimmed mean, Krum) are standard extensions that fall out of the same
stacked representation.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.base import Aggregator
from fedml_tpu.core import tree as treelib

Pytree = Any


def _is_norm_stat(path: str) -> bool:
    """BatchNorm statistics filter (robust_aggregation.py:28-29 skips
    num_batches_tracked; we exclude the whole batch_stats collection)."""
    return "batch_stats" in path


def clip_scale(norms, norm_bound: float):
    """THE norm-difference clip factor (robust_aggregation.py:38-49):
    ``min(1, bound / max(norm, 1e-12))``. Single source of the clip
    arithmetic — shared by the sim engine's stacked :func:`clip_deltas`
    and the wire path's per-upload streaming clip
    (algorithms/robust_distributed.py), so both defenses are one
    definition. Accepts jnp tracers and np scalars alike."""
    return jnp.minimum(1.0, norm_bound / jnp.maximum(norms, 1e-12))


def delta_norms(global_params: Pytree, stacked: Pytree) -> tuple[Pytree, jnp.ndarray]:
    """Per-client deltas and their L2 norms over non-BN leaves. Returns
    (deltas with leaves [C, ...], norms [C])."""

    def _client_norm(client_tree):
        vec = treelib.tree_vectorize(client_tree, exclude=_is_norm_stat)
        return jnp.linalg.norm(vec)

    deltas = jax.tree.map(lambda s, g: s - g[None], stacked, global_params)
    norms = jax.vmap(lambda i: _client_norm(jax.tree.map(lambda d: d[i], deltas)))(
        jnp.arange(jax.tree_util.tree_leaves(stacked)[0].shape[0])
    )
    return deltas, norms


def clip_deltas(global_params: Pytree, stacked: Pytree, norm_bound: float) -> Pytree:
    """Norm-difference clipping (robust_aggregation.py:38-49): scale each
    client's delta so its L2 norm (over non-BN leaves) is <= norm_bound."""
    deltas, norms = delta_norms(global_params, stacked)
    scale = clip_scale(norms, norm_bound)  # [C]

    def _apply(d_leaf, g_leaf):
        sb = scale.reshape((-1,) + (1,) * (d_leaf.ndim - 1))
        return g_leaf[None] + d_leaf * sb

    return jax.tree.map(_apply, deltas, global_params)


# --- flat-vector (wire payload) defense helpers ------------------------------
# The message-passing server folds pack_pytree byte vectors (all-f32 leaves,
# validated at server init) — these helpers apply the SAME defense statistics
# to that layout so the sim and distributed paths share one definition of
# "what gets clipped and over which coordinates".


def flat_norm_mask(model_desc: str) -> np.ndarray | None:
    """Elementwise bool mask over the ``pack_pytree`` f32 wire layout:
    False on BatchNorm-statistics leaves (:func:`_is_norm_stat`), which the
    robust statistics exclude. Returns None when nothing is excluded (the
    common no-BN case — callers skip the masked gather entirely)."""
    desc = json.loads(model_desc)
    if not any(_is_norm_stat(d["path"]) for d in desc):
        return None
    parts = [
        np.full(int(np.prod(d["shape"])) if d["shape"] else 1,
                not _is_norm_stat(d["path"]))
        for d in desc
    ]
    return np.concatenate(parts)


def flat_delta_norm(delta: np.ndarray, mask: np.ndarray | None) -> float:
    """L2 norm of a flat f32 delta vector over non-excluded coordinates —
    the wire-path counterpart of :func:`delta_norms` (f32 accumulation,
    matching the sim's ``jnp.linalg.norm`` over f32)."""
    v = delta if mask is None else delta[mask]
    return float(np.linalg.norm(v))


def add_cli_flags(parser):
    """Register the canonical robust-defense flags on a repro entry point
    (one help text everywhere; mirrors obs.trace.add_cli_flag). The flags
    map 1:1 onto the SimConfig robust fields via
    :func:`sim_config_fields`."""
    parser.add_argument("--robust_rule", type=str, default="mean",
                        choices=list(RobustConfig.RULES),
                        help="robust combine rule over the cohort stack "
                             "(docs/ROBUSTNESS.md); 'mean' is plain FedAvg")
    parser.add_argument("--norm_bound", type=float, default=0.0,
                        help="clip each client delta's L2 norm to this "
                             "bound (0 = no clipping)")
    parser.add_argument("--dp_stddev", type=float, default=0.0,
                        help="seeded weak-DP gaussian noise stddev on the "
                             "aggregate (0 = no noise)")
    return parser


def sim_config_fields(args) -> dict:
    """The SimConfig kwargs for :func:`add_cli_flags`'s values."""
    return {
        "robust_rule": args.robust_rule,
        "norm_bound": args.norm_bound,
        "dp_stddev": args.dp_stddev,
    }


def dp_noise_key(seed: int, round_idx: int) -> jax.Array:
    """Round-indexed DP noise key: ``fold_in(key(seed), round)`` — the
    seeded schedule the wire path's streaming and buffered arms share, so
    clipped+DP runs are bit-reproducible (and bit-identical across arms)."""
    return jax.random.fold_in(jax.random.key(seed), round_idx)


def add_weak_dp_noise(tree: Pytree, stddev: float, rng: jax.Array) -> Pytree:
    """Weak differential privacy: gaussian noise on the aggregate
    (robust_aggregation.py:51-55)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        leaf + jax.random.normal(k, leaf.shape, leaf.dtype) * stddev
        if jnp.issubdtype(leaf.dtype, jnp.floating)
        else leaf
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


def coordinate_median(stacked: Pytree) -> Pytree:
    """Coordinate-wise median over the client axis
    (robust_aggregation.py:57-89)."""
    return jax.tree.map(lambda s: jnp.median(s, axis=0).astype(s.dtype), stacked)


def trimmed_ratio_k(c: int, trim_ratio: float) -> int:
    """Per-side trim count ``k = int(trim_ratio * C)``, validated: a config
    where ``C - 2k <= 0`` would trim away every client — the old code
    silently fell back to a plain mean, masking the misconfiguration."""
    k = int(trim_ratio * c)
    if c - 2 * k <= 0:
        raise ValueError(
            f"trimmed_mean: trim_ratio={trim_ratio} with C={c} clients trims "
            f"k={k} per side, leaving C - 2k = {c - 2 * k} <= 0 updates — "
            "nothing to average; lower trim_ratio (or grow the cohort)"
        )
    return k


def trimmed_mean(stacked: Pytree, trim_ratio: float = 0.1) -> Pytree:
    """Coordinate-wise trimmed mean: drop the k highest/lowest per coordinate."""
    c = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    k = trimmed_ratio_k(c, trim_ratio)

    def _tm(s):
        srt = jnp.sort(s, axis=0)
        return jnp.mean(srt[k : c - k], axis=0).astype(s.dtype)

    return jax.tree.map(_tm, stacked)


def krum_select(stacked: Pytree, num_byzantine: int = 1) -> jnp.ndarray:
    """Krum: index of the client whose summed distance to its closest
    C−f−2 neighbors is minimal. Returns the selected client index."""
    mat = jax.vmap(lambda i: treelib.tree_vectorize(
        jax.tree.map(lambda s: s[i], stacked), exclude=_is_norm_stat
    ))(jnp.arange(jax.tree_util.tree_leaves(stacked)[0].shape[0]))  # [C, D]
    d2 = jnp.sum((mat[:, None, :] - mat[None, :, :]) ** 2, axis=-1)  # [C, C]
    C = mat.shape[0]
    closest = C - num_byzantine - 2
    if closest < 1:
        # the old code silently clamped to 1, i.e. quietly ran a different
        # (much weaker) selection rule than the one configured
        raise ValueError(
            f"krum_select: num_byzantine={num_byzantine} with C={C} clients "
            f"leaves C - f - 2 = {closest} < 1 neighbors to score — Krum "
            f"needs num_byzantine <= C - 3 (here <= {C - 3})"
        )
    d2 = d2 + jnp.eye(C) * jnp.inf  # exclude self
    scores = jnp.sum(jnp.sort(d2, axis=1)[:, :closest], axis=1)
    return jnp.argmin(scores)


@dataclasses.dataclass(frozen=True)
class RobustConfig:
    """Defense pipeline flags (FedAvgRobustAggregator defense_type args)."""

    norm_bound: float = 0.0  # >0 enables clipping
    stddev: float = 0.0  # >0 enables weak-DP noise
    rule: str = "mean"  # mean | median | trimmed_mean | krum
    trim_ratio: float = 0.1
    num_byzantine: int = 1

    RULES = ("mean", "median", "trimmed_mean", "krum")

    def __post_init__(self):
        if self.rule not in self.RULES:
            raise ValueError(
                f"unknown robust rule {self.rule!r} (expected one of "
                f"{self.RULES}) — a silent mean fallback would run no "
                "defense at all"
            )

    @property
    def enabled(self) -> bool:
        """True when any defense stage is active (a disabled config is
        exactly plain FedAvg)."""
        return self.norm_bound > 0 or self.stddev > 0 or self.rule != "mean"


def robust_aggregator(config: RobustConfig) -> Aggregator:
    """Clip → combine (mean/median/trimmed/krum) → noise, the reference
    pipeline (FedAvgRobustAggregator.py:176-206) as one jitted function.

    Round metrics gain the Robust/* keys (obs/metrics.py): mean pre-clip
    delta norm, clipped fraction, and rule-filtered client count — all over
    the real (weight > 0) cohort, excluding padding slots."""
    from fedml_tpu.obs import metrics as metricslib

    def init_state(global_variables):
        return ()

    def aggregate(global_variables, stacked, weights, state, rng, extras=None):
        c = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        real = (weights > 0).astype(jnp.float32)  # padding slots excluded
        n_real = jnp.maximum(jnp.sum(real), 1.0)
        deltas, norms = delta_norms(global_variables, stacked)
        # updates the combine rule discards, counted over REAL clients
        # (median/krum keep one representative; trimmed mean drops k per
        # side of the executed — possibly padded — stack)
        if config.rule in ("median", "krum"):
            filtered = n_real - 1.0
        elif config.rule == "trimmed_mean":
            filtered = jnp.float32(2 * trimmed_ratio_k(c, config.trim_ratio))
        else:
            filtered = jnp.float32(0.0)
        metrics = {
            metricslib.ROBUST_UPDATE_NORM: jnp.sum(norms * real) / n_real,
            metricslib.ROBUST_FILTERED: jnp.float32(filtered),
        }
        if config.norm_bound > 0:
            scale = clip_scale(norms, config.norm_bound)  # [C]
            metrics[metricslib.ROBUST_CLIP_FRACTION] = (
                jnp.sum((scale < 1.0).astype(jnp.float32) * real) / n_real
            )

            def _apply(d_leaf, g_leaf):
                sb = scale.reshape((-1,) + (1,) * (d_leaf.ndim - 1))
                return g_leaf[None] + d_leaf * sb

            stacked = jax.tree.map(_apply, deltas, global_variables)
        if config.rule == "median":
            out = coordinate_median(stacked)
        elif config.rule == "trimmed_mean":
            out = trimmed_mean(stacked, config.trim_ratio)
        elif config.rule == "krum":
            idx = krum_select(stacked, config.num_byzantine)
            out = jax.tree.map(lambda s: s[idx], stacked)
        else:
            out = treelib.tree_weighted_mean(stacked, weights)
        if config.stddev > 0:
            out = add_weak_dp_noise(out, config.stddev, rng)
        return out, state, metrics

    return Aggregator(init_state, aggregate, name=f"robust-{config.rule}")
