"""FedGKT over the message-passing comm layer.

Reference: fedml_api/distributed/fedgkt/ — GKTServerManager.py:8 and
GKTClientManager run server and clients as separate processes; each round a
client uploads its extracted feature maps, local logits, and labels
(GKTClientTrainer.py:49 train -> extracted_feature_dict/logits_dict/
labels_dict), the server trains the big model on them with bidirectional KL
(GKTServerTrainer.train_and_eval) and sends its logits back per client.
This module is that real multi-process path: features/logits/labels are
typed array payloads over any comm backend — the raw images never leave the
client.

Numerics contract: both sides call the SAME jitted phase programs as the
in-process ``run_fedgkt`` (client_train / server_train with an identical
key schedule), so the loopback run is bit-identical to it
(tests/test_comm_pipelines.py). The exchange granularity is per-round
(one upload + one feedback per client per round), matching the reference.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedgkt import FedGKT
from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message, pack_pytree, unpack_pytree

Pytree = Any


class GKTMsg:
    MSG_TYPE_S2C_INIT = 1
    MSG_TYPE_S2C_ROUND = 2      # round key (+ server logits after round 0)
    MSG_TYPE_C2S_FEATURES = 3   # feats, client logits, labels, masks
    MSG_TYPE_S2C_FINISHED = 4
    MSG_TYPE_C2S_FINAL_VARS = 5

    KEY_MODEL = Message.MSG_ARG_KEY_MODEL_PARAMS
    KEY_DESC = Message.MSG_ARG_KEY_MODEL_DESC
    KEY_ROUND = Message.MSG_ARG_KEY_ROUND_IDX
    KEY_ROUND_KEY = "round_key"
    KEY_SERVER_LOGITS = "server_logits"
    KEY_FEATS = "extracted_features"
    KEY_LOGITS = "client_logits"
    KEY_Y = "labels"
    KEY_MASK = "masks"


class GKTServerManager(ServerManager):
    """Holds the big server model; trains on uploaded features each round
    (GKTServerManager.py:8 role)."""

    def __init__(self, comm: BaseCommunicationManager, gkt: FedGKT,
                 n_clients: int, rounds: int, server_epochs: int,
                 rng: jax.Array, cvars0: Pytree, svars: Pytree):
        super().__init__(comm, rank=0, size=n_clients + 1)
        # send_init_msg unconditionally starts round 0, so rounds=0 would
        # still run one full round — reject it up front (same contract as
        # repro_ceilings.centralized_ceiling)
        if rounds < 1:
            raise ValueError(f"FedGKT needs rounds >= 1, got {rounds}")
        self.gkt = gkt
        self.n_clients = n_clients
        self.rounds = rounds
        self.server_epochs = server_epochs
        self.server_train = jax.jit(gkt.server_train, static_argnums=5)
        self.svars = svars
        self.rng = rng
        self.round_idx = 0
        self._uploads: dict[int, dict[str, np.ndarray]] = {}
        self.final_cvars: dict[int, Pytree] = {}
        self._flat0, self._desc = pack_pytree(jax.tree.map(np.asarray, cvars0))

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            GKTMsg.MSG_TYPE_C2S_FEATURES, self._on_features
        )
        self.register_message_receive_handler(
            GKTMsg.MSG_TYPE_C2S_FINAL_VARS, self._on_final_vars
        )

    def send_init_msg(self) -> None:
        for w in range(1, self.n_clients + 1):
            msg = Message(GKTMsg.MSG_TYPE_S2C_INIT, 0, w)
            msg.add_params(GKTMsg.KEY_MODEL, self._flat0)
            msg.add_params(GKTMsg.KEY_DESC, self._desc)
            self.send_message(msg)
        self._start_round(None)

    def _start_round(self, per_client_logits: list[np.ndarray] | None) -> None:
        # key schedule identical to run_fedgkt: one split per (round, client)
        # in client order; round 0 sends no logits (clients use zeros —
        # the reference warm-up)
        for w in range(1, self.n_clients + 1):
            self.rng, sub = jax.random.split(self.rng)
            msg = Message(GKTMsg.MSG_TYPE_S2C_ROUND, 0, w)
            msg.add_params(GKTMsg.KEY_ROUND, self.round_idx)
            msg.add_params(GKTMsg.KEY_ROUND_KEY,
                           np.asarray(jax.random.key_data(sub)))
            if per_client_logits is not None:
                msg.add_params(GKTMsg.KEY_SERVER_LOGITS, per_client_logits[w - 1])
            self.send_message(msg)

    def _on_features(self, msg: Message) -> None:
        self._uploads[msg.get_sender_id()] = {
            "feats": np.asarray(msg.get(GKTMsg.KEY_FEATS)),
            "logits": np.asarray(msg.get(GKTMsg.KEY_LOGITS)),
            "y": np.asarray(msg.get(GKTMsg.KEY_Y)),
            "mask": np.asarray(msg.get(GKTMsg.KEY_MASK)),
        }
        if len(self._uploads) < self.n_clients:
            return
        # concatenate in client order (run_fedgkt oracle order)
        ups = [self._uploads[w] for w in range(1, self.n_clients + 1)]
        sizes = [u["y"].shape[0] for u in ups]
        feats = jnp.concatenate([jnp.asarray(u["feats"]) for u in ups], 0)
        clog = jnp.concatenate([jnp.asarray(u["logits"]) for u in ups], 0)
        ys = jnp.concatenate([jnp.asarray(u["y"]) for u in ups], 0)
        ms = jnp.concatenate([jnp.asarray(u["mask"]) for u in ups], 0)
        self._uploads = {}
        self.svars, slog = self.server_train(
            self.svars, feats, clog, ys, ms, self.server_epochs
        )
        slog = np.asarray(slog)
        per_client, off = [], 0
        for s in sizes:
            per_client.append(slog[off:off + s])
            off += s
        self.round_idx += 1
        if self.round_idx >= self.rounds:
            for w in range(1, self.n_clients + 1):
                self.send_message(Message(GKTMsg.MSG_TYPE_S2C_FINISHED, 0, w))
        else:
            self._start_round(per_client)

    def _on_final_vars(self, msg: Message) -> None:
        flat = np.asarray(msg.get(GKTMsg.KEY_MODEL))
        self.final_cvars[msg.get_sender_id()] = jax.tree.map(
            jnp.asarray, unpack_pytree(flat, self._desc)
        )
        if len(self.final_cvars) == self.n_clients:
            self.finish()


class GKTClientManager(ClientManager):
    """Holds the small edge model + its shard; uploads features per round
    (GKTClientManager role)."""

    def __init__(self, comm: BaseCommunicationManager, rank: int, size: int,
                 gkt: FedGKT, batches: dict[str, jnp.ndarray],
                 client_epochs: int):
        super().__init__(comm, rank, size)
        self.gkt = gkt
        self.batches = batches  # [S, B, ...] stack
        self.client_epochs = client_epochs
        self.client_train = jax.jit(gkt.client_train, static_argnums=3)
        self.cvars: Pytree = None
        self._n_classes: int | None = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(GKTMsg.MSG_TYPE_S2C_INIT, self._on_init)
        self.register_message_receive_handler(GKTMsg.MSG_TYPE_S2C_ROUND, self._on_round)
        self.register_message_receive_handler(
            GKTMsg.MSG_TYPE_S2C_FINISHED, self._on_finished
        )

    def _on_init(self, msg: Message) -> None:
        flat = np.asarray(msg.get(GKTMsg.KEY_MODEL))
        self.cvars = jax.tree.map(
            jnp.asarray, unpack_pytree(flat, msg.get(GKTMsg.KEY_DESC))
        )
        _, logits = self.gkt.client_module.apply(
            self.cvars, self.batches["x"][0], train=False
        )
        self._n_classes = int(logits.shape[-1])

    def _on_round(self, msg: Message) -> None:
        raw = msg.get(GKTMsg.KEY_SERVER_LOGITS)
        if raw is None:  # round 0: the reference's zero-logit warm-up
            s_logits = jnp.zeros(
                tuple(np.shape(self.batches["y"])) + (self._n_classes,)
            )
        else:
            s_logits = jnp.asarray(raw)
        key = jax.random.wrap_key_data(jnp.asarray(msg.get(GKTMsg.KEY_ROUND_KEY)))
        self.cvars, feats, logits = self.client_train(
            self.cvars, self.batches, s_logits, self.client_epochs, key
        )
        out = Message(GKTMsg.MSG_TYPE_C2S_FEATURES, self.rank, 0)
        out.add_params(GKTMsg.KEY_FEATS, np.asarray(feats))
        out.add_params(GKTMsg.KEY_LOGITS, np.asarray(logits))
        out.add_params(GKTMsg.KEY_Y, np.asarray(self.batches["y"]))
        out.add_params(GKTMsg.KEY_MASK, np.asarray(self.batches["mask"]))
        self.send_message(out)

    def _on_finished(self, msg: Message) -> None:
        out = Message(GKTMsg.MSG_TYPE_C2S_FINAL_VARS, self.rank, 0)
        flat, _ = pack_pytree(jax.tree.map(np.asarray, self.cvars))
        out.add_params(GKTMsg.KEY_MODEL, flat)
        self.send_message(out)
        self.finish()


def run_distributed_fedgkt(
    gkt: FedGKT,
    client_batches: list[dict],
    rounds: int,
    client_epochs: int,
    server_epochs: int,
    rng: jax.Array,
    make_comm: Callable[[int], BaseCommunicationManager],
):
    """FedGKT over any comm fabric. Returns (cvars per client, svars) — the
    same contract as ``run_fedgkt``."""
    from fedml_tpu.algorithms.fedavg_distributed import run_manager_protocol

    sample_x = client_batches[0]["x"][0]
    cvars0, svars = gkt.init(rng, sample_x)

    server = GKTServerManager(
        make_comm(0), gkt, len(client_batches), rounds, server_epochs,
        rng, cvars0, svars,
    )
    clients = [
        GKTClientManager(make_comm(r), r, len(client_batches) + 1, gkt, b,
                         client_epochs)
        for r, b in enumerate(client_batches, start=1)
    ]
    run_manager_protocol(server, clients)
    cvars = [server.final_cvars[r] for r in range(1, len(client_batches) + 1)]
    return cvars, server.svars


def run_distributed_fedgkt_loopback(gkt, client_batches, rounds,
                                    client_epochs, server_epochs, rng):
    from fedml_tpu.comm.loopback import LoopbackCommManager, LoopbackFabric

    fabric = LoopbackFabric(len(client_batches) + 1)
    return run_distributed_fedgkt(
        gkt, client_batches, rounds, client_epochs, server_epochs, rng,
        lambda r: LoopbackCommManager(fabric, r),
    )
