"""SplitNN: model split at a cut layer between client and server.

Reference: fedml_api/distributed/split_nn/ — client computes activations
(client.py:24-30 forward_pass), sends them; server finishes the forward,
computes loss, backprops and returns ``acts.grad`` (server.py:40-60); clients
take turns in a relay ring (server.py:62-72 active-node rotation).

TPU-native: the activation/gradient exchange is an explicit ``jax.vjp``
boundary — the same two-program structure, jittable end to end. This module
is the single-program simulation path (both halves in one jitted scan);
``splitnn_dist.py`` runs the same protocol over the comm layer with the
activation/grad arrays as wire payloads, bit-identical to this path
(tests/test_comm_pipelines.py). This is 2-stage pipeline parallelism; the
cut generalizes to a mesh axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core import scan as scanlib

Pytree = Any


@dataclasses.dataclass
class SplitNN:
    """client_module: x -> activations; server_module: activations -> logits."""

    client_module: Any
    server_module: Any
    client_opt: optax.GradientTransformation
    server_opt: optax.GradientTransformation

    def init(self, rng: jax.Array, sample_x: jnp.ndarray):
        k1, k2 = jax.random.split(rng)
        cvars = self.client_module.init({"params": k1, "dropout": k1}, sample_x, train=False)
        acts = self.client_module.apply(cvars, sample_x, train=False)
        svars = self.server_module.init({"params": k2, "dropout": k2}, acts, train=False)
        return dict(cvars), dict(svars)

    def train_step(self, cvars: Pytree, svars: Pytree, c_opt_state, s_opt_state,
                   batch: dict[str, jnp.ndarray], rng: jax.Array):
        """One split step with the explicit activation/grad boundary."""
        x, y, mask = batch["x"], batch["y"], batch["mask"]

        # --- client forward (client.py:24-30); vjp captures the backward ---
        def client_fwd(cp):
            return self.client_module.apply({**cvars, "params": cp}, x, train=True,
                                            rngs={"dropout": rng})

        acts, client_vjp = jax.vjp(client_fwd, cvars["params"])

        # --- server forward/backward (server.py:40-60) ---
        def server_loss(sp, acts_in):
            logits = self.server_module.apply({**svars, "params": sp}, acts_in,
                                              train=True, rngs={"dropout": rng})
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)

        (loss, (s_grads, acts_grad)) = (
            server_loss(svars["params"], acts),
            jax.grad(server_loss, argnums=(0, 1))(svars["params"], acts),
        )
        s_updates, s_opt_state = self.server_opt.update(s_grads, s_opt_state, svars["params"])
        new_sp = optax.apply_updates(svars["params"], s_updates)

        # --- grads cross back to the client (client.py:32-34) ---
        (c_grads,) = client_vjp(acts_grad)
        c_updates, c_opt_state = self.client_opt.update(c_grads, c_opt_state, cvars["params"])
        new_cp = optax.apply_updates(cvars["params"], c_updates)

        return ({**cvars, "params": new_cp}, {**svars, "params": new_sp},
                c_opt_state, s_opt_state, loss)


def run_splitnn_relay(
    split: SplitNN,
    client_batches: list[dict[str, jnp.ndarray]],
    epochs: int,
    rng: jax.Array,
):
    """Relay training: clients take turns against the shared server half
    (server.py:62-72 rotation). ``client_batches[i]`` is client i's
    [S, B, ...] batch stack. Client halves are per-client; the server half is
    shared state across the relay."""
    sample_x = jax.tree.map(lambda v: v[0], client_batches[0])["x"]
    cvars0, svars = split.init(rng, sample_x)
    cvars = [jax.tree.map(jnp.copy, cvars0) for _ in client_batches]
    s_opt_state = split.server_opt.init(svars["params"])

    @jax.jit
    def train_client(cv, sv, s_opt, batches, key):
        c_opt = split.client_opt.init(cv["params"])

        def step(carry, batch):
            cv, sv, c_opt, s_opt, key = carry
            key, sub = jax.random.split(key)
            cv, sv, c_opt, s_opt, loss = split.train_step(cv, sv, c_opt, s_opt, batch, sub)
            return (cv, sv, c_opt, s_opt, key), loss

        (cv, sv, _, s_opt, _), losses = scanlib.scan(
            step, (cv, sv, c_opt, s_opt, key), batches
        )
        return cv, sv, s_opt, losses.mean()

    losses = []
    for _ in range(epochs):
        for ci, batches in enumerate(client_batches):  # relay ring
            rng, sub = jax.random.split(rng)
            cvars[ci], svars, s_opt_state, loss = train_client(
                cvars[ci], svars, s_opt_state, batches, sub
            )
            losses.append(float(loss))
    return cvars, svars, losses


def splitnn_eval(split: SplitNN, cvars, svars, batches):
    logits_correct = 0.0
    total = 0.0
    for b in range(batches["x"].shape[0]):
        x, y, m = batches["x"][b], batches["y"][b], batches["mask"][b]
        acts = split.client_module.apply(cvars, x, train=False)
        logits = split.server_module.apply(svars, acts, train=False)
        logits_correct += float(jnp.sum((jnp.argmax(logits, -1) == y) * m))
        total += float(jnp.sum(m))
    return logits_correct / max(total, 1.0)
