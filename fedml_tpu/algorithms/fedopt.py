"""FedOpt: server-side adaptive optimization (FedAdam/FedYogi/FedAdagrad/
FedAvgM family).

Reference: fedml_api/distributed/fedopt/FedOptAggregator.py:94-120 — weighted-
average the client models, set the *pseudo-gradient* ``old − avg`` on the
global params, and step a torch server optimizer looked up by name from
``OptRepo`` (optrepo.py:7-25) with ``server_lr`` / ``server_momentum``.

Here the server optimizer is any optax GradientTransformation — optax covers
the whole OptRepo surface natively. Only the ``params`` collection gets the
optimizer treatment; auxiliary state (BN stats) is plainly averaged, matching
the reference which applies the optimizer to named parameters only.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms.base import Aggregator
from fedml_tpu.core import tree as treelib


def server_optimizer(name: str, server_lr: float = 1.0, server_momentum: float = 0.9) -> optax.GradientTransformation:
    """Name dispatch mirroring OptRepo.name2cls (fedopt/optrepo.py:25)."""
    name = name.lower()
    if name in ("sgd", "fedavgm"):
        return optax.sgd(server_lr, momentum=server_momentum)
    if name in ("adam", "fedadam"):
        return optax.adam(server_lr, b1=server_momentum, eps=1e-3)
    if name in ("yogi", "fedyogi"):
        return optax.yogi(server_lr, b1=server_momentum)
    if name in ("adagrad", "fedadagrad"):
        return optax.adagrad(server_lr)
    if name == "rmsprop":
        return optax.rmsprop(server_lr, momentum=server_momentum)
    if name == "adamw":
        return optax.adamw(server_lr, b1=server_momentum)
    raise ValueError(f"unknown server optimizer {name!r}")


def fedopt_aggregator(opt: optax.GradientTransformation) -> Aggregator:
    def init_state(global_variables):
        return opt.init(global_variables["params"])

    def aggregate(global_variables, stacked, weights, opt_state, rng, extras=None):
        avg = treelib.tree_weighted_mean(stacked, weights)
        # pseudo-gradient: old - avg (FedOptAggregator.set_model_global_grads:109-120)
        pseudo_grad = treelib.tree_sub(global_variables["params"], avg["params"])
        updates, opt_state = opt.update(pseudo_grad, opt_state, global_variables["params"])
        new_params = optax.apply_updates(global_variables["params"], updates)
        new_global = {**avg, "params": new_params}
        return new_global, opt_state, {}

    return Aggregator(init_state, aggregate, name="fedopt")
