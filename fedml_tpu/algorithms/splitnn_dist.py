"""SplitNN over the message-passing comm layer.

Reference: fedml_api/distributed/split_nn/ — the SERVER process holds the top
half and the active client streams per-step activations to it
(client.py:24-34 forward_pass/backward_pass over comm), the server finishes
the forward, backprops and returns the activation gradient (server.py:40-60),
and clients take turns in a relay ring (server.py:62-72 active-node
rotation). This module is the real two-program path: server and clients are
separate threads/processes on any comm backend (loopback for tests, shm for
single-host multiprocess, grpc across hosts), and the activation / gradient
arrays are the wire payloads — never pickled modules.

Numerics contract: the per-step compute is factored into three jitted
functions (``make_split_steps``) used identically by the wire path and by
the in-process stepwise oracle ``run_splitnn_relay_stepwise``; the test
suite asserts the loopback run is bit-identical to the oracle, and the
oracle matches the single-program ``run_splitnn_relay`` scan
(tests/test_comm_pipelines.py) — the same oracle discipline as multihost
and is_mobile.

Protocol state machines (handlers never block their receive loop):
  server: INIT cvars0 -> START_TURN(key) -> [ACTS -> GRADS]* -> next turn
          ... -> FINISHED -> collect FINAL_VARS -> stop
  client: on START_TURN re-init the local optimizer (one relay turn = a
          fresh client optimizer, matching run_splitnn_relay), then drive
          step i from the GRADS handler for step i-1.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms.splitnn import SplitNN
from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message, pack_pytree, unpack_pytree

Pytree = Any


class SplitMsg:
    """Message types (reference split_nn/message_define.py role)."""

    MSG_TYPE_S2C_INIT = 1
    MSG_TYPE_S2C_START_TURN = 2
    MSG_TYPE_C2S_ACTS = 3
    MSG_TYPE_S2C_GRADS = 4
    MSG_TYPE_S2C_FINISHED = 5
    MSG_TYPE_C2S_FINAL_VARS = 6

    KEY_MODEL = Message.MSG_ARG_KEY_MODEL_PARAMS
    KEY_DESC = Message.MSG_ARG_KEY_MODEL_DESC
    KEY_ACTS = "acts"
    KEY_GRADS = "acts_grad"
    KEY_STEP_KEY = "step_key"
    KEY_TURN_KEY = "turn_key"
    KEY_Y = "y"
    KEY_MASK = "mask"
    KEY_LAST = "last_step"


def make_split_steps(split: SplitNN):
    """The three per-step jitted programs of the split protocol. The wire
    path and the in-process stepwise oracle call EXACTLY these, so the wire
    adds serialization only — f32 arrays cross bit-exactly (comm/message.py).

    ``client_backward`` recomputes the cut-layer forward inside ``jax.vjp``
    (same inputs -> same program -> same bits as ``client_forward``): vjp
    residuals never cross the wire, the standard split-learning recompute.
    """

    def _client_fwd(cvars, x, key):
        def fwd(cp):
            return split.client_module.apply(
                {**cvars, "params": cp}, x, train=True, rngs={"dropout": key}
            )

        return fwd

    @jax.jit
    def client_forward(cvars, x, key):
        return _client_fwd(cvars, x, key)(cvars["params"])

    @jax.jit
    def server_step(svars, s_opt_state, acts, y, mask, key):
        # server.py:40-60 — finish forward, loss, backprop, return acts grad
        def server_loss(sp, acts_in):
            logits = split.server_module.apply(
                {**svars, "params": sp}, acts_in, train=True, rngs={"dropout": key}
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)

        loss = server_loss(svars["params"], acts)
        s_grads, acts_grad = jax.grad(server_loss, argnums=(0, 1))(
            svars["params"], acts
        )
        s_updates, s_opt_state = split.server_opt.update(
            s_grads, s_opt_state, svars["params"]
        )
        new_sp = optax.apply_updates(svars["params"], s_updates)
        return {**svars, "params": new_sp}, s_opt_state, acts_grad, loss

    @jax.jit
    def client_backward(cvars, c_opt_state, x, key, acts_grad):
        # client.py:32-34 — the returned grad flows through the local half
        _, vjp = jax.vjp(_client_fwd(cvars, x, key), cvars["params"])
        (c_grads,) = vjp(acts_grad)
        c_updates, c_opt_state = split.client_opt.update(
            c_grads, c_opt_state, cvars["params"]
        )
        new_cp = optax.apply_updates(cvars["params"], c_updates)
        return {**cvars, "params": new_cp}, c_opt_state

    return client_forward, server_step, client_backward


class SplitNNServerManager(ServerManager):
    """Holds the top half; runs the relay rotation (server.py:62-72)."""

    def __init__(self, comm: BaseCommunicationManager, split: SplitNN,
                 n_clients: int, epochs: int, rng: jax.Array,
                 cvars0: Pytree, svars: Pytree):
        super().__init__(comm, rank=0, size=n_clients + 1)
        # send_init_msg unconditionally starts the first relay turn, so an
        # empty schedule would still run one full turn — reject it up front
        # (same contract as repro_ceilings.centralized_ceiling)
        if epochs < 1:
            raise ValueError(f"SplitNN relay needs epochs >= 1, got {epochs}")
        self.split = split
        self.n_clients = n_clients
        self.total_turns = epochs * n_clients
        _, self.server_step, _ = make_split_steps(split)
        self.svars = svars
        self.s_opt_state = split.server_opt.init(svars["params"])
        self.rng = rng
        self.turn = 0
        self.losses: list[float] = []
        self._turn_losses: list[jnp.ndarray] = []
        self.final_cvars: dict[int, Pytree] = {}  # guarded-by: _lock
        self._flat0, self._desc = pack_pytree(jax.tree.map(np.asarray, cvars0))
        self._lock = threading.Lock()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(SplitMsg.MSG_TYPE_C2S_ACTS, self._on_acts)
        self.register_message_receive_handler(
            SplitMsg.MSG_TYPE_C2S_FINAL_VARS, self._on_final_vars
        )

    def send_init_msg(self) -> None:
        for w in range(1, self.n_clients + 1):
            msg = Message(SplitMsg.MSG_TYPE_S2C_INIT, 0, w)
            msg.add_params(SplitMsg.KEY_MODEL, self._flat0)
            msg.add_params(SplitMsg.KEY_DESC, self._desc)
            self.send_message(msg)
        self._start_turn()

    def _start_turn(self) -> None:
        # turn-key schedule identical to run_splitnn_relay's relay loop:
        # rng, sub = split(rng) once per (epoch, client) in ring order
        self.rng, sub = jax.random.split(self.rng)
        active = (self.turn % self.n_clients) + 1
        msg = Message(SplitMsg.MSG_TYPE_S2C_START_TURN, 0, active)
        msg.add_params(SplitMsg.KEY_TURN_KEY, np.asarray(jax.random.key_data(sub)))
        self.send_message(msg)

    def _on_acts(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        acts = jnp.asarray(msg.get(SplitMsg.KEY_ACTS))
        y = jnp.asarray(msg.get(SplitMsg.KEY_Y))
        mask = jnp.asarray(msg.get(SplitMsg.KEY_MASK))
        key = jax.random.wrap_key_data(jnp.asarray(msg.get(SplitMsg.KEY_STEP_KEY)))
        self.svars, self.s_opt_state, acts_grad, loss = self.server_step(
            self.svars, self.s_opt_state, acts, y, mask, key
        )
        self._turn_losses.append(loss)
        out = Message(SplitMsg.MSG_TYPE_S2C_GRADS, 0, sender)
        out.add_params(SplitMsg.KEY_GRADS, np.asarray(acts_grad))
        self.send_message(out)
        if msg.get(SplitMsg.KEY_LAST):
            # same reduction as the scan path: mean of the f32 loss stack
            self.losses.append(float(jnp.stack(self._turn_losses).mean()))
            self._turn_losses = []
            self.turn += 1
            if self.turn >= self.total_turns:
                for w in range(1, self.n_clients + 1):
                    self.send_message(Message(SplitMsg.MSG_TYPE_S2C_FINISHED, 0, w))
            else:
                self._start_turn()

    def _on_final_vars(self, msg: Message) -> None:
        flat = np.asarray(msg.get(SplitMsg.KEY_MODEL))
        with self._lock:
            self.final_cvars[msg.get_sender_id()] = unpack_pytree(flat, self._desc)
            done = len(self.final_cvars) == self.n_clients
        if done:
            self.finish()


class SplitNNClientManager(ClientManager):
    """Holds the bottom half + its shard; streams per-step activations."""

    def __init__(self, comm: BaseCommunicationManager, rank: int, size: int,
                 split: SplitNN, batches: dict[str, jnp.ndarray]):
        super().__init__(comm, rank, size)
        self.split = split
        self.batches = batches  # [S, B, ...] stack
        self.n_steps = int(np.shape(batches["x"])[0])
        self.client_forward, _, self.client_backward = make_split_steps(split)
        self.cvars: Pytree = None
        self.c_opt_state = None
        self.key = None
        self._step_i = 0
        self._step_key = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(SplitMsg.MSG_TYPE_S2C_INIT, self._on_init)
        self.register_message_receive_handler(
            SplitMsg.MSG_TYPE_S2C_START_TURN, self._on_start_turn
        )
        self.register_message_receive_handler(SplitMsg.MSG_TYPE_S2C_GRADS, self._on_grads)
        self.register_message_receive_handler(
            SplitMsg.MSG_TYPE_S2C_FINISHED, self._on_finished
        )

    def _on_init(self, msg: Message) -> None:
        flat = np.asarray(msg.get(SplitMsg.KEY_MODEL))
        self.cvars = jax.tree.map(
            jnp.asarray, unpack_pytree(flat, msg.get(SplitMsg.KEY_DESC))
        )

    def _on_start_turn(self, msg: Message) -> None:
        # a relay turn re-inits the local optimizer (run_splitnn_relay
        # train_client: c_opt = client_opt.init per turn)
        self.c_opt_state = self.split.client_opt.init(self.cvars["params"])
        self.key = jax.random.wrap_key_data(
            jnp.asarray(msg.get(SplitMsg.KEY_TURN_KEY))
        )
        self._step_i = 0
        self._send_acts()

    def _send_acts(self) -> None:
        i = self._step_i
        self.key, sub = jax.random.split(self.key)
        self._step_key = sub
        x = self.batches["x"][i]
        acts = self.client_forward(self.cvars, x, sub)
        msg = Message(SplitMsg.MSG_TYPE_C2S_ACTS, self.rank, 0)
        msg.add_params(SplitMsg.KEY_ACTS, np.asarray(acts))
        msg.add_params(SplitMsg.KEY_STEP_KEY, np.asarray(jax.random.key_data(sub)))
        msg.add_params(SplitMsg.KEY_Y, np.asarray(self.batches["y"][i]))
        msg.add_params(SplitMsg.KEY_MASK, np.asarray(self.batches["mask"][i]))
        msg.add_params(SplitMsg.KEY_LAST, int(i == self.n_steps - 1))
        self.send_message(msg)

    def _on_grads(self, msg: Message) -> None:
        acts_grad = jnp.asarray(msg.get(SplitMsg.KEY_GRADS))
        x = self.batches["x"][self._step_i]
        self.cvars, self.c_opt_state = self.client_backward(
            self.cvars, self.c_opt_state, x, self._step_key, acts_grad
        )
        self._step_i += 1
        if self._step_i < self.n_steps:
            self._send_acts()
        # else: turn over — wait for the next START_TURN or FINISHED

    def _on_finished(self, msg: Message) -> None:
        out = Message(SplitMsg.MSG_TYPE_C2S_FINAL_VARS, self.rank, 0)
        flat, _ = pack_pytree(jax.tree.map(np.asarray, self.cvars))
        out.add_params(SplitMsg.KEY_MODEL, flat)
        self.send_message(out)
        self.finish()


def run_distributed_splitnn(
    split: SplitNN,
    client_batches: Sequence[dict[str, jnp.ndarray]],
    epochs: int,
    rng: jax.Array,
    make_comm: Callable[[int], BaseCommunicationManager],
):
    """SplitNN relay over any comm fabric. Returns (cvars per client, svars,
    per-turn losses) — the same contract as ``run_splitnn_relay``."""
    from fedml_tpu.algorithms.fedavg_distributed import run_manager_protocol

    sample_x = jax.tree.map(lambda v: v[0], client_batches[0])["x"]
    cvars0, svars = split.init(rng, sample_x)

    server = SplitNNServerManager(
        make_comm(0), split, len(client_batches), epochs, rng, cvars0, svars
    )
    clients = [
        SplitNNClientManager(make_comm(r), r, len(client_batches) + 1, split, b)
        for r, b in enumerate(client_batches, start=1)
    ]
    run_manager_protocol(server, clients)
    cvars = [
        jax.tree.map(jnp.asarray, server.final_cvars[r])
        for r in range(1, len(client_batches) + 1)
    ]
    return cvars, server.svars, server.losses


def run_distributed_splitnn_loopback(split, client_batches, epochs, rng):
    """SplitNN relay on the in-process loopback fabric."""
    from fedml_tpu.comm.loopback import LoopbackCommManager, LoopbackFabric

    fabric = LoopbackFabric(len(client_batches) + 1)
    return run_distributed_splitnn(
        split, client_batches, epochs, rng,
        lambda r: LoopbackCommManager(fabric, r),
    )


def run_splitnn_relay_stepwise(
    split: SplitNN,
    client_batches: Sequence[dict[str, jnp.ndarray]],
    epochs: int,
    rng: jax.Array,
):
    """In-process oracle: the SAME per-step jitted programs as the wire path,
    driven sequentially with no comm layer. Bit-comparable to
    ``run_distributed_splitnn`` by construction; cross-checked against the
    single-program ``run_splitnn_relay`` scan in tests."""
    client_forward, server_step, client_backward = make_split_steps(split)
    sample_x = jax.tree.map(lambda v: v[0], client_batches[0])["x"]
    cvars0, svars = split.init(rng, sample_x)
    cvars = [jax.tree.map(jnp.copy, cvars0) for _ in client_batches]
    s_opt_state = split.server_opt.init(svars["params"])

    losses = []
    for _ in range(epochs):
        for ci, batches in enumerate(client_batches):  # relay ring
            rng, sub = jax.random.split(rng)
            c_opt_state = split.client_opt.init(cvars[ci]["params"])
            key = sub
            turn_losses = []
            for i in range(int(np.shape(batches["x"])[0])):
                key, step_key = jax.random.split(key)
                x, y, mask = batches["x"][i], batches["y"][i], batches["mask"][i]
                acts = client_forward(cvars[ci], x, step_key)
                svars, s_opt_state, acts_grad, loss = server_step(
                    svars, s_opt_state, acts, y, mask, step_key
                )
                turn_losses.append(loss)
                cvars[ci], c_opt_state = client_backward(
                    cvars[ci], c_opt_state, x, step_key, acts_grad
                )
            losses.append(float(jnp.stack(turn_losses).mean()))
    return cvars, svars, losses
