from fedml_tpu.algorithms.base import Aggregator, fedavg_aggregator
from fedml_tpu.algorithms.fednova import fednova_aggregator, fednova_optimizer
from fedml_tpu.algorithms.fedopt import fedopt_aggregator, server_optimizer
from fedml_tpu.algorithms.fedprox import fedprox_aggregator, fedprox_trainer
from fedml_tpu.algorithms.robust import RobustConfig, robust_aggregator
