"""Federated NAS (FedNAS): clients run DARTS bilevel search; the server
averages both weights and architecture parameters.

Reference: fedml_api/distributed/fednas/ — FedNASTrainer.search:34 alternates
the architecture step (architect.py:13, 2nd-order approx optional) with the
weight step per batch; FedNASAggregator.py:71-113 averages weights AND α;
record_model_global_architecture:173 decodes the genotype each round.

Here the bilevel alternation is a jitted scan over (train, val) batch pairs:
the α step takes the gradient of the *validation* loss w.r.t. the ``arch``
collection, the weight step the training loss w.r.t. ``params``.

Both architect orders are offered (architect.py:47-55 ``unrolled`` flag):
- first-order (reference ``_backward_step``): ∇α L_val(w, α);
- second-order (``unrolled=True``, reference ``_backward_step_unrolled``
  :169-197, DARTS eq. 7): w' = one real optimizer step on L_train, then
  ∇α L_val(w', α) − η · ∇²_{α,w} L_train(w, α) · ∇w' L_val(w', α).
  The reference approximates the Hessian-vector product by a finite
  difference around w (``_hessian_vector_product``:229-259, eq. 8); here it
  is EXACT — one ``jax.jvp`` through ``jax.grad`` — which is both cheaper
  (no ±R parameter reconstruction) and what the finite difference converges
  to. ``tests/test_fednas.py`` checks it against that finite-difference
  oracle.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms.base import Aggregator
from fedml_tpu.core import scan as scanlib
from fedml_tpu.core import tree as treelib
from fedml_tpu.models.darts import DARTSNetwork, decode_genotype

Pytree = Any


@dataclasses.dataclass(frozen=True)
class FedNASTrainer:
    network: DARTSNetwork
    w_opt: optax.GradientTransformation
    arch_opt: optax.GradientTransformation
    epochs: int = 1
    # second-order architect (architect.py:47): unroll one weight step before
    # the α gradient; ``unrolled_eta`` is the reference's η (network lr) that
    # scales the implicit term in DARTS eq. 7
    unrolled: bool = False
    unrolled_eta: float = 0.025

    def init(self, rng: jax.Array, sample_x: jnp.ndarray) -> Pytree:
        return dict(self.network.init({"params": rng}, sample_x, train=False))

    def _loss(self, params, arch, state, batch, rng):
        out, new_state = self.network.apply(
            {"params": params, "arch": arch, **state}, batch["x"], train=True,
            mutable=[k for k in list(state.keys()) + []] or ["batch_stats"],
            rngs={"gumbel": rng},  # used only by search_mode="gdas"
        )
        ce = optax.softmax_cross_entropy_with_integer_labels(out, batch["y"])
        m = batch["mask"]
        return jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0), new_state

    def arch_grads_unrolled(self, params, arch, state, w_opt_state,
                            train_batch, val_batch, t_rng, v_rng):
        """Second-order architecture gradient (architect.py:169-197).

        w' is one REAL ``w_opt`` update on the training loss (the reference
        reconstructs momentum-SGD by hand in ``_compute_unrolled_model``:32;
        using the live optimizer state covers the same momentum semantics for
        any optax chain), and the ∇²_{α,w}·v term is the exact jvp the
        reference's ±R finite difference (eq. 8) approximates.
        """
        def loss_t(p, a):
            return self._loss(p, a, state, train_batch, t_rng)[0]

        def loss_v(p, a):
            return self._loss(p, a, state, val_batch, v_rng)[0]

        g_w = jax.grad(loss_t)(params, arch)
        updates, _ = self.w_opt.update(g_w, w_opt_state, params)
        w_unrolled = optax.apply_updates(params, updates)

        val_loss, (dalpha, vector) = jax.value_and_grad(
            lambda a, p: loss_v(p, a), argnums=(0, 1)
        )(arch, w_unrolled)
        # exact ∇²_{α,w} L_train(w, α) · vector: differentiate ∇α L_train
        # along direction `vector` in w
        _, implicit = jax.jvp(
            lambda p: jax.grad(loss_t, argnums=1)(p, arch), (params,), (vector,)
        )
        a_grads = jax.tree.map(
            lambda d, i: d - self.unrolled_eta * i, dalpha, implicit
        )
        return val_loss, a_grads

    def search_step(self, variables: Pytree, opt_states, train_batch, val_batch,
                    rng=None):
        """One bilevel alternation (FedNASTrainer.local_search:82-127)."""
        rng = rng if rng is not None else jax.random.key(0)
        a_rng, w_rng = jax.random.split(rng)
        params, arch = variables["params"], variables["arch"]
        state = {k: v for k, v in variables.items() if k not in ("params", "arch")}
        w_opt_state, a_opt_state = opt_states

        if self.unrolled:
            # α step through the unrolled weight step (architect.step unrolled)
            val_loss, a_grads = self.arch_grads_unrolled(
                params, arch, state, w_opt_state, train_batch, val_batch,
                w_rng, a_rng,
            )
        else:
            # α step on validation loss (architect.step, first-order)
            (val_loss, _), a_grads = jax.value_and_grad(
                lambda a: self._loss(params, a, state, val_batch, a_rng), has_aux=True
            )(arch)
        a_updates, a_opt_state = self.arch_opt.update(a_grads, a_opt_state, arch)
        arch = optax.apply_updates(arch, a_updates)

        # weight step on training loss
        (train_loss, new_state), w_grads = jax.value_and_grad(
            lambda p: self._loss(p, arch, state, train_batch, w_rng), has_aux=True
        )(params)
        w_updates, w_opt_state = self.w_opt.update(w_grads, w_opt_state, params)
        params = optax.apply_updates(params, w_updates)

        return (
            {"params": params, "arch": arch, **new_state},
            (w_opt_state, a_opt_state),
            {"train_loss": train_loss, "val_loss": val_loss},
        )

    def local_search(self, global_variables: Pytree, train_batches, val_batches, rng):
        """K epochs of alternating search as one scan — the FedNAS client
        round. val_batches must have the same leading steps axis."""
        opt_states = (
            self.w_opt.init(global_variables["params"]),
            self.arch_opt.init(global_variables["arch"]),
        )

        def epoch(carry, _):
            variables, opt_states, rng_e = carry

            def step(carry, inp):
                variables, opt_states, rng_s = carry
                tb, vb = inp
                rng_s, step_rng = jax.random.split(rng_s)
                variables, opt_states, losses = self.search_step(
                    variables, opt_states, tb, vb, step_rng
                )
                return (variables, opt_states, rng_s), losses["train_loss"]

            (variables, opt_states, rng_e), losses = scanlib.scan(
                step, (variables, opt_states, rng_e), (train_batches, val_batches)
            )
            return (variables, opt_states, rng_e), losses.mean()

        (variables, _, _), epoch_losses = scanlib.scan(
            epoch, (global_variables, opt_states, rng), None, length=self.epochs
        )
        return variables, {"train_loss": epoch_losses[-1]}


def fednas_aggregator() -> Aggregator:
    """Weighted-average weights AND α (FedNASAggregator.py:71-113); metrics
    include the decoded genotype via host callback-free argmax (decode happens
    host-side in the driver)."""

    def init_state(global_variables):
        return ()

    def aggregate(global_variables, stacked, weights, state, rng, extras=None):
        return treelib.tree_weighted_mean(stacked, weights), state, {}

    return Aggregator(init_state, aggregate, name="fednas")


def global_genotype(variables: Pytree):
    """Decode the current global architecture (record_model_global_
    architecture:173)."""
    import numpy as np

    return decode_genotype(
        np.asarray(variables["arch"]["alphas_normal"]),
        np.asarray(variables["arch"]["alphas_reduce"]),
    )
