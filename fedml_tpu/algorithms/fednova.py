"""FedNova: normalized averaging for heterogeneous local work.

Reference: fedml_api/standalone/fednova/fednova.py:10-154 (``FedNova``
optimizer: per-step cum_grad accumulation, local normalizing vector a_i
recurrences for momentum/proximal variants) + fednova_trainer.py:97-125
(server aggregates normalized gradients scaled by tau_eff).

Math carried over exactly:
- client runs tau_i local steps; cum_grad_i = x_global − x_i (the delta)
- a_i: plain SGD → tau_i; momentum m → Σ_t (1−m^t)/(1−m) via the counter
  recurrence; proximal ημ → a ← a(1−ημ)+1 per step
- tau_eff = Σ_i p_i·a_i (p_i = n_i/n; local_steps instead of a_i when μ≠0)
- x' = x − tau_eff · Σ_i p_i · cum_grad_i / a_i

The client optimizer is an optax transformation replicating the reference's
update order (weight decay → momentum buffer → proximal term → step), so
momentum composes with μ exactly as in fednova.py:112-126.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms.base import Aggregator
from fedml_tpu.core import tree as treelib


class FedNovaState(NamedTuple):
    momentum_buf: optax.Params
    old_init: optax.Params


def fednova_optimizer(
    lr: float,
    momentum: float = 0.0,
    mu: float = 0.0,
    dampening: float = 0.0,
    nesterov: bool = False,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Client-side FedNova SGD (reference fednova.py:79-154 step())."""

    def init(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return FedNovaState(momentum_buf=zeros, old_init=params)

    def update(grads, state, params):
        d = grads
        if weight_decay:
            d = jax.tree.map(lambda g, p: g + weight_decay * p, d, params)
        if momentum:
            # first step seeds the buffer with d (reference :115-118)
            def _buf(buf, g):
                return momentum * buf + (1.0 - dampening) * g

            new_buf = jax.tree.map(_buf, state.momentum_buf, d)
            if nesterov:
                d = jax.tree.map(lambda g, b: g + momentum * b, d, new_buf)
            else:
                d = new_buf
        else:
            new_buf = state.momentum_buf
        if mu:
            d = jax.tree.map(
                lambda g, p, o: g + mu * (p - o), d, params, state.old_init
            )
        updates = jax.tree.map(lambda g: -lr * g, d)
        return updates, FedNovaState(momentum_buf=new_buf, old_init=state.old_init)

    return optax.GradientTransformation(init, update)


def normalizing_vector(tau, momentum: float, etamu: float, max_tau: int):
    """a_i for tau local steps (reference fednova.py:139-151 recurrences).
    ``tau`` may be a traced per-client array; recursion runs to ``max_tau``
    with masking so it stays jit-friendly."""

    def body(t, carry):
        counter, a = carry
        active = (t < tau).astype(jnp.float32)
        if momentum != 0.0:
            counter = jnp.where(active > 0, counter * momentum + 1.0, counter)
            a = a + active * counter
        if etamu != 0.0:
            a = jnp.where(active > 0, a * (1.0 - etamu) + 1.0, a)
        if momentum == 0.0 and etamu == 0.0:
            a = a + active
        return counter, a

    shape = jnp.shape(tau)
    init = (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
    _, a = jax.lax.fori_loop(0, max_tau, body, init)
    return a


def fednova_aggregator(
    client_lr: float,
    momentum: float = 0.0,
    mu: float = 0.0,
    batch_size: int = 32,
    epochs: int = 1,
    max_client_samples: int = 1 << 20,
) -> Aggregator:
    etamu = client_lr * mu
    max_tau = epochs * max(1, -(-max_client_samples // batch_size))

    def init_state(global_variables):
        return ()

    def aggregate(global_variables, stacked, weights, state, rng, extras=None):
        # per-client effective local steps: the engine passes the TRUE τ_i
        # (heterogeneous straggler budgets, reference fednova.py:79-154
        # semantics) via extras, together with a static "max_tau" bound for
        # the normalizer recursion; fall back to deriving from sample counts
        if extras is not None and "tau" in extras:
            tau = extras["tau"]
            mt = int(extras.get("max_tau", max_tau))
        else:
            tau = epochs * jnp.ceil(jnp.maximum(weights, 1.0) / batch_size)
            mt = max_tau
        # keep τ and a consistent even if the bound is misconfigured: a
        # truncated recursion with un-truncated τ would silently inflate coeff
        tau = jnp.minimum(tau, float(mt))
        a = normalizing_vector(tau, momentum, etamu, mt)  # [C]
        p = weights / jnp.maximum(jnp.sum(weights), 1e-12)  # [C]
        tau_eff = jnp.sum(p * (tau if mu != 0.0 else a))

        gp = global_variables["params"]
        coeff = tau_eff * p / jnp.maximum(a, 1e-12)  # [C]

        def _combine(g_leaf, s_leaf):
            delta = g_leaf[None] - s_leaf  # [C, ...] cum_grad
            cb = coeff.reshape((-1,) + (1,) * (delta.ndim - 1))
            return g_leaf - jnp.sum(cb * delta, axis=0)

        new_params = jax.tree.map(_combine, gp, stacked["params"])
        # aux collections (BN stats): plain weighted average
        aux = {k: v for k, v in stacked.items() if k != "params"}
        new_aux = treelib.tree_weighted_mean(aux, weights) if aux else {}
        return {"params": new_params, **new_aux}, state, {"tau_eff": tau_eff}

    return Aggregator(init_state, aggregate, name="fednova")
