"""Federated semantic segmentation (fedseg).

Reference: fedml_api/distributed/fedseg/ — per-client mIoU / FWIoU /
pixel-accuracy evaluation via a confusion-matrix ``Evaluator``
(fedseg/utils.py, MyModelTrainer.py:92-125), an aggregator that tracks
per-client eval dicts plus global averages (FedSegAggregator.py:105-235), and
an ``EvaluationMetricsKeeper`` record per client.

TPU design: training is ordinary FedAvg over a segmentation ClientTrainer
(task="segmentation" — per-pixel CE inside the same vmapped scan). The
evaluator becomes pure array math: each client's confusion matrix accumulates
inside the jitted eval (one [C, C] scatter-add per batch), the cohort's
matrices come back stacked ``[num_clients, C, C]``, and every reference metric
is a closed-form reduction of that stack — the reference's serial per-client
Python eval loop is one vmapped program.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.sim.engine import FedSim


# ---------------------------------------------------------------------------
# Metrics from confusion matrices (reference fedseg/utils.py Evaluator)
# ---------------------------------------------------------------------------


def pixel_accuracy(conf: jnp.ndarray) -> jnp.ndarray:
    return jnp.trace(conf) / jnp.maximum(jnp.sum(conf), 1.0)


def pixel_accuracy_class(conf: jnp.ndarray) -> jnp.ndarray:
    per_class = jnp.diag(conf) / jnp.maximum(jnp.sum(conf, axis=1), 1.0)
    present = jnp.sum(conf, axis=1) > 0
    return jnp.sum(jnp.where(present, per_class, 0.0)) / jnp.maximum(
        jnp.sum(present), 1.0
    )


def iou_per_class(conf: jnp.ndarray) -> jnp.ndarray:
    inter = jnp.diag(conf)
    union = jnp.sum(conf, axis=0) + jnp.sum(conf, axis=1) - inter
    return inter / jnp.maximum(union, 1.0)


def mean_iou(conf: jnp.ndarray) -> jnp.ndarray:
    union = jnp.sum(conf, axis=0) + jnp.sum(conf, axis=1) - jnp.diag(conf)
    present = union > 0
    iou = iou_per_class(conf)
    return jnp.sum(jnp.where(present, iou, 0.0)) / jnp.maximum(jnp.sum(present), 1.0)


def frequency_weighted_iou(conf: jnp.ndarray) -> jnp.ndarray:
    freq = jnp.sum(conf, axis=1) / jnp.maximum(jnp.sum(conf), 1.0)
    iou = iou_per_class(conf)
    return jnp.sum(jnp.where(freq > 0, freq * iou, 0.0))


@dataclasses.dataclass
class EvaluationMetricsKeeper:
    """Per-client eval record (reference fedseg/utils.py
    EvaluationMetricsKeeper — acc / acc_class / mIoU / FWIoU / loss)."""

    accuracy: float
    accuracy_class: float
    mIoU: float
    FWIoU: float
    loss: float


def metrics_from_confusion(conf: np.ndarray, loss: float = 0.0) -> EvaluationMetricsKeeper:
    c = jnp.asarray(conf)
    return EvaluationMetricsKeeper(
        accuracy=float(pixel_accuracy(c)),
        accuracy_class=float(pixel_accuracy_class(c)),
        mIoU=float(mean_iou(c)),
        FWIoU=float(frequency_weighted_iou(c)),
        loss=float(loss),
    )


# ---------------------------------------------------------------------------
# FedSeg simulation: FedAvg + vectorized per-client segmentation eval
# ---------------------------------------------------------------------------


class FedSegSim(FedSim):
    """FedAvg on a segmentation trainer + the fedseg evaluation protocol.

    ``evaluate_clients`` replaces the reference aggregator's per-client eval
    dict bookkeeping (FedSegAggregator.py:105-235): one jitted vmap returns
    every client's confusion matrix; global metrics come from the summed
    matrix (exactly the reference's global average over clients, but weighted
    by true pixel counts rather than a mean of per-client ratios).
    """

    def __init__(self, trainer: ClientTrainer, train_data, test_arrays, config,
                 aggregator=None, mesh=None):
        assert trainer.task == "segmentation", "FedSegSim requires the segmentation task"
        super().__init__(trainer, train_data, test_arrays, config,
                         aggregator=aggregator, mesh=mesh)

    def evaluate_clients(self, variables, client_ids=None, batch_size=None):
        """Returns (per-client EvaluationMetricsKeeper dict, global metrics dict)."""
        cfg = self.config
        ids = np.asarray(
            client_ids
            if client_ids is not None
            else np.arange(cfg.client_num_in_total)
        )
        m = self.evaluate_per_client(
            variables, client_ids=ids, batch_size=batch_size or cfg.eval_batch_size
        )
        confs = np.asarray(m["confusion"])  # [C_clients, num_classes, num_classes]
        losses = np.asarray(m["test_loss"]) / np.maximum(np.asarray(m["test_total"]), 1.0)
        per_client = {
            int(cid): metrics_from_confusion(confs[i], losses[i])
            for i, cid in enumerate(ids)
        }
        global_conf = confs.sum(axis=0)
        total = float(np.maximum(np.asarray(m["test_total"]).sum(), 1.0))
        global_metrics = {
            "Eval/PixelAcc": float(pixel_accuracy(jnp.asarray(global_conf))),
            "Eval/AccClass": float(pixel_accuracy_class(jnp.asarray(global_conf))),
            "Eval/mIoU": float(mean_iou(jnp.asarray(global_conf))),
            "Eval/FWIoU": float(frequency_weighted_iou(jnp.asarray(global_conf))),
            "Eval/Loss": float(np.asarray(m["test_loss"]).sum() / total),
        }
        return per_client, global_metrics
