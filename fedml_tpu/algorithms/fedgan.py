"""Federated GAN.

Reference: fedml_api/distributed/fedgan/ — clients run an adversarial train
loop on a (generator, discriminator) pair; the aggregator weighted-averages a
*dict of two networks* with a nested two-level loop
(FedGANAggregator.aggregate:58-88). Here the pair is one pytree
``{"generator": vars, "discriminator": vars}`` so the standard weighted mean
IS the nested average, and the local adversarial loop is a jitted scan vmapped
over the cohort like any other trainer.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms.base import Aggregator, fedavg_aggregator
from fedml_tpu.core import scan as scanlib

Pytree = Any


@dataclasses.dataclass(frozen=True)
class GANTrainer:
    generator: Any
    discriminator: Any
    g_opt: optax.GradientTransformation
    d_opt: optax.GradientTransformation
    latent_dim: int = 100
    epochs: int = 1

    def init(self, rng: jax.Array, sample_batch: dict) -> Pytree:
        kg, kd = jax.random.split(rng)
        z = jnp.zeros((sample_batch["x"].shape[0], self.latent_dim))
        gvars = self.generator.init({"params": kg}, z, train=False)
        dvars = self.discriminator.init({"params": kd}, sample_batch["x"], train=False)
        return {"generator": dict(gvars), "discriminator": dict(dvars)}

    def _apply(self, module, variables, x, train, rng):
        state = {k: v for k, v in variables.items() if k != "params"}
        if train and state:
            out, new_state = module.apply(variables, x, train=True, mutable=list(state.keys()),
                                          rngs={"dropout": rng})
            return out, new_state
        return module.apply(variables, x, train=train, rngs={"dropout": rng}), state

    def train_step(self, variables: Pytree, opt_states, batch: dict, rng: jax.Array):
        """Non-saturating GAN step: D on real+fake, then G (reference
        MyModelTrainer adversarial loop)."""
        kz, kd, kg = jax.random.split(rng, 3)
        real, mask = batch["x"], batch["mask"]
        B = real.shape[0]
        z = jax.random.normal(kz, (B, self.latent_dim))
        gvars, dvars = variables["generator"], variables["discriminator"]
        g_opt_state, d_opt_state = opt_states

        def bce_logits(logits, target):
            return optax.sigmoid_binary_cross_entropy(logits[:, 0], target)

        # --- discriminator step ---
        def d_loss_fn(dp):
            dv = {**dvars, "params": dp}
            fake, _ = self._apply(self.generator, gvars, z, True, kg)
            real_logit, dstate = self._apply(self.discriminator, dv, real, True, kd)
            fake_logit, _ = self._apply(self.discriminator, dv, jax.lax.stop_gradient(fake), True, kd)
            loss = bce_logits(real_logit, jnp.ones(B)) + bce_logits(fake_logit, jnp.zeros(B))
            return jnp.sum(loss * mask) / jnp.maximum(jnp.sum(mask), 1.0), dstate

        (d_loss, dstate), d_grads = jax.value_and_grad(d_loss_fn, has_aux=True)(dvars["params"])
        d_updates, d_opt_state = self.d_opt.update(d_grads, d_opt_state, dvars["params"])
        dvars = {**dvars, **dstate, "params": optax.apply_updates(dvars["params"], d_updates)}

        # --- generator step ---
        def g_loss_fn(gp):
            gv = {**gvars, "params": gp}
            fake, gstate = self._apply(self.generator, gv, z, True, kg)
            fake_logit, _ = self._apply(self.discriminator, dvars, fake, True, kd)
            loss = bce_logits(fake_logit, jnp.ones(B))
            return jnp.sum(loss * mask) / jnp.maximum(jnp.sum(mask), 1.0), gstate

        (g_loss, gstate), g_grads = jax.value_and_grad(g_loss_fn, has_aux=True)(gvars["params"])
        g_updates, g_opt_state = self.g_opt.update(g_grads, g_opt_state, gvars["params"])
        gvars = {**gvars, **gstate, "params": optax.apply_updates(gvars["params"], g_updates)}

        return ({"generator": gvars, "discriminator": dvars},
                (g_opt_state, d_opt_state), {"d_loss": d_loss, "g_loss": g_loss})


def make_gan_local_train(trainer: GANTrainer):
    """local_train(global_pair, data, rng, num_steps=None) -> (pair, metrics)
    — same contract as core.trainer.make_local_train (incl. the per-client
    step budget), so FedSim can federate GANs unchanged."""

    def local_train(global_variables: Pytree, data: dict, rng: jax.Array,
                    num_steps=None):
        opt_states = (
            trainer.g_opt.init(global_variables["generator"]["params"]),
            trainer.d_opt.init(global_variables["discriminator"]["params"]),
        )
        S = jax.tree.leaves(data)[0].shape[0]

        def epoch(carry, e):
            variables, opt_states, rng = carry

            def step(carry, xs):
                variables, opt_states, rng = carry
                s, batch = xs
                rng, sub = jax.random.split(rng)
                new_vars, new_opts, losses = trainer.train_step(
                    variables, opt_states, batch, sub
                )
                # freeze past the step budget or on fully-padded batches
                active = jnp.sum(batch["mask"]) > 0
                if num_steps is not None:
                    active = active & ((e * S + s) < num_steps)
                keep = lambda n, o: jax.tree.map(
                    lambda a, b: jnp.where(active, a, b), n, o
                )
                variables = keep(new_vars, variables)
                opt_states = keep(new_opts, opt_states)
                return (variables, opt_states, rng), losses["g_loss"] + losses["d_loss"]

            (variables, opt_states, rng), losses = scanlib.scan(
                step, (variables, opt_states, rng), (jnp.arange(S), data)
            )
            return (variables, opt_states, rng), losses.mean()

        (variables, opt_states, rng), epoch_losses = scanlib.scan(
            epoch, (global_variables, opt_states, rng), jnp.arange(trainer.epochs)
        )
        return variables, {"train_loss": epoch_losses[-1]}

    return local_train


def fedgan_aggregator() -> Aggregator:
    """The nested two-network weighted average (FedGANAggregator.aggregate:
    58-88) — identical math to fedavg over the pair pytree."""
    inner = fedavg_aggregator()
    return Aggregator(inner.init_state, inner.aggregate, name="fedgan")
