"""Classical vertical (feature-partitioned) federated learning.

Reference: fedml_api/distributed/classical_vertical_fl/ — the guest holds
labels + its feature columns, hosts hold other columns; per batch, hosts send
logit contributions, the guest sums them, computes BCE loss, and returns
per-host gradients (guest_trainer.py:73-120); standalone party models in
fedml_api/standalone/classical_vertical_fl/party_models.py:12,81
(VFLGuestModel / VFLHostModel — dense feature extractor + linear head).

TPU-native: the feature dimension is partitioned across parties — structurally
tensor parallelism. The batch-synchronous two-phase protocol is an explicit
``jax.vjp`` per party; this module is the single-program simulation path
(the whole round jits into one program). ``vertical_dist.py`` runs the same
protocol over the comm layer with the logit/gradient arrays as wire
payloads, bit-identical to this path (tests/test_comm_pipelines.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

Pytree = Any


class PartyModel(nn.Module):
    """Dense feature extractor -> scalar logit contribution (party_models.py:12)."""

    hidden: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.relu(nn.Dense(self.hidden)(x.astype(jnp.float32)))
        return nn.Dense(1)(h)[:, 0]


@dataclasses.dataclass
class VerticalFL:
    """N-party VFL: party 0 is the guest (has labels), 1..N are hosts."""

    party_modules: Sequence[Any]
    optimizer: optax.GradientTransformation

    def init(self, rng: jax.Array, feature_splits: Sequence[jnp.ndarray]):
        keys = jax.random.split(rng, len(self.party_modules))
        return [
            dict(m.init({"params": k}, x[:1], train=False))
            for m, k, x in zip(self.party_modules, keys, feature_splits)
        ]

    def train_step(self, party_vars: list[Pytree], opt_states, feature_splits,
                   y: jnp.ndarray, mask: jnp.ndarray):
        """Two-phase batch-synchronous protocol (guest_trainer.py:73-120):
        phase 1 — every party computes its logit contribution; phase 2 — the
        guest's loss gradient w.r.t. the summed logit flows back per party."""
        vjps, logits = [], []
        for m, v, x in zip(self.party_modules, party_vars, feature_splits):
            out, vjp = jax.vjp(lambda p, m=m, v=v, x=x: m.apply({**v, "params": p}, x, train=True),
                               v["params"])
            logits.append(out)
            vjps.append(vjp)
        total_logit = sum(logits)  # guest sums host contributions

        def loss_fn(z):
            bce = optax.sigmoid_binary_cross_entropy(z, y.astype(jnp.float32))
            return jnp.sum(bce * mask) / jnp.maximum(jnp.sum(mask), 1.0)

        loss, dz = jax.value_and_grad(loss_fn)(total_logit)

        new_vars, new_opts = [], []
        for v, vjp, opt_state in zip(party_vars, vjps, opt_states):
            (g,) = vjp(dz)  # per-party gradient returned by the guest
            updates, opt_state = self.optimizer.update(g, opt_state, v["params"])
            new_vars.append({**v, "params": optax.apply_updates(v["params"], updates)})
            new_opts.append(opt_state)
        return new_vars, new_opts, loss

    def predict(self, party_vars, feature_splits):
        total = sum(
            m.apply(v, x, train=False)
            for m, v, x in zip(self.party_modules, party_vars, feature_splits)
        )
        return jax.nn.sigmoid(total)


def run_vfl(
    feature_splits_train: Sequence[jnp.ndarray],
    y_train: jnp.ndarray,
    epochs: int = 5,
    batch_size: int = 32,
    lr: float = 0.05,
    hidden: int = 16,
    seed: int = 0,
):
    """Standalone VFL driver (vfl_fixture.py:27 orchestration)."""
    n = len(y_train)
    parties = [PartyModel(hidden=hidden) for _ in feature_splits_train]
    vfl = VerticalFL(parties, optax.sgd(lr))
    rng = jax.random.key(seed)
    pvars = vfl.init(rng, feature_splits_train)
    opts = [vfl.optimizer.init(v["params"]) for v in pvars]

    step = jax.jit(vfl.train_step)
    losses = []
    steps = max(1, n // batch_size)
    for _ in range(epochs):
        for s in range(steps):
            sl = slice(s * batch_size, (s + 1) * batch_size)
            fs = [x[sl] for x in feature_splits_train]
            yb = y_train[sl]
            mask = jnp.ones(yb.shape[0], jnp.float32)
            pvars, opts, loss = step(pvars, opts, fs, yb, mask)
            losses.append(float(loss))
    return vfl, pvars, losses
