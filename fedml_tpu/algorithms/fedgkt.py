"""FedGKT: Group Knowledge Transfer.

Reference: fedml_api/distributed/fedgkt/ — clients train a small feature
extractor locally (GKTClientTrainer.train:49+, returns per-batch
extracted_feature_dict/logits_dict/labels_dict), the server trains a large
model on those features with CE + temperature-scaled bidirectional KL
distillation (GKTServerTrainer.py:13, train_and_eval:193+; KL_Loss
utils.py:75-90 with temperature and alpha args), then sends its logits back
to guide the clients' next local phase.

TPU-native: feature/logit exchange is array transfer; both training phases
are jitted scans. The client-side distillation term uses the server logits
from the previous round (zeros in round 0, matching the reference warm-up).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core import scan as scanlib

Pytree = Any


def kl_loss(student_logits, teacher_logits, temperature: float):
    """T²·KL(softmax(teacher/T) || log_softmax(student/T)) (utils.py:75-90)."""
    t = temperature
    p_teacher = jax.nn.softmax(teacher_logits / t, axis=-1)
    log_p_teacher = jax.nn.log_softmax(teacher_logits / t, axis=-1)
    log_p_student = jax.nn.log_softmax(student_logits / t, axis=-1)
    return (t * t) * jnp.sum(p_teacher * (log_p_teacher - log_p_student), axis=-1)


@dataclasses.dataclass
class FedGKT:
    client_module: Any  # ResNetGKTClient
    server_module: Any  # ResNetGKTServer
    client_opt: optax.GradientTransformation
    server_opt: optax.GradientTransformation
    temperature: float = 3.0
    alpha: float = 1.0  # distillation weight

    def init(self, rng: jax.Array, sample_x: jnp.ndarray):
        k1, k2 = jax.random.split(rng)
        cvars = self.client_module.init({"params": k1}, sample_x, train=False)
        feats, _ = self.client_module.apply(cvars, sample_x, train=False)
        svars = self.server_module.init({"params": k2}, feats, train=False)
        return dict(cvars), dict(svars)

    # ---- client phase: local CE + KL against server logits ----------------

    def client_train(self, cvars: Pytree, batches: dict, server_logits: jnp.ndarray,
                     epochs: int, rng: jax.Array):
        """batches: [S, B, ...] stack; server_logits: [S, B, C] from last round.
        Returns (new cvars, features [S,B,H,W,F], client logits [S,B,C])."""
        opt_state = self.client_opt.init(cvars["params"])
        model_state = {k: v for k, v in cvars.items() if k != "params"}

        def loss_fn(params, state, batch, s_logits):
            out = self.client_module.apply(
                {"params": params, **state}, batch["x"], train=True,
                mutable=list(state.keys()),
            )
            (feats, logits), new_state = out
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, batch["y"])
            kl = kl_loss(logits, s_logits, self.temperature)
            m = batch["mask"]
            loss = jnp.sum((ce + self.alpha * kl) * m) / jnp.maximum(jnp.sum(m), 1.0)
            return loss, new_state

        def epoch(carry, _):
            params, state, opt_state = carry

            def step(carry, inp):
                params, state, opt_state = carry
                batch, s_logits = inp
                (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, state, batch, s_logits
                )
                updates, opt_state = self.client_opt.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), new_state, opt_state), loss

            (params, state, opt_state), losses = scanlib.scan(
                step, (params, state, opt_state), (batches, server_logits)
            )
            return (params, state, opt_state), losses.mean()

        (params, state, opt_state), _ = scanlib.scan(
            epoch, (cvars["params"], model_state, opt_state), None, length=epochs
        )
        new_cvars = {"params": params, **state}

        # extraction pass (GKTClientTrainer.train returns feature/logit dicts)
        def extract(batch):
            feats, logits = self.client_module.apply(new_cvars, batch["x"], train=False)
            return feats, logits

        feats, logits = jax.vmap(extract)(batches)
        return new_cvars, feats, logits

    # ---- server phase: train on uploaded features -------------------------

    def server_train(self, svars: Pytree, feats, client_logits, labels, masks,
                     epochs: int):
        """feats/client_logits/labels/masks: stacked [N_batches, B, ...] from
        all clients (GKTServerTrainer.train_and_eval). Returns (new svars,
        per-batch server logits for the feedback path)."""
        opt_state = self.server_opt.init(svars["params"])
        model_state = {k: v for k, v in svars.items() if k != "params"}

        def loss_fn(params, state, f, cl, y, m):
            out = self.server_module.apply(
                {"params": params, **state}, f, train=True, mutable=list(state.keys())
            )
            logits, new_state = out
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            kl = kl_loss(logits, cl, self.temperature)
            loss = jnp.sum((ce + self.alpha * kl) * m) / jnp.maximum(jnp.sum(m), 1.0)
            return loss, new_state

        def epoch(carry, _):
            params, state, opt_state = carry

            def step(carry, inp):
                params, state, opt_state = carry
                f, cl, y, m = inp
                (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, state, f, cl, y, m
                )
                updates, opt_state = self.server_opt.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), new_state, opt_state), loss

            (params, state, opt_state), losses = scanlib.scan(
                step, (params, state, opt_state), (feats, client_logits, labels, masks)
            )
            return (params, state, opt_state), losses.mean()

        (params, state, opt_state), _ = scanlib.scan(
            epoch, (svars["params"], model_state, opt_state), None, length=epochs
        )
        new_svars = {"params": params, **state}

        def feedback(f):
            return self.server_module.apply(new_svars, f, train=False)

        server_logits = jax.vmap(feedback)(feats)
        return new_svars, server_logits


def run_fedgkt(
    gkt: FedGKT,
    client_batches: list[dict],
    rounds: int,
    client_epochs: int,
    server_epochs: int,
    rng: jax.Array,
):
    """In-process GKT orchestration (GKTServerManager round loop role):
    every client trains locally against last round's server logits (zeros in
    round 0), the server trains on the concatenated feature/logit/label
    stacks in client order, and its per-batch logits flow back split per
    client. ``client_batches[i]`` is client i's [S, B, ...] stack.

    Also the numerics oracle for the comm-layer path (fedgkt_dist.py): the
    distributed run calls the SAME two jitted phase programs with the same
    key schedule, so it is bit-identical to this loop."""
    import numpy as np

    sample_x = client_batches[0]["x"][0]
    cvars0, svars = gkt.init(rng, sample_x)
    cvars = [jax.tree.map(jnp.copy, cvars0) for _ in client_batches]
    _, logits0 = gkt.client_module.apply(cvars0, sample_x, train=False)
    n_classes = logits0.shape[-1]
    server_logits = [
        jnp.zeros(tuple(np.shape(b["y"])) + (n_classes,)) for b in client_batches
    ]
    client_train = jax.jit(gkt.client_train, static_argnums=3)
    server_train = jax.jit(gkt.server_train, static_argnums=5)

    for _ in range(rounds):
        feats_l, clog_l = [], []
        for ci, batches in enumerate(client_batches):
            rng, sub = jax.random.split(rng)
            cvars[ci], f, cl = client_train(
                cvars[ci], batches, server_logits[ci], client_epochs, sub
            )
            feats_l.append(f)
            clog_l.append(cl)
        feats = jnp.concatenate(feats_l, 0)
        clog = jnp.concatenate(clog_l, 0)
        ys = jnp.concatenate([b["y"] for b in client_batches], 0)
        ms = jnp.concatenate([b["mask"] for b in client_batches], 0)
        svars, slog = server_train(svars, feats, clog, ys, ms, server_epochs)
        off = 0
        for ci, b in enumerate(client_batches):
            s = int(np.shape(b["y"])[0])
            server_logits[ci] = slog[off:off + s]
            off += s
    return cvars, svars, server_logits
