"""Server-side aggregator protocol.

Reference shape: each algorithm package has an ``<Algo>Aggregator`` class
holding mutable server state and an ``aggregate()`` method looping over
client state_dicts key by key (e.g. fedml_api/distributed/fedavg/
FedAVGAggregator.py:59-88). Here an aggregator is a pair of pure functions
over *stacked* client pytrees (leading client axis) — aggregation is one
weighted reduction XLA lowers to a psum over the mesh's client axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from fedml_tpu.core import tree as treelib

Pytree = Any


@dataclasses.dataclass(frozen=True)
class Aggregator:
    """``init_state(global_variables) -> state`` and
    ``aggregate(global, stacked_locals, weights, state, rng, extras=None)
    -> (new_global, new_state, metrics)``.

    ``stacked_locals`` leaves have shape [C, ...]; ``weights`` is [C]
    (per-client sample counts — the reference's weighting scheme).
    ``extras`` is an optional dict of additional per-client arrays the engine
    supplies — currently ``tau`` [C], the true local SGD step counts
    (heterogeneous under the straggler protocol), consumed by FedNova.
    """

    init_state: Callable[[Pytree], Any]
    aggregate: Callable[..., tuple[Pytree, Any, dict]]
    name: str = "aggregator"


def fedavg_aggregator() -> Aggregator:
    """Sample-count-weighted averaging (FedAVGAggregator.py:59-88)."""

    def init_state(global_variables):
        return ()

    def aggregate(global_variables, stacked, weights, state, rng, extras=None):
        new_global = treelib.tree_weighted_mean(stacked, weights)
        return new_global, state, {}

    return Aggregator(init_state, aggregate, name="fedavg")
