"""Server-side aggregator protocol.

Reference shape: each algorithm package has an ``<Algo>Aggregator`` class
holding mutable server state and an ``aggregate()`` method looping over
client state_dicts key by key (e.g. fedml_api/distributed/fedavg/
FedAVGAggregator.py:59-88). Here an aggregator is a pair of pure functions
over *stacked* client pytrees (leading client axis) — aggregation is one
weighted reduction XLA lowers to a psum over the mesh's client axis.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from fedml_tpu.core import tree as treelib

Pytree = Any


class EmptyRoundError(RuntimeError):
    """A round closed (or staged) with NOTHING to aggregate.

    Wire path (fedavg_distributed): ``aggregate()`` was asked to close a
    round with ZERO uploads — every worker (stragglers included) was
    dropped by the elastic round timeout. The server keeps the previous
    global model in that case (``_round_timed_out`` re-arms instead of
    closing); calling aggregate directly on an empty tally is a protocol
    bug, reported loudly instead of the legacy ``IndexError``/NaN.

    Sim engine: a population's availability churn left the round's cohort
    empty (or every sampled member dropped mid-round) — raised at staging
    with the round named, mirroring the wire path's semantics instead of
    surfacing as a downstream shape/NaN error. Defined here (the light
    shared layer) so both paths raise ONE class."""


@dataclasses.dataclass(frozen=True)
class Aggregator:
    """``init_state(global_variables) -> state`` and
    ``aggregate(global, stacked_locals, weights, state, rng, extras=None)
    -> (new_global, new_state, metrics)``.

    ``stacked_locals`` leaves have shape [C, ...]; ``weights`` is [C]
    (per-client sample counts — the reference's weighting scheme).
    ``extras`` is an optional dict of additional per-client arrays the engine
    supplies — currently ``tau`` [C], the true local SGD step counts
    (heterogeneous under the straggler protocol), consumed by FedNova, and
    the static ``max_tau`` loop bound.

    ``per_client=True`` switches the engine to per-client persistent models
    (decentralized/gossip FL): the first aggregate argument and return value
    are then *stacked* [C, ...] pytrees — each client trains from its own
    round-(r-1) model, and aggregation maps the trained stack to next round's
    per-client stack (e.g. a mixing-matrix multiply). The reference analogue
    is each DecentralizedWorker holding its own model across rounds
    (decentralized_framework/decentralized_worker.py:4).
    """

    init_state: Callable[[Pytree], Any]
    aggregate: Callable[..., tuple[Pytree, Any, dict]]
    name: str = "aggregator"
    per_client: bool = False
    # per_client only: number of real clients the rule is configured for
    # (e.g. the mixing matrix's order) — the engine validates it against
    # client_num_in_total so a misconfigured topology fails loudly instead of
    # silently isolating the overflow clients behind identity rows
    num_clients: int | None = None
    # per_client only: gather the previous round's full model stack as the
    # first aggregate argument (costs an all_gather; rules like gossip that
    # only consume the trained stack leave this off and receive the local
    # shard's slice instead)
    needs_prev_stack: bool = False


def fedavg_aggregator() -> Aggregator:
    """Sample-count-weighted averaging (FedAVGAggregator.py:59-88)."""

    def init_state(global_variables):
        return ()

    def aggregate(global_variables, stacked, weights, state, rng, extras=None):
        new_global = treelib.tree_weighted_mean(stacked, weights)
        return new_global, state, {}

    return Aggregator(init_state, aggregate, name="fedavg")
