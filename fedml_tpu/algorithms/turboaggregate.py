"""Secure aggregation: finite-field MPC primitives (TurboAggregate).

Reference: fedml_api/distributed/turboaggregate/mpc_function.py (275 LoC of
field math): modular inverse (:62), Lagrange coefficients, BGW secret-sharing
encode/decode (:62-110), Lagrange Coded Computing encode/decode (:111-262),
additive secret shares (:214), DH-style key agreement (:263-275).

The math is integer/finite-field — implemented here with int64 numpy (the
field prime fits 32 bits, products fit 64) plus vectorized polynomial
evaluation. These run host-side: secure aggregation is a *protocol* between
distrusting parties, so it lives in the comm layer, not inside a jit program.
A quantize/dequantize pair maps float model deltas into the field.
"""

from __future__ import annotations

import numpy as np

DEFAULT_PRIME = 2**31 - 1  # Mersenne prime; products fit in int64


def modular_inverse(a: int | np.ndarray, p: int = DEFAULT_PRIME):
    """a^(p-2) mod p by fast exponentiation (Fermat; reference divmod:62)."""
    a = np.asarray(a, dtype=np.int64) % p
    result = np.ones_like(a)
    exp = p - 2
    base = a.copy()
    while exp:
        if exp & 1:
            result = (result * base) % p
        base = (base * base) % p
        exp >>= 1
    return result


def _poly_eval(coeffs: np.ndarray, xs: np.ndarray, p: int) -> np.ndarray:
    """Horner evaluation of D polynomials at each x. coeffs [T, D], xs [N]
    -> [N, D], all mod p."""
    out = np.zeros((len(xs), coeffs.shape[1]), dtype=np.int64)
    for c in coeffs[::-1]:
        out = (out * xs[:, None] + c[None, :]) % p
    return out


def bgw_encode(secret: np.ndarray, n_shares: int, threshold: int,
               p: int = DEFAULT_PRIME, seed: int | None = None) -> np.ndarray:
    """Shamir/BGW secret sharing: secret [D] ints -> shares [N, D]
    (mpc_function.py BGW_encoding). Any threshold+1 shares reconstruct."""
    rng = np.random.RandomState(seed)
    secret = np.asarray(secret, dtype=np.int64).reshape(1, -1) % p
    coeffs = np.concatenate(
        [secret, rng.randint(0, p, (threshold, secret.shape[1])).astype(np.int64)]
    )
    xs = np.arange(1, n_shares + 1, dtype=np.int64)
    return _poly_eval(coeffs, xs, p)


def lagrange_coefficients(eval_points: np.ndarray, target: int = 0,
                          p: int = DEFAULT_PRIME) -> np.ndarray:
    """ℓ_i(target) for interpolation through eval_points (gen_Lagrange_coeffs)."""
    pts = np.asarray(eval_points, dtype=np.int64) % p
    coeffs = np.ones(len(pts), dtype=np.int64)
    for i in range(len(pts)):
        num, den = 1, 1
        for j in range(len(pts)):
            if i == j:
                continue
            num = (num * ((target - pts[j]) % p)) % p
            den = (den * ((pts[i] - pts[j]) % p)) % p
        coeffs[i] = (num * int(modular_inverse(den, p))) % p
    return coeffs


def bgw_decode(shares: np.ndarray, share_idx: np.ndarray, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Reconstruct secret from shares [K, D] held at x = share_idx+1
    (BGW_decoding)."""
    xs = np.asarray(share_idx, dtype=np.int64) + 1
    lam = lagrange_coefficients(xs, 0, p)
    # reduce each product mod p before summing: lam_i * s_i < p^2 fits int64,
    # but a sum of >= 3 unreduced products overflows and wraps silently
    return (lam[:, None] * (np.asarray(shares, np.int64) % p) % p).sum(axis=0) % p


def lcc_encode(data: np.ndarray, n_workers: int, k_batches: int, t_privacy: int = 0,
               p: int = DEFAULT_PRIME, seed: int | None = None) -> np.ndarray:
    """Lagrange Coded Computing encode (LCC_encoding_w_Random):
    data [K, D] batches -> coded shares [N, D] along the polynomial through
    interpolation points 1..K(+T noise points), evaluated at K+T+1..K+T+N."""
    rng = np.random.RandomState(seed)
    data = np.asarray(data, dtype=np.int64) % p
    K, D = data.shape
    if t_privacy:
        noise = rng.randint(0, p, (t_privacy, D)).astype(np.int64)
        data = np.concatenate([data, noise])
    alpha = np.arange(1, K + t_privacy + 1, dtype=np.int64)  # interpolation pts
    beta = np.arange(K + t_privacy + 1, K + t_privacy + 1 + n_workers, dtype=np.int64)
    shares = np.zeros((n_workers, D), dtype=np.int64)
    for w, b in enumerate(beta):
        lam = lagrange_coefficients(alpha, int(b), p)
        shares[w] = (lam[:, None] * data % p).sum(axis=0) % p
    return shares


def lcc_decode(shares: np.ndarray, worker_idx: np.ndarray, k_batches: int,
               t_privacy: int = 0, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Recover the K data batches from >= K+T shares (LCC_decoding)."""
    beta = np.asarray(worker_idx, dtype=np.int64) + k_batches + t_privacy + 1
    out = np.zeros((k_batches, shares.shape[1]), dtype=np.int64)
    for target in range(1, k_batches + 1):
        lam = lagrange_coefficients(beta, target, p)
        out[target - 1] = (
            lam[:, None] * (np.asarray(shares, np.int64) % p) % p
        ).sum(axis=0) % p
    return out


def additive_shares(secret: np.ndarray, n: int, p: int = DEFAULT_PRIME,
                    seed: int | None = None) -> np.ndarray:
    """n additive shares summing to secret mod p (my_pk_gen / :214)."""
    rng = np.random.RandomState(seed)
    secret = np.asarray(secret, dtype=np.int64) % p
    shares = rng.randint(0, p, (n - 1,) + secret.shape).astype(np.int64)
    last = (secret - shares.sum(axis=0)) % p
    return np.concatenate([shares, last[None]])


def dh_keygen(generator: int, private: int, p: int = DEFAULT_PRIME) -> int:
    """Public key g^sk mod p (mpc_function.py:263-275)."""
    return pow(generator, private, p)


def dh_shared(peer_public: int, private: int, p: int = DEFAULT_PRIME) -> int:
    return pow(peer_public, private, p)


# --- float <-> field bridging for model aggregation -------------------------


def quantize(x: np.ndarray, scale: float = 2**16, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Map floats to field elements (two's-complement style around p)."""
    q = np.round(np.asarray(x, np.float64) * scale).astype(np.int64)
    return q % p


def dequantize(q: np.ndarray, scale: float = 2**16, p: int = DEFAULT_PRIME) -> np.ndarray:
    q = np.asarray(q, np.int64) % p
    signed = np.where(q > p // 2, q - p, q)
    return signed.astype(np.float64) / scale


def secure_sum(client_vectors: list[np.ndarray], threshold: int | None = None,
               p: int = DEFAULT_PRIME, seed: int = 0) -> np.ndarray:
    """End-to-end secure aggregation demo: each client BGW-shares its
    quantized vector; servers sum shares pointwise; the sum polynomial is
    decoded from threshold+1 share-sums. Returns the float sum."""
    n = len(client_vectors)
    threshold = threshold if threshold is not None else max(1, (n - 1) // 2)
    share_sum = None
    for i, vec in enumerate(client_vectors):
        shares = bgw_encode(quantize(vec, p=p), n, threshold, p, seed=seed + i)
        share_sum = shares if share_sum is None else (share_sum + shares) % p
    idx = np.arange(threshold + 1)
    summed = bgw_decode(share_sum[idx], idx, p)
    return dequantize(summed, p=p)
