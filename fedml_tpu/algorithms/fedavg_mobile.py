"""`is_mobile` federated rounds: phone-side clients speak the reference's
nested-list JSON wire format.

Reference: fedml_api/distributed/fedavg/ — with ``args.is_mobile == 1`` the
server transforms every outgoing model through ``transform_tensor_to_list``
and every incoming one through ``transform_list_to_tensor``
(FedAvgServerManager.py:36,77; FedAVGAggregator.py:65), so an Android/iOS
runtime holding "a dict of parameter-name -> nested float lists" can join
rounds without torch on the device. Here the same contract rides this
framework's typed message layer: for ranks declared mobile, the model
payload is a JSON string of :func:`params_to_nested_lists` (models/
export.py — byte-exact float32 round-trip through JSON), and everything
else about the protocol (message types, elastic rounds, staleness checks,
status tracking) is inherited unchanged from fedavg_distributed.

``MobileFedAvgClientManager`` stands in for the phone: it consumes ONLY the
JSON wire dict (never the packed byte vector), trains, and uploads JSON.
``tests/test_comm.py::test_mobile_wire_clients_match_native`` proves a
mixed native+mobile federation reproduces the all-native result exactly.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

import jax

from fedml_tpu.algorithms.fedavg_distributed import (
    FedAvgClientManager,
    FedAvgServerManager,
    MyMessage,
    run_distributed_fedavg,
)
from fedml_tpu.comm.message import Message, pack_pytree, unpack_pytree
from fedml_tpu.models.export import (
    nested_lists_to_params,
    params_to_nested_lists,
)


def variables_to_wire(variables) -> str:
    """Reference ``transform_tensor_to_list`` over the full variables
    pytree, as a JSON string (the mobile app's message body)."""
    return json.dumps(params_to_nested_lists(variables))


def wire_to_variables(payload: str, template):
    """Reference ``transform_list_to_tensor``: JSON wire dict back to
    variables shaped like ``template``."""
    return nested_lists_to_params(json.loads(payload), template)


class MobileFedAvgServerManager(FedAvgServerManager):
    """FedAvg server that speaks nested-list JSON to its ``mobile_ranks``
    and the packed byte vector to everyone else (the reference's
    ``is_mobile`` branches, FedAvgServerManager.py:36,77)."""

    def __init__(self, *args, mobile_ranks=(), **kwargs):
        super().__init__(*args, **kwargs)
        self.mobile_ranks = set(mobile_ranks)
        self._wire_cache: tuple[Any, str] | None = None

    def _current_variables(self):
        return unpack_pytree(np.asarray(self.global_flat), self.model_desc)

    def _model_payload(self, rank: int):
        if rank not in self.mobile_ranks:
            return super()._model_payload(rank)
        # encode once per global model, not once per mobile rank: the JSON
        # text of a full model is megabytes; M ranks share one encoding
        cached = self._wire_cache
        if cached is not None and cached[0] is self.global_flat:
            return cached[1]
        payload = variables_to_wire(self._current_variables())
        self._wire_cache = (self.global_flat, payload)
        return payload

    def _decode_upload(self, msg: Message) -> np.ndarray:
        if msg.get_sender_id() in self.mobile_ranks:
            # the shape template is derivable from the current global —
            # no separate (driftable) template state needed
            variables = wire_to_variables(
                msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS),
                self._current_variables(),
            )
            flat, _ = pack_pytree(jax.tree.map(np.asarray, variables))
            return flat
        return super()._decode_upload(msg)


class MobileFedAvgClientManager(FedAvgClientManager):
    """The phone-side participant: model state crosses the wire ONLY as the
    reference's JSON dict; local training here stands in for the on-device
    runtime (the wire contract is the interop surface)."""

    def _decode_model(self, msg: Message):
        return wire_to_variables(
            msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS), self.template
        )

    def _encode_model(self, new_vars) -> str:
        return variables_to_wire(jax.tree.map(np.asarray, new_vars))


def mobile_runner_kwargs(mobile_ranks) -> dict:
    """The manager wiring that makes ``run_distributed_fedavg`` (or any of
    its per-backend wrappers) speak JSON to ``mobile_ranks`` — one
    definition shared by :func:`run_distributed_fedavg_mobile` and the
    ``--is_mobile`` CLI path."""
    mobile = set(mobile_ranks)
    return {
        "server_cls": MobileFedAvgServerManager,
        "server_kwargs": {"mobile_ranks": mobile},
        "client_cls_for_rank": lambda r: (
            MobileFedAvgClientManager if r in mobile else FedAvgClientManager
        ),
    }


def run_distributed_fedavg_mobile(*args, mobile_ranks=(), **kwargs):
    """:func:`run_distributed_fedavg` with ``mobile_ranks`` speaking the
    JSON wire format — all base-runner features (elastic ``round_timeout``,
    ``init_overrides`` warm-start, ...) pass through."""
    return run_distributed_fedavg(
        *args, **mobile_runner_kwargs(mobile_ranks), **kwargs
    )
