"""FedProx: proximal local objective for heterogeneous clients.

Reference capability note: the reference's *distributed* fedprox package is a
verbatim FedAvg copy whose MyModelTrainer has NO μ term (fedml_api/distributed/
fedprox/MyModelTrainer.py:19-49 — SURVEY §2.2); the real proximal math lives
in its standalone fednova optimizer (fednova.py:48 mu support). Here FedProx
is actually implemented: the client loss gains μ/2·||w − w_global||²
(core/trainer.py ClientTrainer.prox_mu), and this module provides the named
algorithm wrapper plus straggler simulation — heterogeneous local epoch
counts, the scenario FedProx was designed for (absent from the reference,
SURVEY §5.3).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from fedml_tpu.algorithms.base import Aggregator, fedavg_aggregator
from fedml_tpu.core.trainer import ClientTrainer


def fedprox_trainer(trainer: ClientTrainer, mu: float) -> ClientTrainer:
    """Attach the proximal term to any ClientTrainer."""
    return dataclasses.replace(trainer, prox_mu=mu)


def fedprox_aggregator() -> Aggregator:
    """Server side is plain weighted averaging (FedProx paper)."""
    inner = fedavg_aggregator()
    return Aggregator(inner.init_state, inner.aggregate, name="fedprox")


def straggler_epochs(
    round_idx: int, cohort_size: int, epochs: int, straggler_frac: float, seed: int = 0
) -> np.ndarray:
    """Per-client local-epoch counts with a straggler fraction doing strictly
    fewer epochs (uniform 1..E-1), the FedProx heterogeneity protocol."""
    rng = np.random.RandomState(seed * 77_003 + round_idx)
    out = np.full(cohort_size, epochs, dtype=np.int32)
    stragglers = rng.rand(cohort_size) < straggler_frac
    out[stragglers] = rng.randint(1, max(epochs, 2), size=int(stragglers.sum()))
    return out
