"""Classical vertical FL over the message-passing comm layer.

Reference: fedml_api/distributed/classical_vertical_fl/ — guest_manager.py:6 /
host_manager.py:6 run the two roles as separate processes; per batch, hosts
send their logit contributions to the guest, the guest sums them, computes
BCE loss, and returns the logit gradient to every host
(guest_trainer.py:73-120, host_trainer.py:37-60). This module is that real
two-program path: the guest (rank 0, holds labels + its feature columns) and
hosts (ranks 1..N-1, each holding its own columns) exchange logit/gradient
arrays as typed wire payloads — raw features never leave a party.

Numerics contract: per-batch compute is factored into per-party jitted
forward/backward programs plus the guest's loss-grad program
(``make_vfl_steps``), used identically by the wire path and the in-process
stepwise oracle ``run_vfl_stepwise``; tests assert the loopback run is
bit-identical to the oracle and the oracle matches the single-program
``run_vfl`` (tests/test_comm_pipelines.py).

Protocol (handlers never block): guest announces a step, hosts answer with
logits, guest answers with the shared logit gradient; both sides apply their
local update and the guest announces the next step. Batch slicing is a
deterministic schedule both sides compute locally — only step indices,
logits, and gradients cross the wire.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms.vertical import VerticalFL
from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message, pack_pytree, unpack_pytree

Pytree = Any


class VFLMsg:
    MSG_TYPE_G2H_INIT = 1
    MSG_TYPE_G2H_STEP = 2
    MSG_TYPE_H2G_LOGITS = 3
    MSG_TYPE_G2H_GRAD = 4
    MSG_TYPE_G2H_FINISHED = 5
    MSG_TYPE_H2G_FINAL_VARS = 6

    KEY_MODEL = Message.MSG_ARG_KEY_MODEL_PARAMS
    KEY_DESC = Message.MSG_ARG_KEY_MODEL_DESC
    KEY_STEP = "step"
    KEY_LOGITS = "logits"
    KEY_GRAD = "logit_grad"


def make_vfl_steps(vfl: VerticalFL):
    """Per-party jitted forward/backward + the guest's loss-grad program.
    ``party_backward`` recomputes the forward inside ``jax.vjp`` (vjp
    residuals never cross the wire — same recompute contract as
    splitnn_dist)."""
    forwards, backwards = [], []
    for m in vfl.party_modules:
        def forward(v, x, m=m):
            def f(p):
                return m.apply({**v, "params": p}, x, train=True)

            return f(v["params"])

        def backward(v, opt_state, x, dz, m=m):
            def f(p):
                return m.apply({**v, "params": p}, x, train=True)

            _, vjp = jax.vjp(f, v["params"])
            (g,) = vjp(dz)  # the guest-returned gradient (host_trainer.py:49)
            updates, opt_state = vfl.optimizer.update(g, opt_state, v["params"])
            return {**v, "params": optax.apply_updates(v["params"], updates)}, opt_state

        forwards.append(jax.jit(forward))
        backwards.append(jax.jit(backward))

    @jax.jit
    def guest_grad(total_logit, y, mask):
        # guest_trainer.py:95-110 — BCE on the summed logit, grad w.r.t. it
        def loss_fn(z):
            bce = optax.sigmoid_binary_cross_entropy(z, y.astype(jnp.float32))
            return jnp.sum(bce * mask) / jnp.maximum(jnp.sum(mask), 1.0)

        return jax.value_and_grad(loss_fn)(total_logit)

    return forwards, backwards, guest_grad


def _step_schedule(n: int, batch_size: int, epochs: int):
    """The deterministic batch schedule every party derives locally
    (run_vfl's slicing: ``steps`` contiguous slices per epoch)."""
    steps = max(1, n // batch_size)
    return [
        slice(s * batch_size, (s + 1) * batch_size)
        for _ in range(epochs)
        for s in range(steps)
    ]


class VFLGuestManager(ServerManager):
    """Rank 0: labels + own columns; orchestrates the two-phase protocol."""

    def __init__(self, comm: BaseCommunicationManager, vfl: VerticalFL,
                 pvars: list[Pytree], features: jnp.ndarray, y: jnp.ndarray,
                 batch_size: int, epochs: int):
        n_hosts = len(vfl.party_modules) - 1
        super().__init__(comm, rank=0, size=n_hosts + 1)
        self.vfl = vfl
        self.n_hosts = n_hosts
        forwards, backwards, self.guest_grad = make_vfl_steps(vfl)
        self.forward, self.backward = forwards[0], backwards[0]
        self.pvars0 = pvars  # full init list; hosts get theirs in INIT
        self.gvars = pvars[0]
        self.g_opt_state = vfl.optimizer.init(self.gvars["params"])
        self.features = features
        self.y = y
        # send_init_msg unconditionally announces step 0, so an empty
        # schedule would IndexError — reject it up front (same contract as
        # repro_ceilings.centralized_ceiling)
        if epochs < 1:
            raise ValueError(f"vertical FL needs epochs >= 1, got {epochs}")
        self.schedule = _step_schedule(len(y), batch_size, epochs)
        self.step = 0
        self._step_logits: dict[int, jnp.ndarray] = {}
        self._host_acked: dict[int, int] = {}  # last step accepted per host
        self._my_logit: jnp.ndarray | None = None
        self.losses: list[float] = []
        self.final_pvars: dict[int, Pytree] = {}
        self._descs: dict[int, str] = {}

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            VFLMsg.MSG_TYPE_H2G_LOGITS, self._on_logits
        )
        self.register_message_receive_handler(
            VFLMsg.MSG_TYPE_H2G_FINAL_VARS, self._on_final_vars
        )

    def send_init_msg(self) -> None:
        for h in range(1, self.n_hosts + 1):
            flat, desc = pack_pytree(jax.tree.map(np.asarray, self.pvars0[h]))
            self._descs[h] = desc
            msg = Message(VFLMsg.MSG_TYPE_G2H_INIT, 0, h)
            msg.add_params(VFLMsg.KEY_MODEL, flat)
            msg.add_params(VFLMsg.KEY_DESC, desc)
            self.send_message(msg)
        self._announce_step()

    def _announce_step(self) -> None:
        for h in range(1, self.n_hosts + 1):
            msg = Message(VFLMsg.MSG_TYPE_G2H_STEP, 0, h)
            msg.add_params(VFLMsg.KEY_STEP, self.step)
            self.send_message(msg)
        sl = self.schedule[self.step]
        self._my_logit = self.forward(self.gvars, self.features[sl])
        self._maybe_complete_step()

    def _on_logits(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        if int(msg.get(VFLMsg.KEY_STEP)) != self.step:
            # stale (cannot happen on FIFO transports; guards WAN reorder).
            # Silently dropping it would deadlock: the host thinks it
            # answered and is never re-asked. Re-announce the CURRENT step
            # to that host so it recomputes (TurboAggregate's
            # resend-on-mismatch pattern); recomputing from current vars is
            # idempotent — the guest overwrites, never double-counts. But a
            # stale message stamped at or below the sender's last ACCEPTED
            # step is a late duplicate of an answer already consumed (the
            # tail a resend itself produces when its extra reply lands after
            # the step advanced) — resending on those would echo one
            # duplicate into an extra (resend, late-reply) pair every step
            # to schedule end, so those are dropped.
            if (self.step < len(self.schedule)
                    and sender not in self._step_logits
                    and int(msg.get(VFLMsg.KEY_STEP))
                    > self._host_acked.get(sender, -1)):
                resend = Message(VFLMsg.MSG_TYPE_G2H_STEP, 0, sender)
                resend.add_params(VFLMsg.KEY_STEP, self.step)
                self.send_message(resend)
            return
        self._host_acked[sender] = self.step
        self._step_logits[sender] = jnp.asarray(
            msg.get(VFLMsg.KEY_LOGITS)
        )
        self._maybe_complete_step()

    def _maybe_complete_step(self) -> None:
        if self._my_logit is None or len(self._step_logits) < self.n_hosts:
            return
        # guest sums contributions in party order (vertical.py train_step:
        # ``sum(logits)`` over parties 0..N-1)
        logits = [self._my_logit] + [
            self._step_logits[h] for h in range(1, self.n_hosts + 1)
        ]
        total = sum(logits)
        sl = self.schedule[self.step]
        y = self.y[sl]
        mask = jnp.ones(y.shape[0], jnp.float32)
        loss, dz = self.guest_grad(total, y, mask)
        self.losses.append(float(loss))
        for h in range(1, self.n_hosts + 1):
            out = Message(VFLMsg.MSG_TYPE_G2H_GRAD, 0, h)
            out.add_params(VFLMsg.KEY_STEP, self.step)
            out.add_params(VFLMsg.KEY_GRAD, np.asarray(dz))
            self.send_message(out)
        self.gvars, self.g_opt_state = self.backward(
            self.gvars, self.g_opt_state, self.features[sl], dz
        )
        self._step_logits = {}
        self._my_logit = None
        self.step += 1
        if self.step >= len(self.schedule):
            for h in range(1, self.n_hosts + 1):
                self.send_message(Message(VFLMsg.MSG_TYPE_G2H_FINISHED, 0, h))
        else:
            self._announce_step()

    def _on_final_vars(self, msg: Message) -> None:
        h = msg.get_sender_id()
        self.final_pvars[h] = jax.tree.map(
            jnp.asarray,
            unpack_pytree(np.asarray(msg.get(VFLMsg.KEY_MODEL)), self._descs[h]),
        )
        if len(self.final_pvars) == self.n_hosts:
            self.finish()


class VFLHostManager(ClientManager):
    """Rank h: its own feature columns; answers steps, applies grads."""

    def __init__(self, comm: BaseCommunicationManager, rank: int, size: int,
                 vfl: VerticalFL, features: jnp.ndarray,
                 batch_size: int, epochs: int):
        super().__init__(comm, rank, size)
        forwards, backwards, _ = make_vfl_steps(vfl)
        self.forward, self.backward = forwards[rank], backwards[rank]
        self.vfl = vfl
        self.features = features
        self.schedule = _step_schedule(len(features), batch_size, epochs)
        self.pvars: Pytree = None
        self.opt_state = None
        self._desc = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(VFLMsg.MSG_TYPE_G2H_INIT, self._on_init)
        self.register_message_receive_handler(VFLMsg.MSG_TYPE_G2H_STEP, self._on_step)
        self.register_message_receive_handler(VFLMsg.MSG_TYPE_G2H_GRAD, self._on_grad)
        self.register_message_receive_handler(
            VFLMsg.MSG_TYPE_G2H_FINISHED, self._on_finished
        )

    def _on_init(self, msg: Message) -> None:
        self._desc = msg.get(VFLMsg.KEY_DESC)
        self.pvars = jax.tree.map(
            jnp.asarray, unpack_pytree(np.asarray(msg.get(VFLMsg.KEY_MODEL)), self._desc)
        )
        self.opt_state = self.vfl.optimizer.init(self.pvars["params"])

    def _on_step(self, msg: Message) -> None:
        step = int(msg.get(VFLMsg.KEY_STEP))
        logit = self.forward(self.pvars, self.features[self.schedule[step]])
        out = Message(VFLMsg.MSG_TYPE_H2G_LOGITS, self.rank, 0)
        out.add_params(VFLMsg.KEY_STEP, step)
        out.add_params(VFLMsg.KEY_LOGITS, np.asarray(logit))
        self.send_message(out)

    def _on_grad(self, msg: Message) -> None:
        step = int(msg.get(VFLMsg.KEY_STEP))
        dz = jnp.asarray(msg.get(VFLMsg.KEY_GRAD))
        self.pvars, self.opt_state = self.backward(
            self.pvars, self.opt_state, self.features[self.schedule[step]], dz
        )

    def _on_finished(self, msg: Message) -> None:
        out = Message(VFLMsg.MSG_TYPE_H2G_FINAL_VARS, self.rank, 0)
        flat, _ = pack_pytree(jax.tree.map(np.asarray, self.pvars))
        out.add_params(VFLMsg.KEY_MODEL, flat)
        self.send_message(out)
        self.finish()


def run_distributed_vfl(
    vfl: VerticalFL,
    feature_splits: Sequence[jnp.ndarray],
    y: jnp.ndarray,
    epochs: int,
    batch_size: int,
    rng: jax.Array,
    make_comm: Callable[[int], BaseCommunicationManager],
):
    """VFL over any comm fabric. Returns (party vars, losses) — the same
    contract as ``run_vfl``'s (pvars, losses)."""
    from fedml_tpu.algorithms.fedavg_distributed import run_manager_protocol

    pvars = vfl.init(rng, feature_splits)
    n_parties = len(vfl.party_modules)

    guest = VFLGuestManager(
        make_comm(0), vfl, pvars, feature_splits[0], y, batch_size, epochs
    )
    hosts = [
        VFLHostManager(make_comm(h), h, n_parties, vfl, feature_splits[h],
                       batch_size, epochs)
        for h in range(1, n_parties)
    ]
    run_manager_protocol(guest, hosts)
    final = [guest.gvars] + [guest.final_pvars[h] for h in range(1, n_parties)]
    return final, guest.losses


def run_distributed_vfl_loopback(vfl, feature_splits, y, epochs, batch_size, rng):
    from fedml_tpu.comm.loopback import LoopbackCommManager, LoopbackFabric

    fabric = LoopbackFabric(len(vfl.party_modules))
    return run_distributed_vfl(
        vfl, feature_splits, y, epochs, batch_size, rng,
        lambda r: LoopbackCommManager(fabric, r),
    )


def run_vfl_stepwise(
    vfl: VerticalFL,
    feature_splits: Sequence[jnp.ndarray],
    y: jnp.ndarray,
    epochs: int,
    batch_size: int,
    rng: jax.Array,
):
    """In-process oracle: the SAME per-party jitted programs as the wire
    path, driven sequentially. Cross-checked against the single-program
    ``run_vfl`` in tests."""
    forwards, backwards, guest_grad = make_vfl_steps(vfl)
    pvars = vfl.init(rng, feature_splits)
    opts = [vfl.optimizer.init(v["params"]) for v in pvars]

    losses = []
    for sl in _step_schedule(len(y), batch_size, epochs):
        fs = [x[sl] for x in feature_splits]
        logits = [f(v, x) for f, v, x in zip(forwards, pvars, fs)]
        total = sum(logits)
        yb = y[sl]
        mask = jnp.ones(yb.shape[0], jnp.float32)
        loss, dz = guest_grad(total, yb, mask)
        losses.append(float(loss))
        for i in range(len(pvars)):
            pvars[i], opts[i] = backwards[i](pvars[i], opts[i], fs[i], dz)
    return pvars, losses
