"""TurboAggregate as a multi-party protocol over the comm layer.

Reference: fedml_api/distributed/turboaggregate/ — TA_Aggregator.py:13 wires
the MPC library (mpc_function.py) into the aggregator/trainer/manager
triple, and TA_decentralized_worker_manager.py exchanges shares between
neighbor workers (message_define.py MSG_TYPE_SEND_MSG_TO_NEIGHBOR=2). The
reference never completes the loop — its aggregate() is plain FedAvg on
plaintext models. Here the secure path actually runs:

1. Server broadcasts the global model (S2C init); clients register their
   clear-text sample counts n_i; the server broadcasts the normalized
   weights p_i = n_i / sum(n) with the round sync. Entering the field with
   p_i * delta_i (|p_i| <= 1) keeps the share-sum bounded by
   scale * max|delta| — no overflow growth with client count or samples.
2. Each client trains locally, quantizes ``p_i * (local - global)``, and
   BGW-shares it: share j goes DIRECTLY to client j (client-to-client typed
   messages; the server never routes or sees a plaintext update).
3. Each client pointwise-sums the W shares it holds (one per peer) — by
   BGW linearity a share of ``sum_i p_i * delta_i`` — and uploads only that
   share-sum.
4. The server Lagrange-reconstructs the weighted-mean delta from
   threshold+1 share-sums and applies it to the global model. Every
   share-sum already contains ALL clients' updates, so clients that die
   after the share-exchange leg but before uploading cost nothing: with
   ``round_timeout`` set, the server reconstructs the full aggregate from
   whichever >= threshold+1 share-sums arrived. (A client that dies before
   sending its peer shares stalls the round — recovering from that requires
   the full SecAgg mask-recovery protocol, out of scope here.)

Privacy: the server sees only the aggregate; a coalition of <= threshold
clients learns nothing about another client's update (Shamir). Exactness:
the aggregate equals FedAvg up to 1/quantize-scale rounding.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.turboaggregate import (
    DEFAULT_PRIME,
    bgw_decode,
    bgw_encode,
    dequantize,
    quantize,
)
from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message, pack_pytree, unpack_pytree
from fedml_tpu.core.trainer import ClientTrainer, make_local_train
from fedml_tpu.sim.cohort import FederatedArrays, stack_cohort


class TAMessage:
    """Message types (reference message_define.py:6-8, extended with the
    share-exchange legs the reference leaves unimplemented)."""

    MSG_TYPE_S2C_INIT = 1
    MSG_TYPE_S2C_SYNC = 2
    MSG_TYPE_C2S_REGISTER = 3      # clear-text sample count n_i
    MSG_TYPE_C2C_SHARE = 4         # BGW share leg: client -> client
    MSG_TYPE_C2S_SHARE_SUM = 5     # masked aggregate leg: client -> server

    KEY_MODEL = Message.MSG_ARG_KEY_MODEL_PARAMS
    KEY_DESC = "model_desc"
    KEY_NUM_SAMPLES = Message.MSG_ARG_KEY_NUM_SAMPLES
    KEY_SHARE = "bgw_share"
    KEY_ROUND = "round_idx"
    KEY_WEIGHT = "p_i"  # this client's normalized aggregation weight


def _check_threshold(threshold: int, worker_num: int) -> int:
    if not 1 <= threshold < worker_num:
        raise ValueError(
            f"privacy threshold must satisfy 1 <= t < worker_num "
            f"(got t={threshold}, workers={worker_num}): BGW needs t+1 of "
            f"the {worker_num} share points to interpolate a degree-t polynomial"
        )
    return threshold


class TAServerManager(ServerManager):
    """Receives only clear sample counts and share-sums; reconstructs only
    the aggregate."""

    def __init__(self, comm: BaseCommunicationManager, worker_num: int,
                 round_num: int, init_flat: np.ndarray, model_desc: str,
                 threshold: int | None = None, scale: float = 2**16,
                 prime: int = DEFAULT_PRIME,
                 round_timeout: float | None = None,
                 on_round_done: Callable[[int, np.ndarray], None] | None = None):
        super().__init__(comm, rank=0, size=worker_num + 1)
        self.worker_num = worker_num
        self.round_num = round_num
        self.round_idx = 0
        self.global_flat = np.asarray(init_flat)
        self.model_desc = model_desc
        self.threshold = _check_threshold(
            threshold if threshold is not None else max(1, (worker_num - 1) // 2),
            worker_num,
        )
        self.scale = scale
        self.prime = prime
        self.round_timeout = round_timeout
        self.on_round_done = on_round_done
        self._sample_nums: dict[int, float] = {}
        self._share_sums: dict[int, np.ndarray] = {}
        self._timer: threading.Timer | None = None
        self._lock = threading.Lock()

    def send_init_msg(self) -> None:
        for w in range(1, self.worker_num + 1):
            msg = Message(TAMessage.MSG_TYPE_S2C_INIT, 0, w)
            msg.add_params(TAMessage.KEY_MODEL, self.global_flat)
            msg.add_params(TAMessage.KEY_DESC, self.model_desc)
            self.send_message(msg)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            TAMessage.MSG_TYPE_C2S_REGISTER, self._on_register
        )
        self.register_message_receive_handler(
            TAMessage.MSG_TYPE_C2S_SHARE_SUM, self._on_share_sum
        )

    # -- registration: collect n_i, broadcast p_i ---------------------------

    def _on_register(self, msg: Message) -> None:
        with self._lock:
            self._sample_nums[msg.get_sender_id()] = float(
                msg.get(TAMessage.KEY_NUM_SAMPLES)
            )
            if len(self._sample_nums) < self.worker_num:
                return
        self._send_sync(finished=False)

    def _send_sync(self, finished: bool) -> None:
        total = sum(self._sample_nums.values())
        for w in range(1, self.worker_num + 1):
            sync = Message(TAMessage.MSG_TYPE_S2C_SYNC, 0, w)
            sync.add_params(TAMessage.KEY_MODEL, self.global_flat)
            sync.add_params(TAMessage.KEY_ROUND, self.round_idx)
            sync.add_params(TAMessage.KEY_WEIGHT, self._sample_nums[w] / total)
            if finished:
                sync.add_params("finished", 1)
            self.send_message(sync)

    # -- aggregation --------------------------------------------------------

    def _on_share_sum(self, msg: Message) -> None:
        with self._lock:
            if int(msg.get(TAMessage.KEY_ROUND)) != self.round_idx:
                return  # late arrival from a timed-out round
            self._share_sums[msg.get_sender_id()] = np.asarray(
                msg.get(TAMessage.KEY_SHARE)
            )
            got = len(self._share_sums)
            if got == 1 and self.round_timeout is not None:
                # every share-sum carries ALL clients' updates; after the
                # timeout any threshold+1 of them reconstruct the aggregate
                self._timed_out = False
                self._timer = threading.Timer(self.round_timeout, self._timeout)
                self._timer.daemon = True
                self._timer.start()
            if got < self.worker_num and not (
                getattr(self, "_timed_out", False) and got >= self.threshold + 1
            ):
                return
        self._close_round()

    def _timeout(self) -> None:
        self._timed_out = True
        self._close_round()

    def _close_round(self) -> None:
        with self._lock:
            if not self._share_sums:
                # benign double close (timer raced the full tally); a stale
                # timer's _timed_out flag must not leak into the next round
                self._timed_out = False
                return
            if len(self._share_sums) < self.threshold + 1:
                logging.error(
                    "turboaggregate round %d: only %d/%d share-sums after "
                    "timeout (< t+1=%d) — cannot reconstruct; waiting on",
                    self.round_idx, len(self._share_sums), self.worker_num,
                    self.threshold + 1,
                )
                return
            # snapshot AND advance the round inside one critical section:
            # a straggler's share-sum from the closed round must fail the
            # round check the moment we commit to reconstructing (the timer
            # thread and the receive thread race here when round_timeout is
            # set)
            share_sums = dict(self._share_sums)
            self._share_sums.clear()
            closed_round = self.round_idx
            self.round_idx += 1
            self._timed_out = False
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        senders = sorted(share_sums)[: self.threshold + 1]
        shares = np.stack([share_sums[s] for s in senders])
        share_idx = np.asarray(senders) - 1  # rank w holds eval point w
        summed = bgw_decode(shares, share_idx, self.prime)
        mean_delta = dequantize(summed, self.scale, self.prime)
        new_flat = (
            self.global_flat.view(np.float32).astype(np.float64) + mean_delta
        ).astype(np.float32)
        self.global_flat = new_flat.view(np.uint8)
        if self.on_round_done:
            self.on_round_done(closed_round, self.global_flat)
        finished = self.round_idx >= self.round_num
        self._send_sync(finished)
        if finished:
            self.finish()


class TAClientManager(ClientManager):
    """Local training + BGW share exchange with peers."""

    def __init__(self, comm: BaseCommunicationManager, rank: int, size: int,
                 trainer: ClientTrainer, train_data: FederatedArrays,
                 batch_size: int, threshold: int | None = None,
                 scale: float = 2**16, prime: int = DEFAULT_PRIME, seed: int = 0,
                 local_train_fn=None):
        super().__init__(comm, rank, size)
        self.worker_num = size - 1
        self.trainer = trainer
        self.train_data = train_data
        self.batch_size = batch_size
        self.threshold = _check_threshold(
            threshold if threshold is not None else max(1, (self.worker_num - 1) // 2),
            self.worker_num,
        )
        self.scale = scale
        self.prime = prime
        self.seed = seed
        # one shared jitted program across all in-process clients (the
        # run_turboaggregate harness passes it; standalone construction
        # compiles its own)
        self._local_train = local_train_fn or jax.jit(make_local_train(trainer))
        self._desc: str | None = None
        self._lock = threading.Lock()
        # shares can arrive before this client finishes its own training —
        # buffer per round
        self._peer_shares: dict[int, dict[int, np.ndarray]] = {}
        self._submitted: set[int] = set()
        self._p_i: float | None = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(TAMessage.MSG_TYPE_S2C_INIT, self._on_init)
        self.register_message_receive_handler(TAMessage.MSG_TYPE_S2C_SYNC, self._on_sync)
        self.register_message_receive_handler(TAMessage.MSG_TYPE_C2C_SHARE, self._on_peer_share)

    # -- round legs ----------------------------------------------------------

    def _client_index(self) -> int:
        return (self.rank - 1) % self.train_data.num_clients

    def _on_init(self, msg: Message) -> None:
        self._desc = msg.get(TAMessage.KEY_DESC)
        n_i = float(len(self.train_data.partition[self._client_index()]))
        out = Message(TAMessage.MSG_TYPE_C2S_REGISTER, self.rank, 0)
        out.add_params(TAMessage.KEY_NUM_SAMPLES, n_i)
        self.send_message(out)

    def _on_sync(self, msg: Message) -> None:
        if msg.get("finished"):
            self.finish()
            return
        round_idx = int(msg.get(TAMessage.KEY_ROUND))
        self._p_i = float(msg.get(TAMessage.KEY_WEIGHT))
        flat = np.asarray(msg.get(TAMessage.KEY_MODEL))
        variables = unpack_pytree(flat, self._desc)
        batches, _ = stack_cohort(
            self.train_data, np.asarray([self._client_index()]), self.batch_size,
            rng=np.random.RandomState(1000 + round_idx),
        )
        batches = jax.tree.map(lambda v: jnp.asarray(v[0]), batches)
        new_vars, _ = self._local_train(
            variables, batches, jax.random.key(self.rank * 100003 + round_idx)
        )
        new_flat, _ = pack_pytree(jax.tree.map(np.asarray, new_vars))
        # weight-normalized update: |p_i * delta| <= |delta|, so the field
        # sum over all clients stays within scale * max|delta| (no overflow
        # growth with client count or dataset size)
        delta = (
            new_flat.view(np.float32).astype(np.float64)
            - flat.view(np.float32).astype(np.float64)
        ) * self._p_i
        shares = bgw_encode(
            quantize(delta, self.scale, self.prime),
            self.worker_num, self.threshold, self.prime,
            seed=self.seed * 7919 + self.rank * 104729 + round_idx,
        )
        with self._lock:
            # my own share (eval point = my rank) stays local
            self._stash_share(round_idx, self.rank, shares[self.rank - 1])
        for peer in range(1, self.worker_num + 1):
            if peer == self.rank:
                continue
            m = Message(TAMessage.MSG_TYPE_C2C_SHARE, self.rank, peer)
            m.add_params(TAMessage.KEY_SHARE, shares[peer - 1])
            m.add_params(TAMessage.KEY_ROUND, round_idx)
            self.send_message(m)
        self._maybe_submit(round_idx)

    def _on_peer_share(self, msg: Message) -> None:
        round_idx = int(msg.get(TAMessage.KEY_ROUND))
        with self._lock:
            self._stash_share(
                round_idx, msg.get_sender_id(),
                np.asarray(msg.get(TAMessage.KEY_SHARE)),
            )
        self._maybe_submit(round_idx)

    def _stash_share(self, round_idx: int, sender: int, share: np.ndarray) -> None:
        self._peer_shares.setdefault(round_idx, {})[sender] = share

    def _maybe_submit(self, round_idx: int) -> None:
        with self._lock:
            got = self._peer_shares.get(round_idx, {})
            if len(got) < self.worker_num or round_idx in self._submitted:
                return
            self._submitted.add(round_idx)
            stack = np.stack([got[s] for s in sorted(got)])
            del self._peer_shares[round_idx]
        share_sum = stack.sum(axis=0) % self.prime
        out = Message(TAMessage.MSG_TYPE_C2S_SHARE_SUM, self.rank, 0)
        out.add_params(TAMessage.KEY_SHARE, share_sum)
        out.add_params(TAMessage.KEY_ROUND, round_idx)
        self.send_message(out)


def run_turboaggregate(
    trainer: ClientTrainer,
    train_data: FederatedArrays,
    worker_num: int,
    round_num: int,
    batch_size: int,
    make_comm: Callable[[int], BaseCommunicationManager],
    threshold: int | None = None,
    scale: float = 2**16,
    seed: int = 0,
    round_timeout: float | None = None,
    on_round_done: Callable[[int, Any], None] | None = None,
):
    """End-to-end secure aggregation over any comm fabric (same harness
    shape as run_distributed_fedavg). Returns the final global variables."""
    from fedml_tpu.algorithms.fedavg_distributed import (
        init_template,
        run_manager_protocol,
    )

    template, flat, desc = init_template(trainer, train_data.arrays, batch_size, seed)
    non_f32 = [leaf.dtype for leaf in jax.tree.leaves(template)
               if np.asarray(leaf).dtype != np.float32]
    if non_f32:
        raise ValueError(f"secure aggregation requires float32 leaves; got {non_f32}")

    results: dict[str, np.ndarray] = {}

    def _done(r, f):
        results["final"] = f
        if on_round_done is not None:
            on_round_done(r, unpack_pytree(f, desc))

    server = TAServerManager(
        make_comm(0), worker_num, round_num, flat, desc,
        threshold=threshold, scale=scale, round_timeout=round_timeout,
        on_round_done=_done,
    )
    shared_local_train = jax.jit(make_local_train(trainer))
    clients = [
        TAClientManager(
            make_comm(r), r, worker_num + 1, trainer, train_data, batch_size,
            threshold=threshold, scale=scale, seed=seed,
            local_train_fn=shared_local_train,
        )
        for r in range(1, worker_num + 1)
    ]
    run_manager_protocol(server, clients)
    if "final" not in results:
        raise RuntimeError("turboaggregate run produced no final model")
    logging.info("turboaggregate: %d rounds complete", round_num)
    return unpack_pytree(results["final"], desc)
