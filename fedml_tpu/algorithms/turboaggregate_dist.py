"""TurboAggregate as a multi-party protocol over the comm layer.

Reference: fedml_api/distributed/turboaggregate/ — TA_Aggregator.py:13 wires
the MPC library (mpc_function.py) into the aggregator/trainer/manager
triple, and TA_decentralized_worker_manager.py exchanges shares between
neighbor workers (message_define.py MSG_TYPE_SEND_MSG_TO_NEIGHBOR=2). The
reference never completes the loop — its aggregate() is plain FedAvg on
plaintext models. Here the secure path actually runs:

1. Server broadcasts the global model (S2C init); clients register their
   clear-text sample counts n_i; the server broadcasts the normalized
   weights p_i = n_i / sum(n) with the round sync. Entering the field with
   p_i * delta_i (|p_i| <= 1) keeps the share-sum bounded by
   scale * max|delta| — no overflow growth with client count or samples.
2. Each client trains locally, quantizes ``p_i * (local - global)``, and
   BGW-shares it: share j goes DIRECTLY to client j (client-to-client typed
   messages; the server never routes or sees a plaintext update).
3. Each client pointwise-sums the W shares it holds (one per peer) — by
   BGW linearity a share of ``sum_i p_i * delta_i`` — and uploads only that
   share-sum.
4. The server Lagrange-reconstructs the weighted-mean delta from
   threshold+1 share-sums and applies it to the global model. Every
   share-sum already contains its inclusion set's updates, so clients that
   die after the share-exchange leg but before uploading cost nothing: with
   ``round_timeout`` set, the server reconstructs the full aggregate from
   whichever >= threshold+1 share-sums arrived.
5. Pre-share dropout recovery (``share_timeout``): a client that dies
   BEFORE sending its peer shares would leave everyone waiting, so clients
   whose share wait times out report (clear metadata only) which peers'
   shares they hold; the server intersects the reports into an agreed
   inclusion set and broadcasts it to EVERY live worker — reporters AND
   clients that already submitted full-set share-sums. Reporters submit
   share-sums over exactly the agreed subset; a full-set holder (which
   necessarily holds every share of any agreed subset) RESUBMITS over the
   agreed subset, superseding its earlier full-set sum, so all live
   workers land in one same-set bucket and t+1 is reachable even when the
   dying client delivered shares to some-but-not-all peers. Share-sums
   carry their inclusion set and the server reconstructs only within the
   largest same-set bucket — sums over different subsets are shares of
   different polynomials and are never mixed — then renormalizes by the
   included weight mass. Two guards bound what recovery can reveal: a
   bucket that can already reconstruct (>= t+1 full-set sums) closes the
   round directly instead of starting subset recovery — otherwise the
   server could interpolate BOTH polynomials and their difference is the
   dead client's individual update — and an inclusion set smaller than
   t+1 (disjoint reports) is refused and the round skipped. This is
   subset consistency, not SecAgg mask recovery: simpler, and sufficient
   because BGW shares (unlike pairwise masks) need no per-dropout
   unmasking.

Privacy: the server sees only the aggregate; a coalition of <= threshold
clients learns nothing about another client's update (Shamir). Exactness:
the aggregate equals FedAvg up to 1/quantize-scale rounding.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.turboaggregate import (
    DEFAULT_PRIME,
    bgw_decode,
    bgw_encode,
    dequantize,
    quantize,
)
from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message, pack_pytree, unpack_pytree
from fedml_tpu.core.trainer import ClientTrainer, make_local_train
from fedml_tpu.sim.cohort import FederatedArrays, stack_cohort


class TAMessage:
    """Message types (reference message_define.py:6-8, extended with the
    share-exchange legs the reference leaves unimplemented)."""

    MSG_TYPE_S2C_INIT = 1
    MSG_TYPE_S2C_SYNC = 2
    MSG_TYPE_C2S_REGISTER = 3      # clear-text sample count n_i
    MSG_TYPE_C2C_SHARE = 4         # BGW share leg: client -> client
    MSG_TYPE_C2S_SHARE_SUM = 5     # masked aggregate leg: client -> server
    # pre-share dropout recovery (subset consistency, see class docstring)
    MSG_TYPE_C2S_SHARE_REPORT = 6  # clear metadata: which peers' shares arrived
    MSG_TYPE_S2C_INCLUDE = 7       # server-agreed inclusion set

    KEY_MODEL = Message.MSG_ARG_KEY_MODEL_PARAMS
    KEY_DESC = Message.MSG_ARG_KEY_MODEL_DESC
    KEY_NUM_SAMPLES = Message.MSG_ARG_KEY_NUM_SAMPLES
    KEY_SHARE = "bgw_share"
    KEY_ROUND = Message.MSG_ARG_KEY_ROUND_IDX
    KEY_WEIGHT = "p_i"  # this client's normalized aggregation weight
    KEY_HOLDERS = "holders"        # share report: ranks whose shares I hold
    KEY_INCLUDE = "include_set"    # ranks whose updates a share-sum includes


def _check_threshold(threshold: int, worker_num: int) -> int:
    if not 1 <= threshold < worker_num:
        raise ValueError(
            f"privacy threshold must satisfy 1 <= t < worker_num "
            f"(got t={threshold}, workers={worker_num}): BGW needs t+1 of "
            f"the {worker_num} share points to interpolate a degree-t polynomial"
        )
    return threshold


class TAServerManager(ServerManager):
    """Receives only clear sample counts and share-sums; reconstructs only
    the aggregate."""

    def __init__(self, comm: BaseCommunicationManager, worker_num: int,
                 round_num: int, init_flat: np.ndarray, model_desc: str,
                 threshold: int | None = None, scale: float = 2**16,
                 prime: int = DEFAULT_PRIME,
                 round_timeout: float | None = None,
                 on_round_done: Callable[[int, np.ndarray], None] | None = None):
        super().__init__(comm, rank=0, size=worker_num + 1)
        self.worker_num = worker_num
        self.round_num = round_num
        self.round_idx = 0
        self.global_flat = np.asarray(init_flat)
        self.model_desc = model_desc
        self.threshold = _check_threshold(
            threshold if threshold is not None else max(1, (worker_num - 1) // 2),
            worker_num,
        )
        self.scale = scale
        self.prime = prime
        self.round_timeout = round_timeout
        self.on_round_done = on_round_done
        self._sample_nums: dict[int, float] = {}
        # sender -> (include_set_tuple, share_sum): share-sums over different
        # inclusion sets are shares of DIFFERENT polynomials and must never
        # be mixed in one reconstruction
        self._share_sums: dict[int, tuple[tuple[int, ...], np.ndarray]] = {}  # guarded-by: _lock
        self._reports: dict[int, tuple[int, ...]] = {}  # guarded-by: _lock
        self._include_sent = False  # guarded-by: _lock
        self._include_set: list[int] = []  # guarded-by: _lock
        self._timed_out = False  # guarded-by: _lock
        self._timer: threading.Timer | None = None  # guarded-by: _lock
        self._lock = threading.Lock()

    def send_init_msg(self) -> None:
        for w in range(1, self.worker_num + 1):
            msg = Message(TAMessage.MSG_TYPE_S2C_INIT, 0, w)
            msg.add_params(TAMessage.KEY_MODEL, self.global_flat)
            msg.add_params(TAMessage.KEY_DESC, self.model_desc)
            self.send_message(msg)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            TAMessage.MSG_TYPE_C2S_REGISTER, self._on_register
        )
        self.register_message_receive_handler(
            TAMessage.MSG_TYPE_C2S_SHARE_SUM, self._on_share_sum
        )
        self.register_message_receive_handler(
            TAMessage.MSG_TYPE_C2S_SHARE_REPORT, self._on_share_report
        )

    # -- registration: collect n_i, broadcast p_i ---------------------------

    def _on_register(self, msg: Message) -> None:
        with self._lock:
            self._sample_nums[msg.get_sender_id()] = float(
                msg.get(TAMessage.KEY_NUM_SAMPLES)
            )
            if len(self._sample_nums) < self.worker_num:
                return
        self._send_sync(finished=False)

    def _send_sync(self, finished: bool) -> None:
        total = sum(self._sample_nums.values())
        for w in range(1, self.worker_num + 1):
            sync = Message(TAMessage.MSG_TYPE_S2C_SYNC, 0, w)
            sync.add_params(TAMessage.KEY_MODEL, self.global_flat)
            sync.add_params(TAMessage.KEY_ROUND, self.round_idx)
            sync.add_params(TAMessage.KEY_WEIGHT, self._sample_nums[w] / total)
            if finished:
                sync.add_params(Message.MSG_ARG_KEY_FINISHED, 1)
            self.send_message(sync)

    # -- aggregation --------------------------------------------------------

    def _on_share_sum(self, msg: Message) -> None:
        resend_to = None
        with self._lock:
            if int(msg.get(TAMessage.KEY_ROUND)) != self.round_idx:
                return  # late arrival from a timed-out round
            include = msg.get(TAMessage.KEY_INCLUDE)
            include = (
                tuple(int(i) for i in include) if include is not None
                else tuple(range(1, self.worker_num + 1))
            )
            sender = msg.get_sender_id()
            if self._include_sent and include != tuple(self._include_set):
                # a share-sum arriving AFTER the inclusion-set decision with
                # a different set (e.g. a slow full-set holder) never saw the
                # broadcast — resend it so this sender can resubmit into the
                # agreed bucket, otherwise the round can stall with subset
                # sums and full sums that never reach t+1 in any one bucket.
                # The mismatched sum is NOT stored: once subset recovery is
                # active the privacy guard's invariant (full-set submissions
                # <= t while a t+1 subset bucket may form) must hold at
                # every instant, and storing a late full-set sum could
                # transiently give the server t+1 points on BOTH polynomials
                # — whose difference is the dead client's individual update
                resend_to = (sender, self._include_set, self.round_idx)
            else:
                self._share_sums[sender] = (
                    include, np.asarray(msg.get(TAMessage.KEY_SHARE))
                )
            got = len(self._share_sums)
            if (got == 1 and self.round_timeout is not None
                    and self._timer is None and not self._timed_out):
                # every share-sum carries its whole inclusion set's updates;
                # after the timeout any threshold+1 same-set share-sums
                # reconstruct the aggregate. Never re-arm (or reset
                # _timed_out) once a recovery timer already fired — the
                # post-include share-sums must close at t+1 immediately,
                # not after a second full round_timeout
                self._timer = threading.Timer(self.round_timeout, self._timeout)
                self._timer.daemon = True
                self._timer.start()
            closing = got >= self.worker_num or (
                self._timed_out and got >= self.threshold + 1
            )
        if resend_to is not None:
            sender, inc, rnd = resend_to
            self._send_include(inc, [sender], rnd)
        if closing:
            self._close_round()

    def _on_share_report(self, msg: Message) -> None:
        """Pre-share dropout recovery, leg 1: a client whose share wait timed
        out reports (clear metadata only) which peers' shares it holds. Once
        every live worker has either submitted or reported, broadcast the
        intersection as the agreed inclusion set — every reporter holds all
        of it, so all share-sums land in one reconstructable bucket."""
        with self._lock:
            if int(msg.get(TAMessage.KEY_ROUND)) != self.round_idx:
                return
            sender = msg.get_sender_id()
            self._reports[sender] = tuple(
                int(i) for i in msg.get(TAMessage.KEY_HOLDERS)
            )
            # capture the round INSIDE the lock: _close_round can advance
            # round_idx between lock release and the include send, and an
            # include stamped with the wrong round would make next round's
            # full-set holders submit over a stale subset, silently dropping
            # a live client's update
            rnd = self.round_idx
            if self._include_sent:
                # a reporter arriving after the decision still needs the set
                # (a lost reply would strand it mid-round forever); sound as
                # long as it holds every member, which the intersection rule
                # cannot guarantee for late reports — verify and fall back to
                # excluding its share-sum (it simply won't submit)
                action, include, recipients = (
                    "send", self._include_set,
                    [sender] if set(self._include_set)
                    <= set(self._reports[sender]) else [],
                )
            elif self._bucket_max_locked() >= self.threshold + 1:
                # PRIVACY GUARD: a reconstructable bucket already exists, so
                # close on it instead of starting subset recovery. The
                # full-set sums carry the dead client's delivered shares, so
                # nothing is lost — and crucially this keeps subset recovery
                # confined to the regime where full-set submissions <= t:
                # were both a reconstructable full-set bucket AND a t+1
                # subset bucket ever visible, the server could interpolate
                # both polynomials and their difference is the dead client's
                # individual (weighted) update — exactly the leak the
                # protocol exists to prevent.
                action, include, recipients = "close", None, []
            else:
                covered = set(self._reports) | set(self._share_sums)
                # decide as soon as every rank is accounted for, or — with
                # dead clients that will never speak — when the timer has
                # declared the silent ranks dead
                if len(covered) < self.worker_num and not (
                    len(self._reports) >= self.threshold + 1 and self._timed_out
                ):
                    # arm the dead-rank-declaring timer even when the caller
                    # set no round_timeout: a pre-share drop would otherwise
                    # wait forever for the dead rank's report (the exact
                    # stall the share_timeout feature exists to prevent)
                    if self._timer is None and not self._timed_out:
                        grace = (self.round_timeout
                                 if self.round_timeout is not None else 5.0)
                        self._timer = threading.Timer(grace, self._timeout)
                        self._timer.daemon = True
                        self._timer.start()
                    return
                action, include, recipients = self._decide_include_locked()
        self._dispatch_recovery(action, include, recipients, rnd)

    def _dispatch_recovery(self, action: str, include, recipients,
                           rnd: int) -> None:
        """Execute a recovery decision outside the lock."""
        if action == "close":
            self._close_round()
        elif action == "abort":
            self._abort_round(rnd)
        else:
            self._send_include(include, recipients, rnd)

    def _bucket_max_locked(self) -> int:  # lock-held: _lock
        """Size of the largest same-inclusion-set bucket (caller holds the
        lock)."""
        counts: dict[tuple[int, ...], int] = {}
        for include, _ in self._share_sums.values():
            counts[include] = counts.get(include, 0) + 1
        return max(counts.values(), default=0)

    def _decide_include_locked(self):  # lock-held: _lock
        """Intersect the reports into the agreed inclusion set (caller holds
        the lock). Returns an explicit ``(action, include, recipients)``
        triple: ``("send", set, live workers)`` normally, ``("abort", ...)``
        when the set is refused (smaller than t+1)."""
        include = sorted(set.intersection(
            *(set(h) for h in self._reports.values())
        ))
        if len(include) < self.threshold + 1:
            # disjoint reports can intersect to (near-)nothing; an aggregate
            # over < t+1 clients would reveal near-individual updates to the
            # server — and an empty set would np.stack([]) on the client.
            # Refuse and skip the round instead of broadcasting it (workers
            # learn of the skip via the next sync, so no recipients here).
            logging.error(
                "turboaggregate round %d: agreed inclusion set %s smaller "
                "than t+1=%d — refusing; round skipped (global unchanged)",
                self.round_idx, include, self.threshold + 1,
            )
            return "abort", None, []
        # every live worker gets the set: reporters submit over it, and
        # full-set submitters (who hold every share of any subset) RESUBMIT
        # over it so one same-set bucket can reach t+1 even when the dead
        # client's shares reached only some peers. Safe against the
        # full-minus-subset difference attack because this path only runs
        # when no bucket reached t+1 (see the privacy guard above): the
        # at-most-t full-set points expose the dead client's degree-t
        # sharing polynomial at at most t points — information-theoretically
        # nothing about its constant term (the update).
        recipients = sorted(set(self._reports) | set(self._share_sums))
        self._include_sent = True
        self._include_set = include
        logging.info(
            "turboaggregate round %d: share dropout — inclusion set %s "
            "agreed from %d reports; notifying %d live workers",
            self.round_idx, include, len(self._reports), len(recipients),
        )
        return "send", include, recipients

    def _abort_round(self, round_to_abort: int) -> None:
        """Skip round ``round_to_abort`` (unreconstructable inclusion set):
        clear state, advance the round counter, and sync clients on the
        UNCHANGED global model so the protocol continues. Idempotent — the
        timer thread and the receive thread can both reach the refusal
        decision for the same round; only the first abort acts."""
        with self._lock:
            if self.round_idx != round_to_abort:
                return  # already aborted/closed by the racing thread
            self._share_sums.clear()
            self._reports.clear()
            self._include_sent = False
            self._include_set = []
            self._timed_out = False
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            skipped = self.round_idx
            self.round_idx += 1
        # the round completed (as a no-op): report the unchanged global so
        # curve recorders and the run harness see every round
        self._finalize_round(skipped)

    def _finalize_round(self, closed_round: int) -> None:
        """Shared end-of-round tail for close and abort: report the round,
        sync clients on the (possibly updated) global, finish when done."""
        if self.on_round_done:
            self.on_round_done(closed_round, self.global_flat)
        finished = self.round_idx >= self.round_num
        self._send_sync(finished)
        if finished:
            self.finish()

    def _send_include(self, include: list[int], recipients: list[int],
                      round_idx: int) -> None:
        for w in recipients:
            m = Message(TAMessage.MSG_TYPE_S2C_INCLUDE, 0, w)
            m.add_params(TAMessage.KEY_ROUND, round_idx)
            m.add_params(TAMessage.KEY_INCLUDE, np.asarray(include, np.int64))
            self.send_message(m)

    def _timeout(self) -> None:
        # if clients reported a share dropout, the timer's job is to declare
        # the silent ranks dead and broadcast the inclusion set — the
        # incoming (re)submissions then close the round normally. A bucket
        # that can already reconstruct takes precedence over subset recovery
        # (privacy guard, see _on_share_report).
        with self._lock:
            self._timed_out = True
            rnd = self.round_idx
            if (self._reports and not self._include_sent
                    and self._bucket_max_locked() < self.threshold + 1):
                action, include, recipients = self._decide_include_locked()
            else:
                action, include, recipients = "close", None, []
        self._dispatch_recovery(action, include, recipients, rnd)

    def _close_round(self) -> None:
        with self._lock:
            if not self._share_sums:
                # benign double close (timer raced the full tally); a stale
                # timer's _timed_out flag must not leak into the next round
                self._timed_out = False
                return
            # share-sums over different inclusion sets are shares of
            # different polynomials: reconstruct from the largest same-set
            # bucket only
            buckets: dict[tuple[int, ...], list[int]] = {}
            for sender, (include, _) in self._share_sums.items():
                buckets.setdefault(include, []).append(sender)
            include, bucket = max(buckets.items(), key=lambda kv: len(kv[1]))
            if len(bucket) < self.threshold + 1:
                logging.error(
                    "turboaggregate round %d: largest same-set bucket has "
                    "%d/%d share-sums (< t+1=%d) — cannot reconstruct; waiting",
                    self.round_idx, len(bucket), self.worker_num,
                    self.threshold + 1,
                )
                return
            # snapshot AND advance the round inside one critical section:
            # a straggler's share-sum from the closed round must fail the
            # round check the moment we commit to reconstructing (the timer
            # thread and the receive thread race here when round_timeout is
            # set)
            share_sums = {s: self._share_sums[s][1] for s in bucket}
            self._share_sums.clear()
            self._reports.clear()
            self._include_sent = False
            self._include_set = []
            closed_round = self.round_idx
            self.round_idx += 1
            self._timed_out = False
            total = sum(self._sample_nums.values())
            # the bucket's aggregate is sum_{i in include} p_i * delta_i;
            # renormalize by the included weight mass so dropped clients
            # don't shrink the update (clear metadata, no privacy cost)
            w_mass = sum(
                self._sample_nums.get(i, 0.0) / total for i in include
            ) or 1.0
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        senders = sorted(share_sums)[: self.threshold + 1]
        shares = np.stack([share_sums[s] for s in senders])
        share_idx = np.asarray(senders) - 1  # rank w holds eval point w
        summed = bgw_decode(shares, share_idx, self.prime)
        mean_delta = dequantize(summed, self.scale, self.prime) / w_mass
        new_flat = (
            self.global_flat.view(np.float32).astype(np.float64) + mean_delta
        ).astype(np.float32)
        self.global_flat = new_flat.view(np.uint8)
        self._finalize_round(closed_round)


class TAClientManager(ClientManager):
    """Local training + BGW share exchange with peers."""

    def __init__(self, comm: BaseCommunicationManager, rank: int, size: int,
                 trainer: ClientTrainer, train_data: FederatedArrays,
                 batch_size: int, threshold: int | None = None,
                 scale: float = 2**16, prime: int = DEFAULT_PRIME, seed: int = 0,
                 local_train_fn=None, share_timeout: float | None = None):
        super().__init__(comm, rank, size)
        self.worker_num = size - 1
        self.trainer = trainer
        self.train_data = train_data
        self.batch_size = batch_size
        self.threshold = _check_threshold(
            threshold if threshold is not None else max(1, (self.worker_num - 1) // 2),
            self.worker_num,
        )
        self.scale = scale
        self.prime = prime
        self.seed = seed
        # one shared jitted program across all in-process clients (the
        # run_turboaggregate harness passes it; standalone construction
        # compiles its own)
        self._local_train = local_train_fn or jax.jit(make_local_train(trainer))
        self._desc: str | None = None
        self._lock = threading.Lock()
        # shares can arrive before this client finishes its own training —
        # buffer per round
        self._peer_shares: dict[int, dict[int, np.ndarray]] = {}  # guarded-by: _lock
        # round -> inclusion set submitted (dict, not set: a resubmission is
        # warranted only when the agreed set differs from what went out)
        self._submitted: dict[int, tuple[int, ...]] = {}  # guarded-by: _lock
        self._p_i: float | None = None
        # pre-share dropout recovery: if a peer's share hasn't arrived
        # share_timeout seconds after our own shares went out, report the
        # holders we DO have and wait for the server's inclusion set
        self.share_timeout = share_timeout
        self._share_timers: dict[int, threading.Timer] = {}
        self._include: dict[int, tuple[int, ...]] = {}

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(TAMessage.MSG_TYPE_S2C_INIT, self._on_init)
        self.register_message_receive_handler(TAMessage.MSG_TYPE_S2C_SYNC, self._on_sync)
        self.register_message_receive_handler(TAMessage.MSG_TYPE_C2C_SHARE, self._on_peer_share)
        self.register_message_receive_handler(TAMessage.MSG_TYPE_S2C_INCLUDE, self._on_include)

    # -- round legs ----------------------------------------------------------

    def _client_index(self) -> int:
        return (self.rank - 1) % self.train_data.num_clients

    def _on_init(self, msg: Message) -> None:
        self._desc = msg.get(TAMessage.KEY_DESC)
        n_i = float(len(self.train_data.partition[self._client_index()]))
        out = Message(TAMessage.MSG_TYPE_C2S_REGISTER, self.rank, 0)
        out.add_params(TAMessage.KEY_NUM_SAMPLES, n_i)
        self.send_message(out)

    def _on_sync(self, msg: Message) -> None:
        if msg.get(Message.MSG_ARG_KEY_FINISHED):
            self.finish()
            return
        round_idx = int(msg.get(TAMessage.KEY_ROUND))
        with self._lock:
            # a new sync closes all earlier rounds: drop their buffered peer
            # shares / inclusion sets / timers (a round this client never
            # submitted — e.g. it was excluded from the inclusion set —
            # would otherwise leak one model-sized share per peer forever)
            for stale in [r for r in self._peer_shares if r < round_idx]:
                del self._peer_shares[stale]
            for stale in [r for r in self._include if r < round_idx]:
                del self._include[stale]
            for stale in [r for r in self._submitted if r < round_idx]:
                del self._submitted[stale]
            for stale in [r for r in self._share_timers if r < round_idx]:
                self._share_timers.pop(stale).cancel()
        self._p_i = float(msg.get(TAMessage.KEY_WEIGHT))
        flat = np.asarray(msg.get(TAMessage.KEY_MODEL))
        variables = unpack_pytree(flat, self._desc)
        batches, _ = stack_cohort(
            self.train_data, np.asarray([self._client_index()]), self.batch_size,
            rng=np.random.RandomState(1000 + round_idx),
        )
        batches = jax.tree.map(lambda v: jnp.asarray(v[0]), batches)
        new_vars, _ = self._local_train(
            variables, batches, jax.random.key(self.rank * 100003 + round_idx)
        )
        new_flat, _ = pack_pytree(jax.tree.map(np.asarray, new_vars))
        # weight-normalized update: |p_i * delta| <= |delta|, so the field
        # sum over all clients stays within scale * max|delta| (no overflow
        # growth with client count or dataset size)
        delta = (
            new_flat.view(np.float32).astype(np.float64)
            - flat.view(np.float32).astype(np.float64)
        ) * self._p_i
        shares = bgw_encode(
            quantize(delta, self.scale, self.prime),
            self.worker_num, self.threshold, self.prime,
            seed=self.seed * 7919 + self.rank * 104729 + round_idx,
        )
        with self._lock:
            # my own share (eval point = my rank) stays local
            self._stash_share(round_idx, self.rank, shares[self.rank - 1])
        for peer in range(1, self.worker_num + 1):
            if peer == self.rank:
                continue
            m = Message(TAMessage.MSG_TYPE_C2C_SHARE, self.rank, peer)
            m.add_params(TAMessage.KEY_SHARE, shares[peer - 1])
            m.add_params(TAMessage.KEY_ROUND, round_idx)
            self.send_message(m)
        if self.share_timeout is not None:
            t = threading.Timer(self.share_timeout,
                                self._report_holders, args=(round_idx,))
            t.daemon = True
            with self._lock:
                self._share_timers[round_idx] = t
            t.start()
        self._maybe_submit(round_idx)

    def _on_peer_share(self, msg: Message) -> None:
        round_idx = int(msg.get(TAMessage.KEY_ROUND))
        with self._lock:
            self._stash_share(
                round_idx, msg.get_sender_id(),
                np.asarray(msg.get(TAMessage.KEY_SHARE)),
            )
        self._maybe_submit(round_idx)

    def _on_include(self, msg: Message) -> None:
        round_idx = int(msg.get(TAMessage.KEY_ROUND))
        with self._lock:
            self._include[round_idx] = tuple(
                int(i) for i in msg.get(TAMessage.KEY_INCLUDE)
            )
        self._maybe_submit(round_idx)

    def _report_holders(self, round_idx: int) -> None:
        """Share wait timed out: report (clear metadata) which peers' shares
        arrived; the server intersects reports into an inclusion set."""
        with self._lock:
            if round_idx in self._submitted:
                return
            holders = sorted(self._peer_shares.get(round_idx, {}))
        out = Message(TAMessage.MSG_TYPE_C2S_SHARE_REPORT, self.rank, 0)
        out.add_params(TAMessage.KEY_HOLDERS, np.asarray(holders, np.int64))
        out.add_params(TAMessage.KEY_ROUND, round_idx)
        self.send_message(out)

    # lock-held: _lock
    def _stash_share(self, round_idx: int, sender: int, share: np.ndarray) -> None:
        self._peer_shares.setdefault(round_idx, {})[sender] = share

    def _maybe_submit(self, round_idx: int) -> None:
        with self._lock:
            got = self._peer_shares.get(round_idx, {})
            agreed = self._include.get(round_idx)
            prev = self._submitted.get(round_idx)
            if prev is not None:
                # already submitted: only a server-agreed subset DIFFERENT
                # from what we sent warrants a RESUBMISSION. A full-set
                # holder necessarily holds every share of any agreed subset;
                # its subset sum supersedes the full-set one on the server,
                # putting all live workers in one reconstructable bucket
                # (pre-share dropout recovery, class docstring step 5).
                if (agreed is None or tuple(agreed) == prev
                        or not set(agreed) <= set(got)):
                    return
                include = tuple(agreed)
            elif len(got) >= self.worker_num:
                # full set — but an already-agreed subset takes precedence
                # so the server's same-set bucket forms without a resubmit
                include = tuple(range(1, self.worker_num + 1))
                if agreed is not None and set(agreed) <= set(got):
                    include = tuple(agreed)
            else:
                # partial shares: only submit once the server has fixed the
                # inclusion set and we hold every share in it
                if agreed is None or not set(agreed) <= set(got):
                    return
                include = tuple(agreed)
            if not include:
                # the server refuses to broadcast an empty set; guard anyway
                # so a malformed message can't np.stack([]) and kill the
                # receive thread
                return
            self._submitted[round_idx] = include
            stack = np.stack([got[s] for s in include])
            # keep _peer_shares/_include until the next sync's stale-round
            # sweep: a later inclusion-set broadcast may require resubmitting
            timer = self._share_timers.pop(round_idx, None)
        if timer is not None:
            timer.cancel()
        share_sum = stack.sum(axis=0) % self.prime
        out = Message(TAMessage.MSG_TYPE_C2S_SHARE_SUM, self.rank, 0)
        out.add_params(TAMessage.KEY_SHARE, share_sum)
        out.add_params(TAMessage.KEY_ROUND, round_idx)
        out.add_params(TAMessage.KEY_INCLUDE, np.asarray(include, np.int64))
        self.send_message(out)


def run_turboaggregate(
    trainer: ClientTrainer,
    train_data: FederatedArrays,
    worker_num: int,
    round_num: int,
    batch_size: int,
    make_comm: Callable[[int], BaseCommunicationManager],
    threshold: int | None = None,
    scale: float = 2**16,
    seed: int = 0,
    round_timeout: float | None = None,
    share_timeout: float | None = None,
    on_round_done: Callable[[int, Any], None] | None = None,
):
    """End-to-end secure aggregation over any comm fabric (same harness
    shape as run_distributed_fedavg). Returns the final global variables."""
    from fedml_tpu.algorithms.fedavg_distributed import (
        init_template,
        run_manager_protocol,
    )

    template, flat, desc = init_template(trainer, train_data.arrays, batch_size, seed)
    non_f32 = [leaf.dtype for leaf in jax.tree.leaves(template)
               if np.asarray(leaf).dtype != np.float32]
    if non_f32:
        raise ValueError(f"secure aggregation requires float32 leaves; got {non_f32}")

    results: dict[str, np.ndarray] = {}

    def _done(r, f):
        results["final"] = f
        if on_round_done is not None:
            on_round_done(r, unpack_pytree(f, desc))

    server = TAServerManager(
        make_comm(0), worker_num, round_num, flat, desc,
        threshold=threshold, scale=scale, round_timeout=round_timeout,
        on_round_done=_done,
    )
    shared_local_train = jax.jit(make_local_train(trainer))
    clients = [
        TAClientManager(
            make_comm(r), r, worker_num + 1, trainer, train_data, batch_size,
            threshold=threshold, scale=scale, seed=seed,
            local_train_fn=shared_local_train, share_timeout=share_timeout,
        )
        for r in range(1, worker_num + 1)
    ]
    run_manager_protocol(server, clients)
    if "final" not in results:
        raise RuntimeError("turboaggregate run produced no final model")
    logging.info("turboaggregate: %d rounds complete", round_num)
    return unpack_pytree(results["final"], desc)
