"""Sharded fold plane: chunk-parallel, order-deterministic upload
aggregation off the comm receive thread (docs/PERFORMANCE.md "The server
fold plane").

Every aggregation plane in this repo tallies through ONE flat f64
accumulator folded one upload at a time under the aggregator lock, on the
comm receive thread — at tree fan-ins the fold, not the wire, is the
server's throughput ceiling. The plane splits the accumulator into
fixed-size element chunks owned round-robin by K worker threads. The
receive handler only assigns the upload its global arrival sequence
position (it is still under the aggregator ``_lock``, so enqueue order IS
arrival order) and appends the task to every worker's FIFO; each worker
folds its own chunks of the uploads in queue order. Every accumulator
element therefore sees the exact same f64 addition sequence as the serial
fold — plane-on is **bitwise identical** to plane-off by construction —
while the receive pump returns immediately and K chunks fold concurrently.

Per-upload work that is not elementwise (decode of an encoded upload, the
robust plane's norm/clip decision) runs once per task in
:meth:`FoldTask.ensure_prepared`, memoized under the task's own lock:
whichever thread first needs the prepared form computes it, off the
receive thread, and the result is the same bits regardless of who ran it.

Quiesce is **wait-free by design**: :meth:`FoldPlane.drain` never blocks
on a condition — it *helps*, acquiring each worker's fold lock in turn and
folding whatever is still queued inline. The only ``wait`` in this module
is the worker idle loop parking on the plane condition itself, which is
exactly the shape fedlint's Condition-wait exemption covers
(docs/STATIC_ANALYSIS.md), so drains may run under the aggregator and
round locks with zero blocking-under-lock findings.

Lock order: aggregator ``_round_lock`` -> aggregator ``_lock`` ->
``_flocks[w]`` -> ``_cv`` -> ``FoldTask._prep_lock``. Workers never touch
the aggregator locks; finalize bookkeeping runs on the draining thread,
which already holds the aggregator ``_lock``.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np

from fedml_tpu.obs import metrics as metricslib
from fedml_tpu.obs import registry as registrylib
from fedml_tpu.obs import trace

# 256k f64 elements = 2MB per chunk: big enough that the per-chunk numpy
# dispatch overhead vanishes, small enough that a 4-worker plane load-
# balances a ~10M-element model across dozens of chunks per worker
DEFAULT_CHUNK_ELEMS = 1 << 18


class FoldTask:
    """One upload in flight through the plane.

    Subclasses supply the three family-specific pieces:

    - :meth:`_prepare` — the once-per-upload work (payload view/copy,
      decode, robust norm+clip). Returns the prepared form handed to every
      chunk fold, or ``None`` when the upload contributes no vector mass
      (a robust rejection) — workers then skip the fold entirely.
    - :meth:`fold_slice` — apply the ``[lo, hi)`` slice of the prepared
      contribution to the accumulator. MUST use the serial fold's exact
      per-element arithmetic.
    - :meth:`finalize` — scalar tally bookkeeping (weight sums, defense
      stats). Runs under the aggregator ``_lock`` at drain, in arrival
      order across tasks, so order-sensitive float sums reproduce the
      serial bits. Returns True when the task contributed vector mass.
    """

    __slots__ = ("seq", "first", "acc_elems", "contributed",
                 "_prep_lock", "_prep_state")

    def __init__(self, acc_elems: int):
        self.seq = -1
        # True when this task observed ``_acc is None`` at submit: partial
        # tasks then ASSIGN their first copy instead of adding to zeros,
        # mirroring the serial first-partial copy exactly
        self.first = False
        self.acc_elems = int(acc_elems)
        self.contributed = False
        self._prep_lock = threading.Lock()
        self._prep_state: tuple | None = None  # guarded-by: _prep_lock

    def ensure_prepared(self):
        """Memoized :meth:`_prepare`: first caller computes (off the
        receive thread), everyone else reuses the result. A prepare
        exception is memoized too, so a crashed task fails every chunk —
        and the drain — identically instead of double-counting side
        effects on retry."""
        with self._prep_lock:
            if self._prep_state is None:
                try:
                    prep = self._prepare()
                    self.contributed = prep is not None
                    self._prep_state = ("ok", prep)
                except BaseException as e:
                    self._prep_state = ("err", e)
            kind, val = self._prep_state
        if kind == "err":
            raise val
        return val

    def _prepare(self):
        raise NotImplementedError

    def fold_slice(self, acc: np.ndarray, lo: int, hi: int, prep) -> None:
        raise NotImplementedError

    def finalize(self, agg) -> bool:  # lock-held: _lock
        return self.contributed


class DenseFoldTask(FoldTask):
    """The base ``FedAvgDistAggregator._fold``: ``acc += n * f32(payload)``
    elementwise in f64 — chunked, same ``np.multiply(..., dtype=f64)``
    expression per element."""

    __slots__ = ("payload", "weight")

    def __init__(self, payload, weight: float):
        arr = np.asarray(payload)
        super().__init__(arr.nbytes // 4)
        self.payload = arr
        self.weight = float(weight)

    def _prepare(self):
        # the (possible) contiguity copy + dtype view move off the pump
        return np.ascontiguousarray(self.payload).view(np.float32)

    def fold_slice(self, acc, lo, hi, prep):
        acc[lo:hi] += np.multiply(prep[lo:hi], self.weight, dtype=np.float64)

    def finalize(self, agg) -> bool:  # lock-held: _lock
        agg._wsum += self.weight
        return True


class EncodedFoldTask(FoldTask):
    """``compress.aggregate.accumulate_encoded`` chunked: decode (or the
    top-k index sort) happens once in prepare, each chunk applies its
    slice through ``fold_encoded_slice`` — bincount scatter for top-k,
    the serial per-element expression for dense schemes."""

    __slots__ = ("enc", "weight", "codec")

    def __init__(self, enc, weight: float, codec, acc_elems: int):
        super().__init__(acc_elems)
        self.enc = enc
        self.weight = float(weight)
        self.codec = codec

    def _prepare(self):
        from fedml_tpu.compress.aggregate import prepare_encoded

        return prepare_encoded(self.enc, self.weight, self.codec)

    def fold_slice(self, acc, lo, hi, prep):
        from fedml_tpu.compress.aggregate import fold_encoded_slice

        fold_encoded_slice(acc, prep, lo, hi)

    def finalize(self, agg) -> bool:  # lock-held: _lock
        agg._wsum += self.weight
        return True


class TierPartialFoldTask(FoldTask):
    """``TierAggregator.fold_partial_weighted``: fold a child tier's raw
    f64 partial. The window's first partial is COPIED into the accumulator
    (``first=True`` -> per-chunk assignment), later ones add — the serial
    first-copy-else-add semantics, chunked."""

    __slots__ = ("payload", "wsum", "scale")

    def __init__(self, payload, wsum: float, scale: float = 1.0):
        arr = np.asarray(payload)
        super().__init__(arr.nbytes // 8)
        self.payload = arr
        self.wsum = float(wsum)
        self.scale = float(scale)

    def _prepare(self):
        part = np.ascontiguousarray(self.payload).view(np.float64)
        if self.scale != 1.0:
            part = part * np.float64(self.scale)
        return part

    def fold_slice(self, acc, lo, hi, prep):
        if self.first:
            acc[lo:hi] = prep[lo:hi]
        else:
            acc[lo:hi] += prep[lo:hi]

    def finalize(self, agg) -> bool:  # lock-held: _lock
        agg._wsum += self.wsum * self.scale
        return True


class FoldPlane:
    """K chunk workers + per-worker FIFO task queues.

    ``submit`` runs under the caller's aggregator lock (that is what makes
    queue order arrival order) and only appends + notifies; ``drain``
    helps fold whatever is left and re-raises the first worker error, so a
    crashed fold fails the round loudly instead of wedging the barrier.

    ``autostart=False`` is a test hook: no worker threads are spawned, so
    tasks provably sit queued until a drain folds them inline —
    deterministic coverage for snapshot-with-non-empty-queues schedules.
    """

    def __init__(self, workers: int, chunk_elems: int = DEFAULT_CHUNK_ELEMS,
                 autostart: bool = True):
        if workers < 1:
            raise ValueError(f"fold plane needs >= 1 worker, got {workers}")
        if chunk_elems < 1:
            raise ValueError(f"chunk_elems must be >= 1, got {chunk_elems}")
        self.workers = int(workers)
        self.chunk_elems = int(chunk_elems)
        self._autostart = bool(autostart)
        self._cv = threading.Condition(threading.Lock())
        self._queues = tuple(deque() for _ in range(self.workers))  # guarded-by: _cv
        self._seq = 0        # guarded-by: _cv
        self._depth = 0      # guarded-by: _cv
        self._error = None   # guarded-by: _cv
        self._closed = False  # guarded-by: _cv
        self._started = False  # guarded-by: _cv
        # serializes "pop one task + fold worker w's chunks of it": held by
        # the worker thread while it works, acquired by a draining thread
        # to help — acquisition order is _flocks[w] -> _cv, never reversed
        self._flocks = tuple(threading.Lock() for _ in range(self.workers))

    # -- receive-thread side ------------------------------------------------

    def submit(self, task: FoldTask, acc: np.ndarray) -> None:
        """Enqueue ``task`` against ``acc`` on every chunk worker. Caller
        holds the aggregator lock, so the assigned sequence position is the
        upload's arrival position."""
        with trace.span("fold/enqueue", elems=task.acc_elems):
            with self._cv:
                if self._closed:
                    raise RuntimeError("fold plane is closed")
                task.seq = self._seq
                self._seq += 1
                if not self._started and self._autostart:
                    self._start_locked()
                for q in self._queues:
                    q.append((task, acc))
                self._depth += 1
                depth = self._depth
                self._cv.notify_all()
        # gauge lands after the condition is released (PR 11 discipline:
        # telemetry never extends a critical section)
        registrylib.gauge(metricslib.FOLD_QUEUE_DEPTH, depth)

    def _start_locked(self) -> None:  # lock-held: _cv
        self._started = True
        for w in range(self.workers):
            threading.Thread(target=self._run, args=(w,),
                             name=f"fold-w{w}", daemon=True).start()

    # -- worker side --------------------------------------------------------

    def _run(self, w: int) -> None:
        while True:
            with self._cv:
                while not self._queues[w] and not self._closed:
                    self._cv.wait()
                if not self._queues[w] and self._closed:
                    return
            self._fold_pending(w)

    def _fold_pending(self, w: int) -> None:
        """Fold every task currently queued for worker ``w``, in queue
        order. The per-worker fold lock makes pop+fold one serialized unit,
        so a helping drain and the worker thread can interleave calls
        without ever reordering or double-applying a task."""
        with self._flocks[w]:
            while True:
                with self._cv:
                    if not self._queues[w]:
                        return
                    task, acc = self._queues[w].popleft()
                    self._depth -= 1
                try:
                    with trace.span("fold/worker", worker=w, seq=task.seq):
                        prep = task.ensure_prepared()
                        if prep is not None:
                            for lo, hi in self._owned(w, acc.size):
                                task.fold_slice(acc, lo, hi, prep)
                except BaseException as e:
                    with self._cv:
                        if self._error is None:
                            self._error = e

    def _owned(self, w: int, n: int):
        """Worker ``w``'s chunks of an ``n``-element accumulator, ascending:
        the fixed chunk grid dealt round-robin. Depends only on (n, chunk,
        K) — every thread that folds for ``w`` sees the same slices."""
        step = self.workers * self.chunk_elems
        for lo in range(w * self.chunk_elems, n, step):
            yield lo, min(lo + self.chunk_elems, n)

    # -- quiesce side -------------------------------------------------------

    def drain(self) -> None:
        """Fold everything still queued, inline, and surface worker errors.

        Wait-free: helping through the per-worker fold locks instead of
        waiting on a condition, so this is safe (and fedlint-clean) under
        the aggregator and round locks."""
        for w in range(self.workers):
            self._fold_pending(w)
        with self._cv:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                "fold plane worker failed; the round's tally is "
                "unrecoverable"
            ) from err

    def queued(self) -> int:
        """Tasks not yet fully folded (test/observability hook)."""
        with self._cv:
            return max(len(q) for q in self._queues) if self._queues else 0

    def close(self) -> None:
        """Wake idle workers so they exit. Queued tasks are NOT folded —
        call ``drain`` first if the tally still matters."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
