"""Streaming Byzantine-robust + DP aggregation for the message-passing wire
path (docs/ROBUSTNESS.md).

The sim engine's ``robust_aggregator`` (algorithms/robust.py) defends over a
stacked [C, ...] cohort — exactly the per-client buffering the streaming
server (PR 5, docs/PERFORMANCE.md "The server wire path") removed from the
hot path. This module folds the same defense pipeline into the
accumulate-on-arrival tally without giving back the O(model) memory win:

- **clip** — each upload's delta against the last broadcast global model is
  norm-clipped AT ARRIVAL (``robust.clip_scale``, the same factor definition
  the sim uses; BN statistics excluded via ``robust.flat_norm_mask``), and
  the clipped update folds straight into the running f64 accumulator.
  Non-finite uploads (a bit-corrupted wire payload decodes to inf/NaN) are
  rejected outright — their weight never enters the divisor.
- **combine** — the ``mean`` rule stays pure streaming. Median / trimmed
  mean / Krum are cross-client order statistics that fundamentally need a
  stack, so they get a bounded-memory arm: a seeded reservoir of K clipped
  uploads (K ≪ N, ``reservoir_k``; 0 keeps every upload = the exact rule).
  At round close the reservoir stack runs through the SAME rule functions
  as the sim (``coordinate_median`` / ``trimmed_mean`` / ``krum_select``).
- **noise** — seeded weak-DP gaussian noise on the aggregate at round close
  (``robust.add_weak_dp_noise`` with the ``dp_noise_key`` round schedule),
  so a clipped+DP run is bit-reproducible.

``Buffered*`` variants retain every upload and replay the identical
defended fold in arrival order at round close — the bit-exactness oracle
for the streaming arm (tools/robust_smoke.py + tests/test_robust.py hold
streaming == buffered byte-for-byte, elastic-timeout drops included).
``RobustCompressedDistAggregator`` composes with the encoded-update uplink:
the decoded fold is lifted to the model domain and clipped exactly like a
dense upload.
"""

from __future__ import annotations

import dataclasses
import logging
import threading

import numpy as np

import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg_distributed import (
    BufferedFedAvgDistAggregator,
    CompressedFedAvgClientManager,
    CompressedFedAvgServerManager,
    FedAvgDistAggregator,
    FedAvgServerManager,
)
from fedml_tpu.algorithms.fold_plane import FoldTask
from fedml_tpu.algorithms.robust import (
    add_weak_dp_noise,
    clip_scale,
    coordinate_median,
    dp_noise_key,
    flat_delta_norm,
    flat_norm_mask,
    krum_select,
    trimmed_mean,
)
from fedml_tpu.obs import metrics as metricslib
from fedml_tpu.obs import trace


@dataclasses.dataclass(frozen=True)
class RobustDistConfig:
    """Wire-path defense pipeline knobs (the distributed counterpart of
    robust.RobustConfig, plus the streaming-specific reservoir bound and
    noise seed)."""

    rule: str = "mean"  # mean | median | trimmed_mean | krum
    norm_bound: float = 0.0  # >0 enables per-upload clipping
    dp_stddev: float = 0.0  # >0 enables seeded weak-DP noise at close
    dp_seed: int = 0  # seeds the noise schedule AND the reservoir rng
    reservoir_k: int = 0  # non-mean rules: keep K uploads (0 = all = exact)
    trim_ratio: float = 0.1
    num_byzantine: int = 1

    def __post_init__(self):
        from fedml_tpu.algorithms.robust import RobustConfig

        if self.rule not in RobustConfig.RULES:
            raise ValueError(
                f"unknown robust rule {self.rule!r} (expected one of "
                f"{RobustConfig.RULES})"
            )
        if self.reservoir_k < 0:
            raise ValueError(f"reservoir_k must be >= 0, got {self.reservoir_k}")

    @property
    def enabled(self) -> bool:
        return self.norm_bound > 0 or self.dp_stddev > 0 or self.rule != "mean"


def _reservoir_rng(config: RobustDistConfig, round_idx: int) -> np.random.RandomState:
    """Per-round seeded reservoir sampler: draws depend only on (seed,
    round, arrival order), so the buffered oracle's arrival-order replay
    reproduces the streaming arm's reservoir exactly."""
    return np.random.RandomState(
        (config.dp_seed * 1_000_003 + round_idx * 7919 + 0x0B57) % (2**31)
    )


class _RobustFoldTask(FoldTask):
    """The mean-rule defended fold through the sharded plane: the whole
    decision phase — delta against the submit-time global, full-vector
    finiteness, the (BN-masked) clip norm and scale — runs once in prepare,
    off the receive thread, with the exact serial expressions of
    ``_defended_fold``; the chunk folds then apply the (possibly clipped)
    vector with the base dense arithmetic. The defense's order-sensitive
    scalars (``norm_sum`` is a float sum) are recorded on the task and
    applied at drain in arrival order, so stats match the serial bits."""

    __slots__ = ("payload", "weight", "base", "config", "norm_mask",
                 "norm", "rejected", "clipped")

    def __init__(self, payload, weight: float, base: np.ndarray,
                 config: RobustDistConfig, norm_mask, acc_elems: int):
        super().__init__(acc_elems)
        self.payload = payload
        self.weight = float(weight)
        self.base = base  # f32 view of the global, captured at submit
        self.config = config
        self.norm_mask = norm_mask
        self.norm = 0.0
        self.rejected = False
        self.clipped = False

    def _dense_f32(self) -> np.ndarray | None:
        return np.ascontiguousarray(self.payload).view(np.float32)

    def _prepare(self):
        x = self._dense_f32()
        if x is None:  # undecodable encoded upload: rejected in finalize
            self.rejected = True
            return None
        cfg = self.config
        with trace.span("robust/fold", rule=cfg.rule):
            base = self.base
            delta = x - base
            with trace.span("robust/clip"):
                full_norm = float(np.linalg.norm(delta))
                if not np.isfinite(full_norm):
                    self.rejected = True
                    return None
                self.norm = (full_norm if self.norm_mask is None
                             else flat_delta_norm(delta, self.norm_mask))
                if cfg.norm_bound > 0:
                    scale = float(clip_scale(jnp.float32(self.norm),
                                             cfg.norm_bound))
                    if scale < 1.0:
                        self.clipped = True
                        x = base + delta * np.float32(scale)
            return x

    def fold_slice(self, acc, lo, hi, prep):
        acc[lo:hi] += np.multiply(prep[lo:hi], self.weight, dtype=np.float64)

    def finalize(self, agg) -> bool:  # lock-held: _lock
        agg._stats["n"] += 1
        if self.rejected:
            agg._stats["rejected"] += 1
            return False
        agg._stats["norm_sum"] += self.norm
        if self.clipped:
            agg._stats["clipped"] += 1
        agg._wsum += self.weight
        return True


class _RobustEncodedFoldTask(_RobustFoldTask):
    """Encoded-uplink variant: the decode (and the delta-domain lift onto
    the submit-time global) joins the prepare phase; an undecodable payload
    is just another hostile upload — rejected, never a crashed round."""

    __slots__ = ("codec",)

    def __init__(self, enc, weight: float, base: np.ndarray,
                 config: RobustDistConfig, norm_mask, codec):
        super().__init__(enc, weight, base, config, norm_mask,
                         base.nbytes // 4)
        self.codec = codec

    def _dense_f32(self) -> np.ndarray | None:
        from fedml_tpu.compress.aggregate import _flat_leaves

        try:
            with trace.span("compress/decode", scheme=self.payload.scheme):
                leaves = _flat_leaves(self.codec.decode(self.payload))
                dense = np.concatenate([l.astype(np.float32) for l in leaves])
        except Exception as e:
            logging.warning("robust fold: undecodable encoded upload "
                            "rejected (%s: %s)", type(e).__name__, e)
            return None
        x = self.base + dense if self.codec.delta_domain else dense
        return np.asarray(x, np.float32)


class RobustDistAggregator(FedAvgDistAggregator):
    """Streaming tally with the defense folded into the arrival path.

    Memory: O(model) for the accumulator plus O(reservoir_k x model) for
    non-mean rules — never O(workers x model). ``get_global`` (wired by the
    server manager) supplies the last broadcast flat model, the clip
    reference."""

    def __init__(self, worker_num: int, config: RobustDistConfig,
                 model_desc: str | None = None):
        super().__init__(worker_num)
        self.config = config
        self.get_global = None  # wired by the server manager (current flat)
        self._norm_mask = flat_norm_mask(model_desc) if model_desc else None
        self._round_counter = 0  # guarded-by: _lock
        self._reservoir: list[np.ndarray] = []  # guarded-by: _lock
        self._res_seen = 0  # guarded-by: _lock
        self._res_rng = _reservoir_rng(config, 0)  # guarded-by: _lock
        self._stats = {"norm_sum": 0.0, "n": 0, "clipped": 0, "rejected": 0}  # guarded-by: _lock
        self._last_record: dict | None = None  # guarded-by: _lock

    # -- defended arrival fold ----------------------------------------------

    def attach_fold_plane(self, plane) -> None:
        """The plane composes with the ``mean`` rule only (two-phase: the
        prepare-side norm/clip decision, then the weighted chunk folds).
        Reservoir rules mutate seeded cross-client sampler state at every
        arrival — inherently serial — so they keep the pre-plane path."""
        if self.config.rule == "mean":
            super().attach_fold_plane(plane)

    def _fold_task(self, payload, weight: float):
        # the clip reference is captured here, under the tally lock — the
        # same global the serial fold would have read at this arrival
        base = np.ascontiguousarray(self.get_global()).view(np.float32)
        return _RobustFoldTask(payload, weight, base, self.config,
                               self._norm_mask,
                               np.asarray(payload).nbytes // 4)

    def _fold(self, payload, sample_num: float) -> None:
        x = np.ascontiguousarray(payload).view(np.float32)
        self._defended_fold(x, sample_num)

    def _defended_fold(self, x: np.ndarray, sample_num: float) -> None:  # lock-held: _lock
        """Clip ``x`` (a flat f32 model vector) against the last broadcast
        global and fold it — into the f64 accumulator (mean rule) and/or the
        reservoir (order-statistic rules). Caller holds the tally lock."""
        cfg = self.config
        with trace.span("robust/fold", rule=cfg.rule):
            self._stats["n"] += 1
            base = np.ascontiguousarray(self.get_global()).view(np.float32)
            delta = x - base
            with trace.span("robust/clip"):
                # finiteness is checked on the FULL delta norm (BN-stat
                # coordinates included — a corrupted coordinate anywhere
                # would poison the accumulator), and runs for every defense
                # config, DP-noise-only included; the clip norm then
                # excludes BN statistics like the sim's
                full_norm = float(np.linalg.norm(delta))
                if not np.isfinite(full_norm):
                    self._stats["rejected"] += 1
                    return
                norm = (full_norm if self._norm_mask is None
                        else flat_delta_norm(delta, self._norm_mask))
                self._stats["norm_sum"] += norm
                if cfg.norm_bound > 0:
                    scale = float(clip_scale(jnp.float32(norm),
                                             cfg.norm_bound))
                    if scale < 1.0:
                        self._stats["clipped"] += 1
                        x = base + delta * np.float32(scale)
            if cfg.rule == "mean":
                super()._fold(x, sample_num)
            else:
                self._reservoir_add(x)

    def _reservoir_add(self, x: np.ndarray) -> None:  # lock-held: _lock
        """Algorithm-R reservoir over the round's (clipped) uploads: every
        upload has equal probability K/seen of being in the close-time
        stack. ``reservoir_k == 0`` keeps everything (the exact rule)."""
        k = self.config.reservoir_k
        self._res_seen += 1
        if k == 0 or len(self._reservoir) < k:
            self._reservoir.append(np.array(x, np.float32))  # own the bytes
        else:
            j = int(self._res_rng.randint(self._res_seen))
            if j < k:
                self._reservoir[j] = np.array(x, np.float32)

    # -- round close ---------------------------------------------------------

    def _finish(self) -> np.ndarray:
        cfg = self.config
        self._fold_epoch += 1
        with trace.span("robust/close", rule=cfg.rule):
            all_rejected = (self._acc is None if cfg.rule == "mean"
                            else not self._reservoir)
            if all_rejected:
                # every upload this round was rejected as non-finite: the
                # defense discards the whole round and keeps the previous
                # global (no noise either — the model must not drift on an
                # all-hostile round)
                logging.warning(
                    "robust round close: every upload rejected (non-finite); "
                    "keeping the previous global model"
                )
                out = np.array(
                    np.ascontiguousarray(self.get_global()).view(np.float32)
                )
                rule_filtered = 0
                self._acc = None
                self._wsum = 0.0
                self._reservoir = []
                self._res_seen = 0
            elif cfg.rule == "mean":
                out = (self._acc / self._wsum).astype(np.float32)
                self._acc = None
                self._wsum = 0.0
                rule_filtered = 0
            else:
                stack = np.stack(self._reservoir)  # [K, D] f32
                out, rule_filtered = self._combine_reservoir(stack)
                self._reservoir = []
                self._res_seen = 0
                self._acc = None
                self._wsum = 0.0
            if cfg.dp_stddev > 0 and not all_rejected:
                key = dp_noise_key(cfg.dp_seed, self._round_counter)
                out = np.asarray(add_weak_dp_noise(
                    {"w": jnp.asarray(out)}, cfg.dp_stddev, key
                )["w"], np.float32)
            self._round_counter += 1
            self._res_rng = _reservoir_rng(cfg, self._round_counter)
            s, self._stats = self._stats, {
                "norm_sum": 0.0, "n": 0, "clipped": 0, "rejected": 0
            }
            # clip statistics average over the uploads that actually folded
            # (rejected non-finite uploads contributed no norm), matching
            # the sim path's real-client denominator
            folded = max(s["n"] - s["rejected"], 1)
            self._last_record = {
                metricslib.ROBUST_UPDATE_NORM: s["norm_sum"] / folded,
                metricslib.ROBUST_CLIP_FRACTION: s["clipped"] / folded,
                metricslib.ROBUST_FILTERED: s["rejected"] + rule_filtered,
            }
            return out.astype(np.float32).view(np.uint8)

    def _combine_reservoir(self, stack: np.ndarray) -> tuple[np.ndarray, int]:  # lock-held: _lock
        """Run the sim's rule functions — the single source of the combine
        arithmetic — over the reservoir stack. Returns (aggregate, number of
        updates the rule discarded).

        An elastic-timeout round can close with fewer survivors than the
        configured rule supports (trimmed_mean with ``C - 2k <= 0``, krum
        with ``num_byzantine > C - 3``); raising here would kill the round
        close on the server's timer/handler thread and wedge the protocol,
        so the close degrades to the coordinate median for THAT round — the
        strictest rule defined for any survivor count — with a warning.
        The same survivor count produces the same fallback in both arms, so
        streaming == buffered is unaffected."""
        cfg, k = self.config, len(stack)
        rule = cfg.rule
        if rule == "trimmed_mean" and k - 2 * int(cfg.trim_ratio * k) <= 0:
            logging.warning(
                "robust close: %d survivors cannot support trimmed_mean"
                "(trim_ratio=%s); using the coordinate median this round",
                k, cfg.trim_ratio,
            )
            rule = "median"
        if rule == "krum" and cfg.num_byzantine > k - 3:
            logging.warning(
                "robust close: %d survivors cannot support krum"
                "(num_byzantine=%d); using the coordinate median this round",
                k, cfg.num_byzantine,
            )
            rule = "median"
        if rule == "median":
            out = np.asarray(
                coordinate_median({"w": jnp.asarray(stack)})["w"], np.float32
            )
            return out, k - 1
        if rule == "trimmed_mean":
            out = np.asarray(
                trimmed_mean({"w": jnp.asarray(stack)}, cfg.trim_ratio)["w"],
                np.float32,
            )
            return out, 2 * int(cfg.trim_ratio * k)
        # krum: score distances over non-BN coordinates, return the winner
        kstack = stack if self._norm_mask is None else stack[:, self._norm_mask]
        idx = int(krum_select({"w": jnp.asarray(kstack)}, cfg.num_byzantine))
        return stack[idx], k - 1

    # -- crash-recovery snapshot ---------------------------------------------

    def snapshot_state(self) -> dict:
        """Base tally snapshot plus the defense's round schedule: the noise
        -key round counter (a restarted server must NOT replay round k's
        noise for round k+1) and the reservoir (empty at round close, when
        the server checkpoints; carried anyway). Called at round close
        under the server's round lock — no concurrent folds."""
        out = super().snapshot_state()
        # the base released _lock after its snapshot; re-acquire for the
        # defense fields (fedlint guarded-by: a fold racing this snapshot
        # must never read a half-written reservoir)
        with self._lock:
            out["robust_round"] = int(self._round_counter)
            out["res_seen"] = int(self._res_seen)
            if self._reservoir:
                out["reservoir"] = np.stack(self._reservoir)
        return out

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        with self._lock:
            self._round_counter = int(state.get("robust_round", 0))
            self._res_seen = int(state.get("res_seen", 0))
            res = state.get("reservoir")
            self._reservoir = (
                [np.array(r, np.float32) for r in res]
                if res is not None else []
            )
            # round-close rng state is exactly "fresh for the current round
            # counter" — the same state _finish() leaves behind
            self._res_rng = _reservoir_rng(self.config, self._round_counter)

    def pop_round_stats(self) -> dict | None:
        """The closed round's Robust/* record (None when no round closed
        since the last pop) — the server manager flushes it into the
        metrics stream."""
        with self._lock:
            rec, self._last_record = self._last_record, None
            return rec


class BufferedRobustDistAggregator(BufferedFedAvgDistAggregator,
                                   RobustDistAggregator):
    """Bit-exactness oracle: retains every upload and replays the SAME
    defended fold in arrival order at round close (same clip reference —
    the global is only replaced after ``aggregate()`` — same reservoir
    draws, same noise key), so streaming == buffered byte-for-byte under
    any schedule, dropped stragglers included."""

    def __init__(self, worker_num: int, config: RobustDistConfig,
                 model_desc: str | None = None):
        RobustDistAggregator.__init__(self, worker_num, config, model_desc)
        self.model_dict = {}


class RobustCompressedDistAggregator(RobustDistAggregator):
    """Robust streaming tally for encoded uploads: decode the client's
    EncodedUpdate to ONE transient dense vector, lift delta-domain codecs
    onto the current global, then clip-and-fold exactly like a dense
    upload ("clip the decoded fold"). Still O(model) host memory — one
    transient decode at a time, never per-worker retention."""

    def __init__(self, worker_num: int, config: RobustDistConfig, codec,
                 model_desc: str | None = None):
        super().__init__(worker_num, config, model_desc)
        self.codec = codec

    def _fold_task(self, payload, weight: float):
        base = np.ascontiguousarray(self.get_global()).view(np.float32)
        return _RobustEncodedFoldTask(payload, weight, base, self.config,
                                      self._norm_mask, self.codec)

    def _fold(self, payload, sample_num: float) -> None:
        from fedml_tpu.compress.aggregate import _flat_leaves

        try:
            with trace.span("compress/decode", scheme=payload.scheme):
                leaves = _flat_leaves(self.codec.decode(payload))
                dense = np.concatenate([l.astype(np.float32) for l in leaves])
        except Exception as e:
            # a bit-corrupted encoded payload can be structurally
            # undecodable (e.g. flipped top-k indices out of range) — for
            # the robust tally that is just another hostile upload: reject
            # it instead of killing the server's receive thread
            logging.warning("robust fold: undecodable encoded upload "
                            "rejected (%s: %s)", type(e).__name__, e)
            self._stats["n"] += 1
            self._stats["rejected"] += 1
            return
        if self.codec.delta_domain:
            base = np.ascontiguousarray(self.get_global()).view(np.float32)
            x = base + dense
        else:
            x = dense
        self._defended_fold(np.asarray(x, np.float32), sample_num)


class BufferedRobustCompressedDistAggregator(BufferedFedAvgDistAggregator,
                                             RobustCompressedDistAggregator):
    """Arrival-order replay oracle for the robust compressed tally."""

    def __init__(self, worker_num: int, config: RobustDistConfig, codec,
                 model_desc: str | None = None):
        RobustCompressedDistAggregator.__init__(
            self, worker_num, config, codec, model_desc
        )
        self.model_dict = {}


class _RobustServerMixin:
    """Shared server-manager wiring: swap in the robust tally and flush its
    Robust/* record per closed round (mirrors comm_stats)."""

    def _hoist_robust(self, robust_config: RobustDistConfig | None) -> None:
        """Validate + stash the defense config. Runs BEFORE super().__init__
        — the base's single ``_make_aggregator()`` call reads it (the
        factory seam, ROADMAP item 1)."""
        if robust_config is None:
            raise ValueError(f"{type(self).__name__} needs a robust_config")
        self.robust_config = robust_config

    def _init_robust(self, robust_stats: dict | None) -> None:
        self._robust_stats = robust_stats
        self.aggregator.get_global = lambda: self.global_flat
        # flush the closed round's Robust/* record BEFORE the caller's
        # round callback fires (same ordering contract as the compressed
        # server's comm_stats flush): a callback merging per-round metrics
        # by round index must find round r already recorded
        inner_cb = self.on_round_done

        def _flush_then(round_idx: int, flat) -> None:
            rec = self.aggregator.pop_round_stats()
            if rec is not None:
                rec = {"round": round_idx, **rec}
                logging.info("robust defense: %s", rec)
                if self._robust_stats is not None:
                    self._robust_stats.setdefault("rounds", []).append(rec)
            if inner_cb is not None:
                inner_cb(round_idx, flat)

        self.on_round_done = _flush_then


class RobustFedAvgServerManager(_RobustServerMixin, FedAvgServerManager):
    """FedAvg server with the streaming robust tally (dense uplink)."""

    def __init__(self, *args, robust_config: RobustDistConfig | None = None,
                 robust_stats: dict | None = None, **kwargs):
        self._hoist_robust(robust_config)
        super().__init__(*args, **kwargs)
        self._init_robust(robust_stats)

    def _make_aggregator(self):
        return (
            BufferedRobustDistAggregator if self.buffered_aggregation
            else RobustDistAggregator
        )(self.worker_num, self.robust_config, model_desc=self.model_desc)


class RobustCompressedFedAvgServerManager(_RobustServerMixin,
                                          CompressedFedAvgServerManager):
    """FedAvg server composing the encoded-update uplink with the robust
    tally: decode → clip → fold, bytes-on-wire accounting unchanged."""

    def __init__(self, *args, robust_config: RobustDistConfig | None = None,
                 robust_stats: dict | None = None, **kwargs):
        self._hoist_robust(robust_config)
        super().__init__(*args, **kwargs)
        self._init_robust(robust_stats)

    def _make_aggregator(self):
        # get_global is wired by _init_robust (the mixin tail shared by
        # every robust arm), not here
        return (
            BufferedRobustCompressedDistAggregator if self.buffered_aggregation
            else RobustCompressedDistAggregator
        )(self.worker_num, self.robust_config, self.codec,
          model_desc=self.model_desc)


# ---------------------------------------------------------------------------
# Loopback attack simulation: poison -> distributed rounds -> ASR
# ---------------------------------------------------------------------------


def eval_accuracy(trainer, variables, arrays: dict, batch_size: int = 64) -> float:
    """Pooled accuracy of ``variables`` on ``arrays`` ({"x","y"}) — used for
    clean accuracy and, on a triggered test set (data/poison.py
    ``backdoor_test_arrays``), the attack success rate."""
    import jax

    from fedml_tpu.sim.cohort import batch_array

    batches = batch_array(arrays, batch_size)
    correct = total = 0.0
    for i in range(len(next(iter(batches.values())))):
        b = {k: jnp.asarray(v[i]) for k, v in batches.items()}
        m = trainer.eval_batch(variables, b)
        correct += float(m["test_correct"])
        total += float(m["test_total"])
    return correct / max(total, 1.0)


def run_attack_simulation(
    trainer,
    train_data,
    test_arrays: dict,
    worker_num: int,
    round_num: int,
    batch_size: int,
    defense: RobustDistConfig,
    compromised_frac: float = 0.5,
    sample_frac: float = 1.0,
    target_label: int = 0,
    trigger=None,
    poison_seed: int = 0,
    fault_specs=None,
    buffered_aggregation: bool = False,
    round_timeout: float | None = None,
    seed: int = 0,
) -> dict:
    """End-to-end loopback attack/defense A-B: poison a client fraction
    (data/poison.py), run the real message-passing FedAvg protocol with the
    defense ON and OFF (optionally through the fault-injection wrapper,
    comm/faults.py), and report the backdoor attack success rate plus clean
    accuracy for both arms. The reference's main_fedavg_robust attack loop,
    driven over the wire path instead of buffered stacks."""
    from fedml_tpu.algorithms.fedavg_distributed import run_distributed_fedavg_loopback
    from fedml_tpu.data.poison import Trigger, backdoor_test_arrays, poison_clients

    trigger = trigger or Trigger()
    poisoned, bad, counts = poison_clients(
        train_data, compromised_frac=compromised_frac, sample_frac=sample_frac,
        target_label=target_label, trigger=trigger, seed=poison_seed,
    )
    backdoor = backdoor_test_arrays(test_arrays, target_label=target_label,
                                    trigger=trigger)

    def arm(robust_config):
        stats: dict = {}
        final = run_distributed_fedavg_loopback(
            trainer, poisoned, worker_num=worker_num, round_num=round_num,
            batch_size=batch_size, seed=seed,
            robust_config=robust_config,
            robust_stats=stats if robust_config else None,
            fault_specs=fault_specs,
            round_timeout=round_timeout,
            server_kwargs={"buffered_aggregation": buffered_aggregation},
        )
        return {
            "asr": eval_accuracy(trainer, final, backdoor),
            "clean_acc": eval_accuracy(trainer, final, test_arrays),
            "robust_rounds": stats.get("rounds", []),
        }

    on, off = arm(defense), arm(None)
    result = {
        "compromised_clients": [int(c) for c in bad],
        "poisoned_counts": counts,
        "asr_defended": on["asr"],
        "asr_undefended": off["asr"],
        "clean_acc_defended": on["clean_acc"],
        "clean_acc_undefended": off["clean_acc"],
        "robust_rounds": on["robust_rounds"],
    }
    logging.info(
        "attack simulation: ASR %.3f defended vs %.3f undefended "
        "(clean acc %.3f vs %.3f)",
        result["asr_defended"], result["asr_undefended"],
        result["clean_acc_defended"], result["clean_acc_undefended"],
    )
    return result
