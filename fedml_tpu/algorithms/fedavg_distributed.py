"""Distributed FedAvg over the message-passing comm layer.

Reference: the canonical 6-file package fedml_api/distributed/fedavg/ —
message_define.py:6-9 (S2C_INIT_CONFIG=1, S2C_SYNC_MODEL=2, C2S_SEND_MODEL=3),
FedAvgServerManager.py:18-82 (round loop in the receive handler),
FedAvgClientManager.py:18-72, FedAVGAggregator.py:13-164.

This is the *real-distributed* path: server and clients are separate
processes/threads exchanging typed array messages (loopback for tests, shm
for single-host multiprocess, grpc across hosts). The vectorized single-
program engine (sim/engine.py) remains the fast path for simulation; this
path exists for capability parity and true cross-silo deployments where
clients own their data.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message, pack_pytree, unpack_pytree
from fedml_tpu.comm.send_pool import BroadcastSendError
from fedml_tpu.core import rng as rnglib
from fedml_tpu.core.trainer import ClientTrainer, make_local_train
from fedml_tpu.algorithms.fold_plane import DenseFoldTask, FoldPlane, FoldTask
from fedml_tpu.obs import jobscope, registry
from fedml_tpu.obs import metrics as metricslib
from fedml_tpu.obs import trace
from fedml_tpu.sim.cohort import FederatedArrays, stack_cohort


class MyMessage:
    """Message types (reference message_define.py:6-9)."""

    MSG_TYPE_S2C_INIT_CONFIG = 1
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3

    MSG_ARG_KEY_MODEL_PARAMS = Message.MSG_ARG_KEY_MODEL_PARAMS
    MSG_ARG_KEY_MODEL_DESC = Message.MSG_ARG_KEY_MODEL_DESC
    MSG_ARG_KEY_NUM_SAMPLES = Message.MSG_ARG_KEY_NUM_SAMPLES
    MSG_ARG_KEY_CLIENT_INDEX = Message.MSG_ARG_KEY_CLIENT_INDEX
    MSG_ARG_KEY_ROUND_IDX = Message.MSG_ARG_KEY_ROUND_IDX


# canonical definition moved to the light shared layer (algorithms/base.py)
# so the sim engine raises the SAME class on population-churn-empty rounds;
# re-exported here — every existing `from ...fedavg_distributed import
# EmptyRoundError` site keeps working
from fedml_tpu.algorithms.base import EmptyRoundError  # noqa: E402,F401


class FedAvgDistAggregator:
    """Server-side round tally, streaming (accumulate-on-arrival).

    The reference (FedAVGAggregator.py:13-108) buffers every worker's model
    until round end and sums on one thread — O(workers x model) peak host
    memory, with all the summation work serialized at round close. Here each
    upload is folded into ONE f64 accumulator as it lands
    (``acc += n_i * x_i``, ``wsum += n_i``) and ``aggregate()`` divides at
    round close: peak memory is O(model) and the adds amortize over the
    receive timeline. Elastic-timeout renormalization is unchanged — the
    divisor is the weight sum over whoever actually uploaded, so dropped
    stragglers renormalize away.

    Folds happen in arrival order (f64 addition is not associative, so two
    runs with different arrival orders can differ in the accumulator's last
    ULPs — the standard streaming-aggregation tradeoff).
    :class:`BufferedFedAvgDistAggregator` keeps the legacy retain-then-sum
    shape but replays the SAME fold arithmetic in the same arrival order, so
    streaming == buffered bit-for-bit under any schedule
    (tools/wire_smoke.py + tests/test_wire_path.py hold the contract)."""

    def __init__(self, worker_num: int):
        self.worker_num = worker_num
        self.sample_num_dict: dict[int, float] = {}  # guarded-by: _lock
        self.flag_client_model_uploaded_dict = {i: False for i in range(worker_num)}  # guarded-by: _lock
        self._lock = threading.Lock()  # reference hazard fixed (SURVEY §5.2)
        self._acc: np.ndarray | None = None  # guarded-by: _lock
        self._wsum = 0.0  # guarded-by: _lock
        # workers dropped via exclude_worker
        self._excluded: list[int] = []  # guarded-by: _lock
        # sharded fold plane (algorithms/fold_plane.py): None = serial fold
        # on the receive thread, exactly the pre-plane behavior
        self._plane: FoldPlane | None = None
        self._pending_finalize: list[FoldTask] = []  # guarded-by: _lock
        # bumped on every tally mutation (fold submit/apply, finish,
        # restore) — the torn-copy detector for the outside-the-lock
        # snapshot copy (snapshot_state retries while it moves)
        self._fold_epoch = 0  # guarded-by: _lock
        # the plane creates the accumulator at submit time (workers need a
        # target before the first fold lands); if NO submitted task ends up
        # contributing vector mass (a robust all-rejected window) the drain
        # nulls it again so `_acc is None` keeps meaning "empty tally"
        self._acc_provisional = False  # guarded-by: _lock

    def exclude_worker(self, index: int) -> None:
        """Stop expecting this worker (marked OFFLINE): later rounds
        complete on the live set alone instead of re-waiting for the
        timeout every round. Only workers that have NOT uploaded this round
        can be excluded — a streaming tally cannot retract a folded
        contribution (the timeout path only ever excludes missing workers).
        No longer a life sentence: :meth:`readmit_worker` reverses it when
        the worker reappears."""
        with self._lock:
            if self.flag_client_model_uploaded_dict.get(index):
                raise ValueError(
                    f"worker {index} already uploaded this round; a streaming "
                    "tally cannot retract a folded contribution"
                )
            if self.flag_client_model_uploaded_dict.pop(index, None) is not None:
                self._excluded.append(index)
            self.sample_num_dict.pop(index, None)

    def readmit_worker(self, index: int) -> None:
        """Inverse of :meth:`exclude_worker`, applied at a ROUND BOUNDARY
        (the server defers readmission to round close — a mid-round
        readmit would stall the all-received barrier until the returnee
        uploads): the worker re-enters the expected set for later rounds."""
        with self._lock:
            if index in self.flag_client_model_uploaded_dict:
                return  # already live
            self.flag_client_model_uploaded_dict[index] = False
            if index in self._excluded:
                self._excluded.remove(index)

    def excluded_workers(self) -> list[int]:
        with self._lock:
            return sorted(self._excluded)

    def _empty_round_error(self) -> "EmptyRoundError":  # lock-held: _lock
        """Diagnosable all-dropped-round error naming WHICH ranks were
        missing and which were already OFFLINE-excluded (caller holds the
        lock) — an all-dropped round must be debuggable from the log
        alone."""
        flags = self.flag_client_model_uploaded_dict
        msg = (
            "no worker uploads this round: all "
            f"{len(flags)} live workers (ranks "
            f"{sorted(i + 1 for i in flags)}) were dropped by the round "
            "timeout"
        )
        if self._excluded:
            msg += (f"; ranks {sorted(i + 1 for i in self._excluded)} "
                    "already excluded as OFFLINE")
        msg += ("; keeping the previous global model — nothing to "
                "aggregate")
        return EmptyRoundError(msg)

    # -- crash-recovery snapshot (docs/ROBUSTNESS.md "Failure recovery") -----

    def snapshot_state(self) -> dict:
        """Round-close tally snapshot for the server checkpoint: np.ndarray
        values plus JSON-safe scalars (obs.checkpoint.RoundCheckpointer.
        save_server splits them). Saved at round close, when the streaming
        accumulator is empty; mid-round acc/wsum are included anyway so a
        future mid-round snapshotter inherits them for free.

        The full-model accumulator copy happens OUTSIDE the lock (the PR 8
        checkpoint-write-outside-lock discipline — a checkpoint must not
        stall arriving folds): grab the reference and the fold epoch under
        the lock, copy unlocked, and retry if the epoch moved (a fold
        landed mid-copy — serial or from a plane worker — so the copy may
        be torn)."""
        while True:
            with self._lock:
                self._drain_locked()
                epoch = self._fold_epoch
                acc_ref = self._acc
                out: dict = {
                    "wsum": float(self._wsum),
                    "live": sorted(self.flag_client_model_uploaded_dict),
                    "uploaded": sorted(
                        i for i, f in
                        self.flag_client_model_uploaded_dict.items() if f
                    ),
                    "excluded": sorted(self._excluded),
                    "sample_num": {str(i): float(v)
                                   for i, v in self.sample_num_dict.items()},
                }
            acc_copy = None if acc_ref is None else np.array(acc_ref)
            with self._lock:
                if self._fold_epoch != epoch:
                    continue  # a fold landed mid-copy; re-snapshot
                if acc_copy is not None:
                    out["acc"] = acc_copy
                return out

    def restore_state(self, state: dict) -> None:
        with self._lock:
            # retire any in-flight folds against the PRE-restore tally
            # first: their target array and scalar bookkeeping are both
            # replaced wholesale below, exactly as a serial restore
            # overwrites folds that already landed
            self._drain_locked()
            self._fold_epoch += 1
            self._acc_provisional = False
            self._wsum = float(state.get("wsum", 0.0))
            acc = state.get("acc")
            self._acc = None if acc is None else np.asarray(acc, np.float64)
            live = state.get("live")
            if live is not None:
                uploaded = {int(i) for i in state.get("uploaded", [])}
                self.flag_client_model_uploaded_dict = {
                    int(i): int(i) in uploaded for i in live
                }
            self._excluded = [int(i) for i in state.get("excluded", [])]
            self.sample_num_dict = {
                int(i): float(v)
                for i, v in state.get("sample_num", {}).items()
            }

    def live_workers(self) -> list[int]:
        with self._lock:
            return sorted(self.flag_client_model_uploaded_dict)

    def is_live(self, index: int) -> bool:
        with self._lock:
            return index in self.flag_client_model_uploaded_dict

    # -- sharded fold plane seam (algorithms/fold_plane.py) ------------------

    def attach_fold_plane(self, plane: FoldPlane) -> None:
        """Arm the chunk-parallel fold plane: subsequent arrivals that have
        a task form (:meth:`_fold_task`) enqueue to the plane's workers
        instead of folding on the receive thread. Aggregator families whose
        fold is not chunkable (a non-mean robust rule) override this to a
        no-op and keep the serial path."""
        self._plane = plane

    def close_fold_plane(self) -> None:
        """Shut the plane's workers down (idempotent; serial-mode no-op)."""
        if self._plane is not None:
            self._plane.close()

    def _fold_task(self, payload, weight: float) -> FoldTask | None:
        """The family-specific task form of one arrival, or None when this
        payload must fold serially (caller holds the lock)."""
        return DenseFoldTask(payload, weight)

    def _fold_arrival(self, payload, weight: float) -> None:  # lock-held: _lock
        """Arrival-order fold dispatch: serial ``_fold`` when the plane is
        off (or the payload has no task form — the queues drain first so a
        mixed schedule stays in arrival order), task submit when it is on.
        Caller holds ``_lock``, so plane sequence order IS arrival order."""
        self._fold_epoch += 1
        task = self._fold_task(payload, weight) if self._plane is not None else None
        if task is None:
            self._drain_locked()
            self._fold(payload, weight)
            return
        if self._acc is None:
            self._acc = np.zeros(task.acc_elems, np.float64)
            self._acc_provisional = True
            task.first = True
        self._pending_finalize.append(task)
        self._plane.submit(task, self._acc)

    def _drain_locked(self) -> None:  # lock-held: _lock
        """Quiesce the plane before any read of the tally: help-fold
        whatever is still queued (wait-free — see FoldPlane.drain), then
        run each task's scalar finalize in arrival order so order-sensitive
        float sums (weight totals, defense stats) reproduce the serial
        bits. Every tally reader (aggregate / snapshot / restore / emit /
        export) calls this first."""
        if self._plane is None or not self._pending_finalize:
            return
        t0 = time.perf_counter()
        with trace.span("fold/drain", pending=len(self._pending_finalize)):
            self._plane.drain()
            pending, self._pending_finalize = self._pending_finalize, []
            folded = False
            for task in pending:
                folded = bool(task.finalize(self)) or folded
            if self._acc_provisional:
                self._acc_provisional = False
                if not folded:
                    self._acc = None
        registry.observe(metricslib.FOLD_STALL_MS,
                         (time.perf_counter() - t0) * 1000.0)

    def _fold(self, payload, sample_num: float) -> None:  # lock-held: _lock
        """Fold one upload into the running tally (caller holds the lock).
        Payloads are pack_pytree byte vectors; model leaves are float32
        (validated against the descriptor at server init), so the weighted
        accumulation runs on an f32 view."""
        self._fold_epoch += 1
        x = np.ascontiguousarray(payload).view(np.float32)
        if self._acc is None:
            self._acc = np.zeros(x.size, np.float64)
        self._acc += np.multiply(x, float(sample_num), dtype=np.float64)
        self._wsum += float(sample_num)

    def _finish(self) -> np.ndarray:  # lock-held: _lock
        """Close the tally (caller holds the lock): divide by the weight sum
        and return wire bytes."""
        self._fold_epoch += 1
        out = (self._acc / self._wsum).astype(np.float32).view(np.uint8)
        self._acc = None
        self._wsum = 0.0
        return out

    def add_local_trained_result(self, index: int, flat_params: np.ndarray, sample_num: float) -> bool:
        with self._lock:
            flags = self.flag_client_model_uploaded_dict
            if index not in flags:
                return False  # excluded (OFFLINE) worker resurfaced; ignore
            if flags[index]:
                # duplicate upload within one round: first wins (a streaming
                # tally cannot replace a folded contribution; the protocol's
                # round-idx guard keeps this unreachable in practice)
                return all(flags.values())
            self._fold_arrival(flat_params, sample_num)
            self.sample_num_dict[index] = sample_num
            flags[index] = True
            return all(flags.values())

    def received_workers(self) -> list[int]:
        with self._lock:
            return [i for i, f in self.flag_client_model_uploaded_dict.items() if f]

    def aggregate(self) -> np.ndarray:
        # Closes over whichever workers uploaded this round (all of them in
        # the synchronous case; the survivors when the elastic round timeout
        # dropped stragglers) with weights renormalized over that subset.
        with self._lock:
            self._drain_locked()
            flags = self.flag_client_model_uploaded_dict
            if not any(flags.values()):
                raise self._empty_round_error()
            out = self._finish()
            for i in flags:
                flags[i] = False
            return out


class BufferedFedAvgDistAggregator(FedAvgDistAggregator):
    """Legacy-shaped tally (the reference's FedAVGAggregator memory
    profile): retains every worker's payload and folds them at round close —
    in arrival order, through the SAME ``_fold``/``_finish`` arithmetic as
    the streaming base, so the two are bit-identical under any schedule.
    Kept as the A/B reference for the streaming path (``buffered_
    aggregation=True`` on the server manager; tools/wire_smoke.py)."""

    def __init__(self, worker_num: int):
        super().__init__(worker_num)
        # insertion == arrival
        self.model_dict: dict[int, np.ndarray] = {}  # guarded-by: _lock

    def attach_fold_plane(self, plane) -> None:
        """No-op: the buffered A/B arm replays at round close by contract
        (its whole point is the legacy retain-then-sum shape), so there is
        nothing to move off the receive thread."""

    def add_local_trained_result(self, index: int, flat_params: np.ndarray, sample_num: float) -> bool:
        with self._lock:
            flags = self.flag_client_model_uploaded_dict
            if index not in flags:
                return False
            if flags[index]:
                return all(flags.values())
            self.model_dict[index] = flat_params
            self.sample_num_dict[index] = sample_num
            flags[index] = True
            return all(flags.values())

    def aggregate(self) -> np.ndarray:
        with self._lock:
            if not self.model_dict:
                raise self._empty_round_error()
            flags = self.flag_client_model_uploaded_dict
            for i, payload in self.model_dict.items():
                self._fold(payload, self.sample_num_dict[i])
            self.model_dict.clear()
            out = self._finish()
            for i in flags:
                flags[i] = False
            return out


class FedAvgServerManager(ServerManager):
    """Round protocol (FedAvgServerManager.py:31-82)."""

    def __init__(self, comm: BaseCommunicationManager, worker_num: int, round_num: int,
                 init_flat: np.ndarray, model_desc: str,
                 client_num_in_total: int | None = None,
                 round_timeout: float | None = None,
                 exclude_after: int = 2,
                 on_round_done: Callable[[int, np.ndarray], None] | None = None,
                 use_broadcast: bool = True,
                 buffered_aggregation: bool = False,
                 heartbeat_timeout: float | None = None,
                 readmission: bool = False,
                 checkpointer=None,
                 checkpoint_every: int = 1,
                 fleet=None,
                 downlink_codec=None,
                 downlink_keyframe_every: int = 8,
                 downlink_retention: int = 4,
                 fold_workers: int = 0,
                 fold_chunk: int | None = None):
        super().__init__(comm, rank=0, size=worker_num + 1)
        # sharded fold plane (algorithms/fold_plane.py, docs/PERFORMANCE.md
        # "The server fold plane"): fold_workers > 0 moves upload folding
        # off the receive thread onto that many chunk workers, bit-identical
        # to the serial fold; 0 (default) keeps the pre-plane serial path
        self.fold_workers = int(fold_workers)
        self.fold_chunk = fold_chunk
        self.worker_num = worker_num
        self.round_num = round_num
        self.round_idx = 0
        # wire-path knobs (docs/PERFORMANCE.md "The server wire path"):
        # use_broadcast=False reverts downlink to the legacy per-rank send
        # loop; buffered_aggregation=True reverts the tally to the legacy
        # retain-then-sum shape — both kept as the A/B reference arms
        self.use_broadcast = bool(use_broadcast)
        self.buffered_aggregation = bool(buffered_aggregation)
        self.global_flat = init_flat
        self.model_desc = model_desc
        # elastic rounds (SURVEY §5.4 failure handling): if set, a round
        # closes round_timeout seconds after its first upload even when
        # stragglers are missing — their weight is renormalized away and
        # they are marked OFFLINE in ``status`` (reference behavior: a dead
        # client hangs the round forever, mpi com_manager has no recovery)
        self.round_timeout = round_timeout
        # a worker missing this many CONSECUTIVE timed-out rounds is
        # excluded (single misses — e.g. round-0 compile skew — only drop
        # it from that round's aggregate); with readmission enabled an
        # excluded worker that re-contacts the server rejoins later cohorts
        self.exclude_after = exclude_after
        self._miss_counts: dict[int, int] = {}  # guarded-by: _round_lock
        # liveness plane (docs/ROBUSTNESS.md "Failure recovery"): a worker
        # missing at the round timeout but heard from (heartbeat/status)
        # within heartbeat_timeout seconds is SLOW — alive, dropped from
        # this round, but not marched toward exclusion. readmission=True
        # additionally parks excluded workers instead of telling them to
        # stop, and re-enters them into later cohorts on contact.
        self.heartbeat_timeout = heartbeat_timeout
        self.readmission = bool(readmission)
        self._pending_readmit: set[int] = set()  # guarded-by: _round_lock
        # crash recovery: a RoundCheckpointer (obs/checkpoint.py) given
        # here snapshots the full server round state every
        # checkpoint_every closes; restore_from_checkpoint() resumes
        self.checkpointer = checkpointer
        self.checkpoint_every = max(1, int(checkpoint_every))
        from fedml_tpu.comm.status import ClientStatusTracker

        self.status = ClientStatusTracker(worker_num)
        # fleet telemetry plane (obs/registry.py FleetHealth, docs/
        # OBSERVABILITY.md "Fleet telemetry"): per-rank health records the
        # server maintains next to the protocol state — None (the default)
        # keeps every hook a single attribute check. Status-tracker
        # transitions (ONLINE/SLOW/OFFLINE) land on the rank's timeline.
        self.fleet = fleet
        if fleet is not None:
            self.status.on_transition = fleet.record_state
        self._round_timer: "threading.Timer | None" = None  # guarded-by: _round_lock
        self._round_lock = threading.Lock()
        import json

        non_f32 = [d["path"] for d in json.loads(model_desc) if d["dtype"] != "float32"]
        if non_f32:
            raise ValueError(
                f"flat-vector aggregation requires float32 model leaves; got {non_f32}"
            )
        self.client_num_in_total = client_num_in_total or worker_num
        self.on_round_done = on_round_done
        # stale-round uploads from live workers (a straggler's model from an
        # already-closed round) are discarded by the sync protocol — counted
        # here so the loss is visible (Comm/StaleUploads in comm_stats
        # totals; the async server folds them weighted instead)
        self.stale_uploads = 0  # guarded-by: _round_lock
        # downlink delta coding (compress/downlink.py, docs/COMPRESSION.md
        # "Downlink delta coding"): when armed, every round close encodes
        # the new global ONCE as a delta against the previous emitted
        # version, the global of record becomes the DECODED model (so
        # quantization error never accumulates), and fan-outs serve each
        # rank by the version it last echoed — one-step delta, cumulative
        # chain, or periodic keyframe. None (default) keeps the dense
        # broadcast bit-identical to the pre-downlink protocol.
        self.downlink = None
        # rank -> newest model version the rank PROVABLY holds (its upload
        # echo; monotonic) — downlink legs can fail, so only the echo is
        # trusted as the delta base
        self._held_versions: dict[int, int] = {}  # guarded-by: _round_lock
        # cumulative encoded downlink bytes actually sent per rank (fleet
        # telemetry gauge; tools/fleet_report.py renders it)
        self._downlink_sent: dict[int, int] = {}  # guarded-by: _round_lock
        if downlink_codec is not None:
            from fedml_tpu.compress.downlink import DownlinkCodecState

            self.downlink = DownlinkCodecState(
                downlink_codec, model_desc,
                keyframe_every=downlink_keyframe_every,
                retention=downlink_retention,
            )
            self.global_flat = self.downlink.reset(init_flat, self.round_idx)
        # bytes-on-wire ledger: armed by the downlink plane here, by the
        # encoded-uplink subclass via its _make_accountant override — the
        # same factory discipline as _make_aggregator
        self.accountant = self._make_accountant()
        # the ONE aggregator construction (fedlint: overwrite-after-super;
        # ROADMAP item 1's factory seam): subclasses override
        # _make_aggregator and hoist whatever config it reads (codec,
        # robust_config) ABOVE their super().__init__ call — the diamond
        # composes by overriding the factory, never by reassigning the
        # already-built tally; the fold plane attaches at the same seam so
        # every variant of the diamond gets it without per-class wiring
        self.aggregator = self._attach_fold_plane(self._make_aggregator())

    def _make_aggregator(self):
        """Build this server's round tally. Called exactly once, at the end
        of the base ``__init__`` (after ``worker_num``/``model_desc``/
        ``global_flat`` are set); every protocol variant overrides this
        instead of construct-then-overwriting ``self.aggregator``."""
        return (
            BufferedFedAvgDistAggregator if self.buffered_aggregation
            else FedAvgDistAggregator
        )(self.worker_num)

    def _attach_fold_plane(self, agg):
        """Arm the sharded fold plane on the freshly-built tally when
        ``fold_workers > 0`` (pass-through otherwise). Runs at the ONE
        construction call site, so every ``_make_aggregator`` override in
        the diamond inherits it; families that cannot chunk their fold
        (buffered replay, non-mean robust rules) no-op their
        ``attach_fold_plane`` and stay serial."""
        if self.fold_workers > 0:
            kwargs = {}
            if self.fold_chunk is not None:
                kwargs["chunk_elems"] = int(self.fold_chunk)
            agg.attach_fold_plane(FoldPlane(self.fold_workers, **kwargs))
        return agg

    def finish(self) -> None:
        self.aggregator.close_fold_plane()
        super().finish()

    def _make_accountant(self):
        """Build the bytes-on-wire ledger (or None when nothing encodes).
        Called exactly once at base init; the encoded-uplink subclass
        overrides it to always account."""
        if self.downlink is None:
            return None
        from fedml_tpu.obs.metrics import CommBytesAccountant

        return CommBytesAccountant()

    def _model_payload(self, rank: int):
        """Model payload for ``rank`` — the wire-format seam. Base sends the
        packed flat byte vector; the mobile server (fedavg_mobile.py) sends
        the reference's nested-list JSON to its ``is_mobile`` ranks."""
        return self.global_flat

    def _round_cohort(self):
        """Client-index assignment for the current round's downlink: worker
        rank w trains as client ``cohort[w - 1]``. The tree-root server
        (async_agg/tree.py) returns None — its direct receivers are edge
        aggregators, and the leaf tiers derive the same assignment from the
        shared ``rnglib.sample_clients`` schedule themselves."""
        return rnglib.sample_clients(self.round_idx, self.client_num_in_total,
                                     self.worker_num)

    def _sync_extra_params(self) -> dict:
        """Extra header params stamped on every downlink sync — the async
        server adds the explicit global-model version here (clients train
        against a version, not a sync count). Header-only scalars: they ride
        the per-receiver head, never the shared payload frame. The downlink
        delta plane needs the same stamp on the SYNC protocol too — clients
        echo it, and the echo is the only trusted delta base."""
        if self.downlink is not None:
            return {Message.MSG_ARG_KEY_MODEL_VERSION: self.round_idx}
        return {}

    def _note_version_echo(self, sender: int, msg: Message) -> None:  # lock-held: _round_lock
        """Record the model version a rank echoed on its upload — monotonic,
        and noted for EVERY upload (stale and duplicate included: the echo
        proves possession regardless of what the tally does with the
        payload). The delta fan-out serves each rank from this base."""
        if self.downlink is None:
            return
        v = msg.get(Message.MSG_ARG_KEY_MODEL_VERSION)
        if v is None:
            return
        prev = self._held_versions.get(sender)
        if prev is None or int(v) > prev:
            self._held_versions[sender] = int(v)

    def _decode_upload(self, msg: Message) -> np.ndarray:
        """Inverse seam: a client upload back to the flat byte vector."""
        return np.asarray(msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS))

    def _fanout_model(self, msg_type: int, ranks: list[int], cohort=None,
                      include_desc: bool = False, finished: bool = False) -> None:
        """Downlink fan-out through the encode-once broadcast path: ranks
        whose ``_model_payload`` is the same object share ONE wire frame
        (one payload serialization for the whole group — the mobile server's
        per-rank JSON payloads fall back to singleton groups); per-rank
        scalars (the assigned client index) ride per-receiver header
        overrides. ``use_broadcast=False`` replays the legacy per-rank
        ``send_message`` loop for A/B comparison.

        With the downlink delta plane armed, ranks are served by the model
        version they last echoed: same gap -> same shared chain blob (one
        frame / one object-store put per distinct version-gap per fan-out),
        with the base version riding a header-only per-receiver override;
        ranks without a usable base get the dense keyframe. The init
        fan-out (``include_desc``) is always the dense keyframe."""
        if not ranks:
            return
        dense_nbytes = len(self.global_flat)
        serves = held = None
        # the init fan-out (include_desc) is always the dense keyframe, and
        # finished fan-outs ship dense too: receivers short-circuit on the
        # FINISHED flag without decoding, so building chain blobs (and
        # possibly warning about a long-excluded rank's retired base) for
        # them would be pure waste on a healthy shutdown
        if self.downlink is not None and not include_desc and not finished:
            with self._round_lock:
                held = {w: self._held_versions.get(w) for w in ranks}
            serves = {w: self.downlink.serve(held[w]) for w in ranks}
            retired = sorted(w for w, s in serves.items()
                             if s[0] == "keyframe" and s[2])
            if retired:
                logging.warning(
                    "downlink delta base RETIRED for ranks %s (%s): serving "
                    "the full keyframe instead — raise downlink_retention / "
                    "broadcast_generations if this recurs",
                    retired, "; ".join(serves[w][1] for w in retired),
                )
        payloads = {
            w: (serves[w][1]
                if serves is not None and serves[w][0] == "delta"
                else self._model_payload(w))
            for w in ranks
        }
        groups: dict[int, list[int]] = {}
        for w in ranks:
            groups.setdefault(id(payloads[w]), []).append(w)
        sent_bytes: dict[int, int] = {}
        for group in groups.values():
            s = serves[group[0]] if serves is not None else None
            is_delta = s is not None and s[0] == "delta"
            per_receiver = None
            if cohort is not None or is_delta:
                per_receiver = {}
                for w in group:
                    ov: dict = {}
                    if cohort is not None:
                        ov[MyMessage.MSG_ARG_KEY_CLIENT_INDEX] = int(
                            cohort[w - 1])
                    if is_delta:
                        # header-only per-receiver base version: every
                        # receiver of the shared payload frame validates the
                        # chain against ITS own base (never a payload re-pack)
                        ov[Message.MSG_ARG_KEY_BASE_VERSION] = int(held[w])
                    per_receiver[w] = ov

            def build(dst: int) -> Message:
                msg = Message(msg_type, 0, dst)
                if is_delta:
                    msg.add_params(Message.MSG_ARG_KEY_ENCODED_UPDATE, s[1])
                    msg.add_params(Message.MSG_ARG_KEY_ENCODED_DESC, s[2])
                else:
                    msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                                   payloads[dst])
                # the authoritative round index rides every sync: clients
                # train AS this round instead of counting received syncs,
                # so a duplicated/replayed downlink leg (comm/faults.py dup)
                # cannot desynchronize a client's round counter forever
                msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self.round_idx)
                for k, v in self._sync_extra_params().items():
                    msg.add_params(k, v)
                if include_desc:
                    msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_DESC,
                                   self.model_desc)
                if finished:
                    msg.add_params(Message.MSG_ARG_KEY_FINISHED, 1)
                return msg

            # bytes actually on the wire per receiver: the encoded chain +
            # its descriptor on the delta path, the dense model otherwise
            actual = (int(s[1].size) + len(s[2])) if is_delta else dense_nbytes
            if self.accountant is not None:
                for _w in group:
                    self.accountant.record_downlink(actual, dense_nbytes)
                if self.downlink is not None and not is_delta:
                    self.accountant.record_keyframes(len(group))
            for w in group:
                sent_bytes[w] = actual
            if self.use_broadcast:
                try:
                    self.broadcast_message(build(group[0]), group,
                                           per_receiver=per_receiver)
                except BroadcastSendError as e:
                    self._downlink_failed(e.errors)
            else:
                errors: dict[int, BaseException] = {}
                for w in group:
                    msg = build(w)
                    if per_receiver is not None:
                        for k, v in per_receiver[w].items():
                            msg.add_params(k, v)
                    try:
                        self.send_message(msg)
                    except Exception as e:
                        if getattr(e, "unretryable", False):
                            raise  # injected crash: process death, not a leg
                        errors[w] = e
                if errors:
                    self._downlink_failed(errors)
        if self.downlink is not None:
            with self._round_lock:
                for w, b in sent_bytes.items():
                    self._downlink_sent[w] = self._downlink_sent.get(w, 0) + b
                    if self.fleet is not None:
                        self.fleet.gauge(w, "downlink_bytes",
                                         self._downlink_sent[w])

    def _downlink_failed(self, errors: dict[int, BaseException]) -> None:
        """Per-destination fan-out failures are NOT fatal to the round
        protocol: the affected ranks simply miss this sync and the elastic
        round timeout / liveness plane accounts for their missing uploads.
        Injected crashes (``unretryable``) re-raise — they simulate THIS
        process dying, not a peer being unreachable."""
        for e in errors.values():
            if getattr(e, "unretryable", False):
                raise e
        logging.warning(
            "downlink fan-out failed to ranks %s (continuing: the round "
            "timeout / liveness plane covers their missing uploads): %s",
            sorted(errors),
            "; ".join(f"{d}: {type(e).__name__}: {e}"
                      for d, e in sorted(errors.items())),
        )

    def send_init_msg(self) -> None:
        # cohort keyed by round_idx (not literal 0) so a server restarted
        # from a checkpoint re-broadcasts ITS round — clients train as that
        # round (authoritative round-index sync) and resume is idempotent
        cohort = self._round_cohort()
        self._fanout_model(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
            [w + 1 for w in range(self.worker_num)],
            cohort=cohort, include_desc=True,
        )

    def register_message_receive_handlers(self) -> None:
        from fedml_tpu.comm.status import ClientStatus

        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self._on_model_from_client
        )
        self.register_message_receive_handler(
            ClientStatus.MSG_TYPE_CLIENT_STATUS, self._on_client_status
        )

    def _on_client_status(self, msg: Message) -> None:
        """Heartbeat/status contact: refresh the liveness table, reset the
        consecutive-miss count (the worker is provably alive), and — when
        readmission is on — queue an excluded worker's return for the next
        round boundary."""
        from fedml_tpu.comm.status import ClientStatus

        sender = msg.get_sender_id()
        status = msg.get(ClientStatus.KEY_STATUS)
        with self._round_lock:
            self.status.update(sender, status)
            if status == ClientStatus.ONLINE:
                self._miss_counts.pop(sender - 1, None)
                if self.readmission and not self.aggregator.is_live(sender - 1):
                    if sender - 1 not in self._pending_readmit:
                        logging.info(
                            "excluded worker %d reappeared (status contact); "
                            "queueing readmission at the next round close",
                            sender,
                        )
                    self._pending_readmit.add(sender - 1)

    def _on_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        from fedml_tpu.comm.status import ClientStatus

        flat = self._decode_upload(msg)
        n = float(msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES))
        upload_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX)
        tel = msg.get(Message.MSG_ARG_KEY_TELEMETRY)
        # staleness/exclusion checks and the tally are one critical section:
        # a timer closing the round between them would otherwise let a
        # round-r model slip into round r+1's tally
        with self._round_lock:
            current = self.round_idx
            # the version echo is trusted for EVERY upload — stale and
            # duplicate ones included: the echo proves what model this rank
            # holds regardless of what the tally does with the payload
            self._note_version_echo(sender, msg)
            if not self.aggregator.is_live(sender - 1):
                if self.readmission:
                    # excluded worker resurfaced WITH an upload: provably
                    # alive — queue readmission at the next round boundary
                    # (this round's tally cannot absorb it; first-wins and
                    # the round-index guard make the replayed leg safe)
                    self.status.update(sender, ClientStatus.ONLINE)
                    self._miss_counts.pop(sender - 1, None)
                    if sender - 1 not in self._pending_readmit:
                        logging.info(
                            "excluded worker %d reappeared (upload for round "
                            "%s); queueing readmission", sender, upload_round,
                        )
                    self._pending_readmit.add(sender - 1)
                else:
                    # readmission off: stays excluded (and stays OFFLINE in
                    # the status table)
                    logging.info("ignoring upload from excluded worker %d",
                                 sender)
                return
            if upload_round is not None and int(upload_round) != current:
                # a straggler's upload from a timed-out round: one-round-stale
                # model, must not pollute the current tally. Counted (not
                # silent): Comm/StaleUploads is the observability baseline
                # the async server's staleness weighting builds on.
                self.stale_uploads += 1
                if self.fleet is not None:
                    self.fleet.counter(sender, "stale_uploads")
                    self.fleet.observe(sender, "staleness",
                                       current - int(upload_round))
                    self.fleet.merge_report(sender, tel)
                logging.info(
                    "discarding stale upload from worker %d (upload_round=%s,"
                    " current=%d; Comm/StaleUploads=%d this run — the async "
                    "server mode folds these with a staleness weight instead)",
                    sender, upload_round, current, self.stale_uploads,
                )
                return
            self.status.update(sender, ClientStatus.ONLINE)
            all_received = self.aggregator.add_local_trained_result(
                sender - 1, flat, n
            )
            if self.fleet is not None:
                self.fleet.counter(sender, "uploads")
                self.fleet.observe(sender, "staleness", 0)
                self.fleet.merge_report(sender, tel)
            self._miss_counts.pop(sender - 1, None)  # it spoke: reset misses
            if not all_received and self.round_timeout is not None:
                if self._round_timer is None:
                    self._round_timer = threading.Timer(
                        self.round_timeout,
                        # timer fires on its own thread: inherit the server
                        # thread's job binding so the timeout path's spans/
                        # counters stay job-scoped (obs/jobscope.py)
                        jobscope.wrap_target(self._round_timed_out),
                        args=(current,),
                    )
                    self._round_timer.daemon = True
                    self._round_timer.start()
        if all_received:
            self._complete_round(current)

    def _round_timed_out(self, expected_round: int) -> None:
        from fedml_tpu.comm.status import ClientStatus

        with self._round_lock:
            if self.round_idx != expected_round:
                return  # the round completed while this timer was in flight
            got = self.aggregator.received_workers()
            if not got:
                # nothing to aggregate; release the timer slot so the next
                # upload re-arms it
                self._round_timer = None
                return
            # snapshot + miss accounting + exclusion stay under the lock:
            # an in-time upload accepted concurrently must either appear in
            # ``got`` or be rejected by the exclusion check — never both
            # tallied and excluded
            missing = sorted(set(self.aggregator.live_workers()) - set(got))
            excluded = []
            slow = []
            for w in missing:
                if (self.heartbeat_timeout is not None
                        and self.status.seen_within(w + 1,
                                                    self.heartbeat_timeout)):
                    # heartbeat fresh: the worker is SLOW, not dead — it
                    # misses this round's aggregate but accrues no
                    # exclusion miss (its heartbeats keep proving liveness)
                    self.status.update(w + 1, ClientStatus.SLOW, touch=False)
                    slow.append(w + 1)
                    continue
                self._miss_counts[w] = self._miss_counts.get(w, 0) + 1
                if self._miss_counts[w] >= self.exclude_after:
                    # consecutive silent misses: presumed dead — stop
                    # expecting it so later rounds complete without another
                    # timeout (readmission re-enters it if it reappears)
                    self.status.update(w + 1, ClientStatus.OFFLINE,
                                       touch=False)
                    self.aggregator.exclude_worker(w)
                    excluded.append(w + 1)
        logging.warning(
            "round %d timed out: aggregating %d/%d workers, dropping %s"
            "%s%s (weights renormalized)",
            expected_round, len(got), self.worker_num,
            [w + 1 for w in missing],
            f", slow (heartbeat fresh) {slow}" if slow else "",
            f", excluding {excluded} as OFFLINE" if excluded else "",
        )
        if excluded and not self.readmission:
            # tell the excluded clients to stop: they would otherwise keep
            # training models the server discards every round. With
            # readmission on they are PARKED instead — still heartbeating,
            # eligible to rejoin later cohorts on contact.
            self._fanout_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                               excluded, finished=True)
        self._complete_round(expected_round, timed_out=True)

    def _complete_round(self, expected_round: int,
                        timed_out: bool = False) -> None:
        # round/close span: aggregate + advance + next fan-out. On the
        # all-received path it runs on the LAST upload's handler thread, so
        # it nests inside that upload's comm/recv span — the causal link
        # the critical-path analyzer (tools/trace_report.py) walks to name
        # the gating client/tier; a timer-fired close carries timed_out=1
        # and has no recv ancestor.
        with trace.span("round/close", round=expected_round,
                        timed_out=int(timed_out)):
            self._complete_round_locked(expected_round)

    def _complete_round_locked(self, expected_round: int) -> None:
        readmitted: list[int] = []
        with self._round_lock:
            if self.round_idx != expected_round:
                return  # a concurrent close won the race for this round
            if not self.aggregator.received_workers():
                return  # benign double fire (timer raced the full tally)
            if self._round_timer is not None:
                self._round_timer.cancel()
                self._round_timer = None
            self.global_flat = self.aggregator.aggregate()
            self.round_idx += 1
            if self.downlink is not None:
                # encode-once at round close: the delta (against the
                # previous DECODED version) lands in the serve chain, and
                # the global of record becomes the decoded model — what
                # every client reconstructs, so quantization error never
                # accumulates across rounds
                self.global_flat = self.downlink.advance(self.global_flat,
                                                         self.round_idx)
            # readmission boundary: workers that re-contacted the server
            # while excluded re-enter the expected set HERE, never
            # mid-round (a mid-round readmit would stall the all-received
            # barrier until the returnee uploads)
            if self._pending_readmit:
                from fedml_tpu.comm.status import ClientStatus

                for w in sorted(self._pending_readmit):
                    self.aggregator.readmit_worker(w)
                    self._miss_counts.pop(w, None)
                    if self.fleet is not None:
                        # the distinct timeline event BEFORE the tracker
                        # flips the state back: ... OFFLINE, READMITTED,
                        # ONLINE — an operator can tell a returnee apart
                        self.fleet.record_state(w + 1,
                                                registry.STATE_READMITTED)
                        self.fleet.counter(w + 1, "readmissions")
                    self.status.update(w + 1, ClientStatus.ONLINE,
                                       touch=False)
                    readmitted.append(w + 1)
                self._pending_readmit.clear()
            # snapshot under the lock (consistent round state), write the
            # files OUTSIDE it — full-model disk I/O must not block the
            # upload/heartbeat handlers queued on _round_lock
            ckpt_state = self._checkpoint_state()
        if ckpt_state is not None:
            self._write_checkpoint(ckpt_state)
        if readmitted:
            logging.info("readmitted workers %s into round %d's cohort",
                         readmitted, self.round_idx)
        if self.on_round_done:
            self.on_round_done(expected_round, self.global_flat)
        if self.round_idx >= self.round_num:
            # graceful stop: notify clients then stop own loop (NOT MPI.Abort)
            self._fanout_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                               [w + 1 for w in range(self.worker_num)],
                               finished=True)
            self.finish()
            return
        cohort = self._round_cohort()
        self._fanout_model(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                           [w + 1 for w in self.aggregator.live_workers()],
                           cohort=cohort)

    # -- fleet telemetry (docs/OBSERVABILITY.md "Fleet telemetry") -----------

    def _fleet_round_record(self, round_idx: int) -> dict | None:
        """Flush heartbeat freshness into the fleet view and return the
        cumulative fleet snapshot stamped with ``round_idx`` — the per-round
        JSONL record the runner appends to ``fleet_stats['rounds']``. None
        when fleet telemetry is off."""
        if self.fleet is None:
            return None
        now = time.monotonic()
        for w in self.aggregator.live_workers():
            seen = self.status.last_seen(w + 1)
            if seen is not None:
                self.fleet.gauge(w + 1, "heartbeat_age_s",
                                 round(now - seen, 4))
        return self.fleet.round_record(round_idx)

    # -- crash recovery (docs/ROBUSTNESS.md "Failure recovery") --------------

    def _checkpoint_state(self) -> dict | None:  # lock-held: _round_lock
        """Snapshot the full server round state at round close (caller
        holds ``_round_lock``) — everything a restarted server needs to
        re-broadcast ``round_idx`` and continue bit-identically: the new
        global flat model, the round index, miss counts, the status table,
        and the aggregator's tally/defense state (robust noise-key round
        included). The snapshot is taken under the lock; the disk write
        (:meth:`_write_checkpoint`) runs after it is released."""
        if self.checkpointer is None or (self.round_idx % self.checkpoint_every):
            return None
        # "server_round", not the wire key's "round_idx" spelling: the
        # checkpoint schema and the wire contract drift independently
        return {
            "server_round": int(self.round_idx),
            "global_flat": np.asarray(self.global_flat),
            "miss_counts": {str(k): int(v)
                            for k, v in self._miss_counts.items()},
            "status": self.status.snapshot(),
            "aggregator": self.aggregator.snapshot_state(),
        }

    def _write_checkpoint(self, state: dict) -> None:
        """Persist a :meth:`_checkpoint_state` snapshot. Runs BEFORE the
        round callback and the next fan-out, so a crash during either
        resumes from this round — and the authoritative-round-index sync
        makes the replayed fan-out idempotent."""
        with trace.span("ft/checkpoint", round=state["server_round"]):
            self.checkpointer.save_server(state["server_round"], state)

    def restore_from_checkpoint(self, checkpointer=None,
                                round_idx: int | None = None) -> int:
        """Load a server snapshot (latest by default) and arrange to resume
        AS that round: the next ``send_init_msg`` re-broadcasts the
        checkpointed round index and global model, clients re-train as that
        round, and the run continues bit-identically to one that never
        crashed (tools/ft_smoke.py holds the contract). Returns the resumed
        round index."""
        ckptr = checkpointer or self.checkpointer
        if ckptr is None:
            raise ValueError("restore_from_checkpoint needs a checkpointer")
        state = ckptr.restore_server(round_idx)
        with self._round_lock:
            # pre-PR 11 snapshots spelled the scalar "round_idx"; accept
            # both so a crash recovery spanning the rename still resumes
            # fedlint: disable=wire-contract -- legacy checkpoint schema field, not the wire key
            legacy = state.get("round_idx")
            self.round_idx = int(state.get("server_round", legacy))
            self.global_flat = np.asarray(state["global_flat"], np.uint8)
            self._miss_counts = {
                int(k): int(v)
                for k, v in state.get("miss_counts", {}).items()
            }
            for cid, st in state.get("status", {}).items():
                self.status.update(int(cid), st, touch=False)
            self.aggregator.restore_state(state.get("aggregator", {}))
            if self.downlink is not None:
                # the delta chain and the held-version table died with the
                # crashed process: re-anchor on a keyframe (the checkpointed
                # global IS the decoded model) and let echoes rebuild the
                # table — every client's first post-resume sync is dense
                self.global_flat = self.downlink.reset(self.global_flat,
                                                       self.round_idx)
                self._held_versions.clear()
        logging.info("restored server round state: resuming as round %d "
                     "(live workers %s)", self.round_idx,
                     [w + 1 for w in self.aggregator.live_workers()])
        return self.round_idx


class FedAvgClientManager(ClientManager):
    """Client protocol (FedAvgClientManager.py:25-72): receive global model,
    K local epochs on the assigned shard (jitted), send params + sample count."""

    def __init__(self, comm: BaseCommunicationManager, rank: int, size: int,
                 trainer: ClientTrainer, train_data: FederatedArrays,
                 batch_size: int, template_variables: Any,
                 local_train_fn=None):
        super().__init__(comm, rank, size)
        self.trainer = trainer
        self.train_data = train_data
        self.batch_size = batch_size
        self.template = template_variables
        # override point: cross-silo clients train data-parallel over their
        # silo mesh (algorithms/cross_silo.py) instead of single-device
        self._local_train = local_train_fn or jax.jit(make_local_train(trainer))
        self._round = 0
        # rng identity on the wire: ranks are fabric-local, so two leaves in
        # different tiers of an aggregation tree can share a rank — the tree
        # harness points rng_rank at the GLOBAL leaf number instead so their
        # local-train key chains never collide (flat runs: rng_rank == rank)
        self.rng_rank = rank
        # fleet telemetry opt-in (set by the runner when fleet_stats is on):
        # piggybacking must not key on the process registry alone — a
        # registry installed for unrelated gauges must never change what
        # goes on the wire
        self.fleet_telemetry = False
        # downlink delta coding (compress/downlink.py): the runner arms
        # every client with the run's downlink codec; the decoder (the
        # mutable held model + version) is built at the first keyframe.
        # None keeps _decode_model the zero-copy dense path bit-identically.
        self.downlink_codec = None
        self._downlink = None
        # per-rank population profile (population/wire.py adapter; set by
        # the runner under population=): feeds the predicted-vs-actual
        # step gauges piggybacked when fleet telemetry is on
        self.population_profile = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._on_sync)

    def _decode_model(self, msg: Message):
        """Wire-format seam: a sync payload back to model variables. The
        mobile client (fedavg_mobile.py) parses the reference's nested-list
        JSON here instead.

        Downlink delta coding (compress/downlink.py): a sync carrying an
        encoded-update payload is a delta CHAIN — applied step-by-step onto
        this client's held version with the server's exact f32 add
        sequence, so the reconstruction is bit-exact. Dense syncs are
        keyframes: with the plane armed they replace the held copy; without
        it this is the unchanged zero-copy dense path."""
        desc = msg.get(MyMessage.MSG_ARG_KEY_MODEL_DESC)
        if desc is not None:
            self._desc = desc
        chain = msg.get(Message.MSG_ARG_KEY_ENCODED_UPDATE)
        if chain is None:
            flat = np.asarray(msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS))
            version = getattr(self, "_model_version", None)
            if self.downlink_codec is not None and version is not None:
                from fedml_tpu.compress.downlink import DownlinkDecoder

                if self._downlink is None:
                    self._downlink = DownlinkDecoder(self.downlink_codec)
                flat = self._downlink.apply_keyframe(flat, version).view(
                    np.uint8)
            return unpack_pytree(flat, self._desc)
        if self.downlink_codec is None:
            raise RuntimeError(
                "received a delta-coded sync but this client has no "
                "downlink codec — server and clients must be armed with "
                "the same --downlink_compressor"
            )
        if self._downlink is None:
            raise RuntimeError(
                "delta-coded sync before any keyframe: the init sync is "
                "always dense, so this client missed it (protocol bug)"
            )
        held = self._downlink.apply_chain(
            np.asarray(chain),
            msg.get(Message.MSG_ARG_KEY_ENCODED_DESC),
            msg.get(Message.MSG_ARG_KEY_BASE_VERSION),
            getattr(self, "_model_version", None),
        )
        # echo what the decoder actually RECONSTRUCTED, not the header
        # stamp: a fan-out racing a round close can stamp one version off,
        # and an echo ahead of the held model would make the next delta
        # serve a base this client does not hold
        self._model_version = self._downlink.version
        return unpack_pytree(held.view(np.uint8), self._desc)

    def _encode_model(self, new_vars):
        """Inverse seam: trained variables to the upload payload."""
        flat_out, _ = pack_pytree(jax.tree.map(np.asarray, new_vars))
        return flat_out

    def _fill_upload(self, out: Message, new_vars, global_vars) -> None:
        """Upload-payload seam: base sends the dense packed model; the
        compressed client sends an encoded delta instead (and needs
        ``global_vars``, the model it trained from, to form it)."""
        out.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                       self._encode_model(new_vars))

    def _on_sync(self, msg: Message) -> None:
        if msg.get(Message.MSG_ARG_KEY_FINISHED):
            self.finish()
            return
        # fleet telemetry (obs/registry.py, docs/OBSERVABILITY.md "Fleet
        # telemetry"): when this client opted in AND a process registry is
        # installed, time the local round and piggyback a compact report on
        # the upload; the disabled path costs one attribute check and adds
        # NO wire field
        reg = registry.get() if self.fleet_telemetry else None
        t_start = time.perf_counter() if reg is not None else 0.0
        # the explicit model-version stamp (async server mode,
        # docs/PERFORMANCE.md "Barrier-free aggregation"): remembered here
        # and ECHOED on the upload, so the server's staleness weight is
        # computed from the version this client verifiably trained against
        # (sync servers stamp no version and get no echo)
        version = msg.get(Message.MSG_ARG_KEY_MODEL_VERSION)
        self._model_version = None if version is None else int(version)
        ridx = msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX)
        if ridx is not None:
            # train AS the server's round, not as "however many syncs this
            # client has seen": a duplicated or delayed downlink leg then
            # re-trains the same round (its duplicate upload is absorbed by
            # the tally's first-wins rule) instead of desynchronizing the
            # round counter for the rest of the run
            self._round = int(ridx)
        variables = self._decode_model(msg)
        client_idx = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX))
        self._client_idx = client_idx  # which client this round trains as
        batches, weights = stack_cohort(
            self.train_data, np.asarray([client_idx]), self.batch_size,
            rng=np.random.RandomState(1000 + self._round),
        )
        batches = jax.tree.map(lambda v: jnp.asarray(v[0]), batches)
        # client/train span: the local-round compute between a sync's
        # arrival and the upload's send — nested (same handler thread)
        # under the sync's comm/recv span, so the merged cross-rank trace
        # links round/close -> upload send -> this span -> sync fan-out
        with trace.span("client/train", rank=self.rank, round=self._round,
                        client_idx=client_idx):
            new_vars, _ = self._local_train(
                variables, batches,
                jax.random.key(self.rng_rank * 100003 + self._round),
            )
        self._round += 1
        out = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        self._fill_upload(out, new_vars, variables)
        out.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, float(weights[0]))
        out.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, self._round - 1)
        if getattr(self, "_model_version", None) is not None:
            out.add_params(Message.MSG_ARG_KEY_MODEL_VERSION,
                           self._model_version)
        if reg is not None:
            step_ms = (time.perf_counter() - t_start) * 1e3
            reg.observe("client/step_ms", step_ms)
            reg.counter("client/rounds")
            # header-only JSON scalars (never payload); "retries" is this
            # manager's cumulative count as of the PREVIOUS send — the
            # current send's re-attempts land on the next round's report
            report = {
                "step_ms": round(step_ms, 3),
                "sent_at": time.time(),
                "retries": self.comm_retries,
            }
            prof = self.population_profile
            if prof is not None:
                # population churn gauges (docs/OBSERVABILITY.md "Fleet
                # telemetry"): cumulative predicted-vs-actual step totals
                # (predicted = the speed model's forecast; actual = what
                # this client really ran) plus the uploads its own fault
                # wrapper dropped — counts ride the report's "counts"
                # field, which the server folds into per-rank gauges
                S = next(iter(batches.values())).shape[0]
                actual = int(self.trainer.epochs * S)
                predicted = int(np.ceil(prof["predicted_frac"] * actual))
                self._pop_predicted = getattr(
                    self, "_pop_predicted", 0) + max(predicted, 1)
                self._pop_actual = getattr(self, "_pop_actual", 0) + actual
                counts = {
                    "pop_predicted_steps": self._pop_predicted,
                    "pop_actual_steps": self._pop_actual,
                }
                applied = getattr(self.comm, "applied_counts", None)
                if applied is not None:
                    counts["pop_dropped_uploads"] = applied().get("drop", 0)
                report["counts"] = counts
            out.add_params(Message.MSG_ARG_KEY_TELEMETRY, report)
        self.send_message(out)



# ---------------------------------------------------------------------------
# Compressed-update protocol variant (fedml_tpu/compress, docs/COMPRESSION.md)
# ---------------------------------------------------------------------------


class CompressedDistAggregator(FedAvgDistAggregator):
    """Streaming tally for encoded uploads: each client's EncodedUpdate is
    folded into ONE dense f64 accumulator AS IT ARRIVES (top-k scatter-adds
    straight from its index/value planes — the server never materializes
    per-client dense trees, and with streaming it no longer retains the
    encoded uploads either). ``aggregate()`` divides by the weight sum at
    round close; delta-domain codecs add the result onto the current global;
    the ``none`` codec carries models and reproduces the dense protocol's
    arithmetic bit-for-bit."""

    def __init__(self, worker_num: int, codec):
        super().__init__(worker_num)
        self.codec = codec
        self.get_global = None  # wired by the server manager (current flat)

    def _fold(self, payload, sample_num: float) -> None:
        from fedml_tpu.compress.aggregate import accumulate_encoded

        if self._acc is None:
            base = np.ascontiguousarray(self.get_global()).view(np.float32)
            self._acc = np.zeros(base.size, np.float64)
        accumulate_encoded(self._acc, payload, float(sample_num), self.codec)
        self._wsum += float(sample_num)

    def _fold_task(self, payload, weight: float):
        from fedml_tpu.algorithms.fold_plane import EncodedFoldTask

        # sized from the round global like the serial first fold — only the
        # SIZE is read here; decode runs in the task's prepare, off the
        # receive thread
        return EncodedFoldTask(payload, weight, self.codec,
                               np.asarray(self.get_global()).nbytes // 4)

    def _finish(self) -> np.ndarray:
        self._fold_epoch += 1
        acc = self._acc / self._wsum
        if self.codec.delta_domain:
            base = np.ascontiguousarray(self.get_global()).view(np.float32)
            acc += base.astype(np.float64)
        self._acc = None
        self._wsum = 0.0
        return acc.astype(np.float32).view(np.uint8)


class CompressedBufferedDistAggregator(BufferedFedAvgDistAggregator,
                                       CompressedDistAggregator):
    """Legacy-shaped compressed tally: retains the encoded uploads and folds
    them at round close in arrival order, through the same fold arithmetic —
    the A/B reference for :class:`CompressedDistAggregator` (bit-identical
    under any schedule)."""

    def __init__(self, worker_num: int, codec):
        CompressedDistAggregator.__init__(self, worker_num, codec)
        self.model_dict = {}


class CompressedFedAvgServerManager(FedAvgServerManager):
    """FedAvg server speaking the encoded-update uplink: dense model down,
    EncodedUpdate planes up, with bytes-on-wire accounting per round."""

    def __init__(self, *args, codec=None, **kwargs):
        if codec is None:
            raise ValueError("CompressedFedAvgServerManager needs a codec")
        # hoisted ABOVE super().__init__ so the base's single
        # _make_aggregator() call sees it (the factory seam, ROADMAP item 1)
        self.codec = codec
        super().__init__(*args, **kwargs)

    def _make_accountant(self):
        # the encoded uplink always accounts (downlink bytes are recorded
        # by the shared fan-out path — dense unless the delta plane is
        # armed on top)
        from fedml_tpu.obs.metrics import CommBytesAccountant

        return CommBytesAccountant()

    def _make_aggregator(self):
        agg = (
            CompressedBufferedDistAggregator if self.buffered_aggregation
            else CompressedDistAggregator
        )(self.worker_num, self.codec)
        agg.get_global = lambda: self.global_flat
        return agg

    def _decode_upload(self, msg: Message):
        from fedml_tpu.comm.message import unpack_encoded_update

        flat = np.asarray(msg.get(Message.MSG_ARG_KEY_ENCODED_UPDATE))
        desc = msg.get(Message.MSG_ARG_KEY_ENCODED_DESC)
        self.accountant.record_uplink(flat.size + len(desc),
                                      len(self.global_flat))
        return unpack_encoded_update(flat, desc)


class CompressedFedAvgClientManager(FedAvgClientManager):
    """FedAvg client that uplinks an encoded update instead of the dense
    model: delta-domain codecs encode (local - global) with error-feedback
    residual carryover; the ``none`` codec encodes the model itself so the
    wire path stays bit-identical to the dense protocol.

    EF residuals are keyed by the *assigned client index*, never by worker:
    at full participation (cohort == arange) that is exact per-client EF;
    under resampling a client's residual is carried by the last worker that
    trained it and rejoins when that worker redraws the client — dropped
    mass from one client is never added into another's update."""

    def __init__(self, comm: BaseCommunicationManager, rank: int, size: int,
                 trainer: ClientTrainer, train_data: FederatedArrays,
                 batch_size: int, template_variables: Any,
                 local_train_fn=None, codec=None, error_feedback: bool = True):
        super().__init__(comm, rank, size, trainer, train_data, batch_size,
                         template_variables, local_train_fn=local_train_fn)
        if codec is None:
            raise ValueError("CompressedFedAvgClientManager needs a codec")
        from functools import partial

        from fedml_tpu.compress import error_feedback as eflib

        self.codec = codec
        self.error_feedback = bool(error_feedback) and codec.delta_domain
        self._residuals: dict[int, Any] = {}
        self._encode_ef = jax.jit(partial(eflib.encode_with_feedback, codec))
        self._encode_plain = jax.jit(codec.encode)

    def _fill_upload(self, out: Message, new_vars, global_vars) -> None:
        from fedml_tpu.comm.message import pack_encoded_update
        from fedml_tpu.compress import error_feedback as eflib
        from fedml_tpu.core import tree as treelib
        from fedml_tpu.obs import trace

        key = jax.random.fold_in(
            jax.random.key(0xC0DEC ^ self.rank), self._round
        )
        with trace.span("compress/encode", scheme=self.codec.name,
                        error_feedback=self.error_feedback):
            if self.codec.delta_domain:
                delta = treelib.tree_sub(new_vars, global_vars)
                if self.error_feedback:
                    comp = eflib.compensate(
                        delta, self._residuals.get(self._client_idx)
                    )
                    enc, _, new_residual = self._encode_ef(comp, key)
                    self._residuals[self._client_idx] = new_residual
                else:
                    # skip the EF program entirely: its jitted outputs
                    # include a dense decode + residual that XLA cannot DCE,
                    # all shipped to host just to be discarded
                    enc = self._encode_plain(delta, key)
            else:
                enc = self._encode_plain(new_vars, key)
            flat, desc = pack_encoded_update(enc)
        out.add_params(Message.MSG_ARG_KEY_ENCODED_UPDATE, flat)
        out.add_params(Message.MSG_ARG_KEY_ENCODED_DESC, desc)


def init_template(trainer: ClientTrainer, train_arrays: dict, batch_size: int,
                  seed: int = 0, init_overrides=None):
    """Shared harness setup: init the model from a data sample and pack it
    for the wire. Returns (template pytree, flat bytes, descriptor).
    ``init_overrides`` grafts warm-start collections (a ``load_params`` dict)
    over the fresh init — the message-passing side of ``--init_from``."""
    sample = {
        name: jnp.asarray(arr[:batch_size]) for name, arr in train_arrays.items()
    }
    sample.setdefault("mask", jnp.ones((batch_size,), jnp.float32))
    template = trainer.init(jax.random.key(seed), sample)
    template = jax.tree.map(np.asarray, template)
    if init_overrides:
        from fedml_tpu.obs.checkpoint import graft_params

        template = graft_params(dict(template), dict(init_overrides))
    flat, desc = pack_pytree(template)
    return template, flat, desc


def run_manager_protocol(server, clients, join_timeout: float = 30.0,
                         client_lanes: list[str] | None = None,
                         server_lane: str | None = None) -> None:
    """Shared run harness: client managers in daemon threads, the server's
    receive loop on the caller thread, graceful join. Used by distributed
    FedAvg, TurboAggregate, and cross-silo. If the server's loop dies (e.g.
    an injected crash, comm/faults.py), the client transports are stopped
    so their threads unblock before the error propagates — a crashed server
    must not leak parked client threads into the next (restarted) run.

    ``client_lanes``/``server_lane`` bind each manager's thread to a
    per-rank lane (obs/jobscope.py) so a ``trace.lane_traces`` harness
    captures one span stream per rank — the in-process form of per-process
    ``--trace_dir`` files that ``tools/trace_merge.py`` merges."""
    # client threads inherit the caller's job binding (obs/jobscope.py)
    # unless an explicit lane is given: under the multi-tenant runner a
    # job's clients emit into ITS job-scoped registry/tracer; single-job
    # runs get the target back unchanged
    threads = [
        threading.Thread(
            target=jobscope.wrap_target(
                c.run, job=client_lanes[i] if client_lanes else None),
            daemon=True)
        for i, c in enumerate(clients)
    ]
    for t in threads:
        t.start()
    with jobscope.bound(server_lane):
        server.register_message_receive_handlers()
        server.send_init_msg()
        try:
            server.comm.handle_receive_message()  # blocks until the protocol finishes
        except BaseException:
            for c in clients:
                try:
                    c.comm.stop_receive_message()
                except Exception:  # noqa: BLE001 — best-effort unblock
                    pass
            raise
    for t in threads:
        t.join(timeout=join_timeout)


def run_distributed_fedavg(
    trainer: ClientTrainer,
    train_data: FederatedArrays,
    worker_num: int,
    round_num: int,
    batch_size: int,
    make_comm: Callable[[int], BaseCommunicationManager],
    seed: int = 0,
    round_timeout: float | None = None,
    on_round_done: Callable[[int, Any], None] | None = None,
    init_overrides=None,
    server_cls: type[FedAvgServerManager] = None,
    server_kwargs: dict | None = None,
    client_cls_for_rank: Callable[[int], type] | None = None,
    codec=None,
    error_feedback: bool = True,
    downlink_codec=None,
    downlink_keyframe_every: int = 8,
    downlink_retention: int = 4,
    comm_stats: dict | None = None,
    robust_config=None,
    robust_stats: dict | None = None,
    fault_specs=None,
    fault_seed: int = 0,
    population=None,
    retry_policy=None,
    heartbeat_interval: float | None = None,
    heartbeat_timeout: float | None = None,
    readmission: bool | None = None,
    checkpoint_dir=None,
    checkpoint_every: int = 1,
    resume: bool = False,
    server_mode: str = "sync",
    buffer_goal: int | None = None,
    staleness_weight: str = "const",
    async_stats: dict | None = None,
    fleet_stats: dict | None = None,
    trace_lanes: str | None = None,
    trace_wire: bool = False,
    fold_workers: int = 0,
    fold_chunk: int | None = None,
):
    """End-to-end distributed FedAvg over any comm fabric: ``make_comm(rank)``
    builds rank 0's server transport and ranks 1..W's client transports
    (loopback queues, native shm rings, grpc localhost, ...). Clients run in
    threads — the single-host harness the reference lacked (SURVEY §4); the
    same managers drive separate processes when the transport spans them.
    ``server_cls``/``server_kwargs``/``client_cls_for_rank`` swap in
    protocol variants (e.g. fedavg_mobile's JSON-wire managers) without
    duplicating this harness. ``codec`` switches the uplink to the
    compressed-update protocol (compress/codec.py; ``error_feedback``
    toggles per-worker residual carryover, ``comm_stats`` — a caller dict —
    receives per-round and total bytes-on-wire records). ``robust_config``
    (a robust_distributed.RobustDistConfig) swaps the server tally for the
    streaming Byzantine-robust + DP one, composing with ``codec``
    (``robust_stats`` receives per-round Robust/* records).
    ``downlink_codec`` (a codec or ``--downlink_compressor`` spec; 'none'
    resolves to the unchanged dense broadcast) arms the downlink delta
    plane (compress/downlink.py, docs/COMPRESSION.md "Downlink delta
    coding"): round closes encode the new global once as a quantized
    delta against the previous emitted version, fan-outs serve each rank
    by its echoed version (one-step delta / cumulative chain / every
    ``downlink_keyframe_every``-th version a dense keyframe; the chain
    keeps ``downlink_retention`` steps, raised by the async server's
    staleness p99), and reconstruction is bit-exact because the global of
    record becomes the decoded model. Composes with ``codec`` (both
    directions encoded), ``robust_config``, and ``server_mode='async'``.
    ``fault_specs`` (comm/faults.py: a {rank: FaultSpec} map or a spec
    string) wraps every rank's transport in the seeded fault injector.

    Fault-tolerance knobs (docs/ROBUSTNESS.md "Failure recovery"):
    ``retry_policy`` (comm/retry.py) arms retry/backoff on every rank's
    send plane, OUTSIDE any fault wrapper so each attempt re-rolls its
    faults; ``heartbeat_interval`` starts a per-client heartbeat thread
    (and defaults ``heartbeat_timeout`` to 3x the interval, the server's
    slow-vs-dead window); ``readmission`` (default: on iff heartbeats are
    on) lets an OFFLINE-excluded worker rejoin later cohorts when it
    re-contacts the server. ``checkpoint_dir`` snapshots the full server
    round state every ``checkpoint_every`` round closes; ``resume=True``
    restores the latest snapshot and re-broadcasts its round — clients
    re-train AS that round, so a crashed-and-restarted run is
    bit-identical to an uninterrupted one (tools/ft_smoke.py).

    Server execution mode (docs/PERFORMANCE.md "Barrier-free aggregation"):
    ``server_mode="async"`` swaps in the FedBuff-style buffered-async
    server (fedml_tpu/async_agg): uploads fold on arrival with a
    ``staleness_weight`` decay (const | poly:a | hinge:a,b), a new global
    model is emitted every ``buffer_goal`` arrivals (default: the worker
    count) with no round barrier, and ``round_num`` counts EMITTED models.
    ``async_stats`` (a caller dict) receives per-emission Async/* records.
    With ``buffer_goal == worker_num`` and the constant weight the async
    path reproduces the sync streaming path bit-for-bit
    (tools/async_smoke.py holds the contract). The hierarchical-tree mode
    has its own harness (async_agg.tree.run_tree_fedavg_loopback).

    ``fleet_stats`` (a caller dict) switches on the fleet telemetry plane
    (docs/OBSERVABILITY.md "Fleet telemetry"): the server grows a per-rank
    health view (obs/registry.py FleetHealth), clients piggyback compact
    telemetry reports on their uploads, and the dict receives per-round
    fleet snapshots (``rounds``), the final fleet view (``totals``), and
    the process MetricRegistry snapshot (``registry``). Read-only:
    telemetry-on runs are bit-identical to telemetry-off runs
    (tools/fleet_smoke.py holds the contract). Returns the final global
    variables."""
    if server_mode not in ("sync", "async"):
        raise ValueError(
            f"unknown server_mode {server_mode!r}: expected 'sync' or "
            "'async' (the hierarchical tree mode runs through "
            "async_agg.tree.run_tree_fedavg_loopback — its process topology "
            "is a tree of comm fabrics, not this harness's flat fan-out)"
        )
    if server_mode == "async":
        if server_cls is not None or client_cls_for_rank is not None:
            raise ValueError(
                "server_mode='async' does not compose with custom manager "
                "classes (e.g. is_mobile's JSON wire format)"
            )
        if round_timeout is not None:
            raise ValueError(
                "server_mode='async' has no round barrier, so the elastic "
                "round_timeout does not apply — drop it (slow workers just "
                "fold late, staleness-weighted)"
            )
    if codec is not None and (server_cls is not None
                              or client_cls_for_rank is not None):
        raise ValueError(
            "codec= does not compose with custom manager classes "
            "(e.g. is_mobile's JSON wire format)"
        )
    if downlink_codec is not None:
        # accept a codec or a --downlink_compressor spec string; 'none'
        # resolves to the unchanged dense broadcast (bit-identity arm)
        from fedml_tpu.compress.downlink import resolve_downlink_codec

        downlink_codec = resolve_downlink_codec(downlink_codec)
    if downlink_codec is not None and (server_cls is not None
                                       or client_cls_for_rank is not None):
        raise ValueError(
            "downlink_codec= does not compose with custom manager classes "
            "(e.g. is_mobile's JSON wire format)"
        )
    if robust_config is not None and not robust_config.enabled:
        robust_config = None  # a no-op defense is exactly plain FedAvg
    if robust_config is not None and (server_cls is not None
                                      or client_cls_for_rank is not None):
        raise ValueError(
            "robust_config= does not compose with custom manager classes "
            "(e.g. is_mobile's JSON wire format)"
        )
    if population is not None:
        # heterogeneous-population wire adapter (population/wire.py,
        # docs/PERFORMANCE.md "Heterogeneous populations"): per-rank upload
        # delays/drops drawn from the population distributions, scheduled
        # through the same seeded fault machinery as fault_specs
        from fedml_tpu.population.wire import (
            PopulationWireAdapter,
            population_fault_specs,
        )

        if not isinstance(population, PopulationWireAdapter):
            population = population_fault_specs(
                population, worker_num, seed=fault_seed or seed
            )
        elif population.worker_num != worker_num:
            raise ValueError(
                f"population adapter was built for "
                f"{population.worker_num} workers but this run has "
                f"{worker_num} — the uncovered ranks would silently run "
                "un-churned (the trace loader rejects the analogous "
                "num_clients mismatch for the same reason)"
            )
        if fault_specs is not None and population.active:
            raise ValueError(
                "population= and fault_specs= both drive the wire fault "
                "injector — one seeded schedule would silently shift the "
                "other; configure churn in exactly one place"
            )
        if population.drops_uploads:
            if server_mode != "sync":
                raise ValueError(
                    "the population drops uploads but the async server "
                    "has no timeout/readmission path for a silently lost "
                    "upload — the dropped rank never receives another "
                    "downlink and strands forever; run server_mode='sync' "
                    "with round_timeout=, or model the churn as delays "
                    "(jitter) instead of drops"
                )
            if round_timeout is None:
                raise ValueError(
                    "the population drops uploads but the sync round "
                    "barrier has no round_timeout — the first dropped "
                    "upload would wedge the round forever; set "
                    "round_timeout="
                )
        if population.active:
            fault_specs = population.fault_specs
    if fault_specs is not None:
        from fedml_tpu.comm.faults import wrap_make_comm

        make_comm = wrap_make_comm(make_comm, fault_specs, seed=fault_seed)
    if retry_policy is not None:
        # armed on the OUTERMOST manager (fault wrappers included): each
        # retry attempt re-runs the full send path with fresh fault draws
        def make_comm(rank: int, _inner=make_comm):
            mgr = _inner(rank)
            mgr.retry_policy = retry_policy
            return mgr

    if readmission is None:
        readmission = heartbeat_interval is not None
    if heartbeat_interval is not None and heartbeat_timeout is None:
        heartbeat_timeout = 3.0 * heartbeat_interval
    ckptr = None
    ft_kwargs: dict = {}
    if fold_workers:
        # sharded fold plane (docs/PERFORMANCE.md "The server fold plane"):
        # bit-identical to the serial fold, so it composes with every server
        # arm below — the knob just rides the server kwargs
        ft_kwargs["fold_workers"] = int(fold_workers)
        if fold_chunk is not None:
            ft_kwargs["fold_chunk"] = int(fold_chunk)
    if heartbeat_timeout is not None:
        ft_kwargs["heartbeat_timeout"] = heartbeat_timeout
    if readmission:
        ft_kwargs["readmission"] = True
    if checkpoint_dir is not None:
        from fedml_tpu.obs.checkpoint import RoundCheckpointer

        ckptr = RoundCheckpointer(checkpoint_dir)
        ft_kwargs["checkpointer"] = ckptr
        ft_kwargs["checkpoint_every"] = checkpoint_every
    fleet = None
    _sysstats = None
    if fleet_stats is not None:
        from fedml_tpu.obs.registry import FleetHealth
        from fedml_tpu.obs.sysstats import SysStats

        fleet = FleetHealth()
        ft_kwargs["fleet"] = fleet
        _sysstats = SysStats()
    if ft_kwargs:
        # explicit caller server_kwargs still win over the derived knobs
        server_kwargs = {**ft_kwargs, **(server_kwargs or {})}
    template, flat, desc = init_template(trainer, train_data.arrays, batch_size,
                                         seed, init_overrides=init_overrides)
    if robust_config is not None:
        from fedml_tpu.algorithms.robust_distributed import (
            RobustCompressedFedAvgServerManager,
            RobustFedAvgServerManager,
        )

        server_cls = (RobustCompressedFedAvgServerManager if codec is not None
                      else RobustFedAvgServerManager)
        server_kwargs = {**(server_kwargs or {}),
                         "robust_config": robust_config,
                         "robust_stats": robust_stats}
    if codec is not None:
        if server_cls is None:
            server_cls = CompressedFedAvgServerManager
        server_kwargs = {**(server_kwargs or {}), "codec": codec}

        def client_cls_for_rank(rank):
            def make(comm, r, size, tr, data, bs, tmpl):
                return CompressedFedAvgClientManager(
                    comm, r, size, tr, data, bs, tmpl,
                    codec=codec, error_feedback=error_feedback,
                )

            return make

    if downlink_codec is not None:
        server_kwargs = {**(server_kwargs or {}),
                         "downlink_codec": downlink_codec,
                         "downlink_keyframe_every": downlink_keyframe_every,
                         "downlink_retention": downlink_retention}

    if server_mode == "async":
        # remap the selected sync server class onto its barrier-free
        # counterpart (fedml_tpu/async_agg): same wire seams, async tally
        from fedml_tpu.async_agg.server import (
            AsyncCompressedFedAvgServerManager,
            AsyncFedAvgServerManager,
            AsyncRobustFedAvgServerManager,
        )

        async_cls = {
            None: AsyncFedAvgServerManager,
            CompressedFedAvgServerManager: AsyncCompressedFedAvgServerManager,
        }
        if robust_config is not None:
            from fedml_tpu.algorithms.robust_distributed import (
                RobustCompressedFedAvgServerManager,
                RobustFedAvgServerManager,
            )

            if server_cls is RobustCompressedFedAvgServerManager:
                raise NotImplementedError(
                    "server_mode='async' composes with a codec OR a robust "
                    "defense, not both at once yet"
                )
            async_cls[RobustFedAvgServerManager] = AsyncRobustFedAvgServerManager
        server_cls = async_cls[server_cls]
        server_kwargs = {**(server_kwargs or {}),
                         "buffer_goal": buffer_goal,
                         "staleness_weight": staleness_weight,
                         "async_stats": async_stats}

    results: dict[str, np.ndarray] = {}

    def _done(r, f):
        results["final"] = f
        if comm_stats is not None and server.accountant is not None:
            comm_stats.setdefault("rounds", []).append(
                server.accountant.round_record(r)
            )
        if fleet_stats is not None:
            # same ordering contract as comm_stats: the fleet record is
            # flushed BEFORE on_round_done so a by-round metrics merge (or
            # an incremental JSONL writer) finds it
            if _sysstats is not None:
                _sysstats.publish_device_gauges()
            rec = server._fleet_round_record(r)
            if rec is not None:
                fleet_stats.setdefault("rounds", []).append(rec)
        if on_round_done is not None:
            on_round_done(r, unpack_pytree(f, desc))

    server = (server_cls or FedAvgServerManager)(
        make_comm(0), worker_num, round_num, flat, desc,
        client_num_in_total=train_data.num_clients,
        round_timeout=round_timeout,
        on_round_done=_done,
        **(server_kwargs or {}),
    )
    if resume:
        if ckptr is None:
            raise ValueError("resume=True requires checkpoint_dir")
        if ckptr.latest_server_round() is not None:
            server.restore_from_checkpoint()
            if server.round_idx >= round_num:
                # every round already closed before the crash: nothing to
                # re-run — the checkpointed global IS the final model
                server.comm.stop_receive_message()
                if fleet_stats is not None:
                    # nothing ran, but the caller still gets a renderable
                    # (empty) fleet view instead of a null totals key that
                    # crashes tools/fleet_report.py
                    fleet_stats["totals"] = fleet.snapshot()
                return unpack_pytree(server.global_flat, desc)
        else:
            logging.info("resume requested but no server checkpoint under "
                         "%s; starting fresh", checkpoint_dir)
    cls_for = client_cls_for_rank or (lambda r: FedAvgClientManager)
    clients = [
        cls_for(r)(
            make_comm(r), r, worker_num + 1, trainer,
            train_data, batch_size, template,
        )
        for r in range(1, worker_num + 1)
    ]
    if fleet_stats is not None:
        for c in clients:
            c.fleet_telemetry = True
    if downlink_codec is not None:
        # every client decodes with the SAME codec object the server
        # encodes with (one shared jitted decode program — the bit-exact
        # held == decoded contract depends on it)
        for c in clients:
            c.downlink_codec = downlink_codec
    if population is not None:
        # per-rank population profile (speed / predicted step fraction):
        # fleet-telemetry-armed clients piggyback predicted-vs-actual step
        # gauges from it so fleet_report renders the churn
        for c in clients:
            c.population_profile = population.profiles.get(c.rank)

    # cross-rank causal tracing (docs/OBSERVABILITY.md): ``trace_wire``
    # arms the context stamp on every rank's transport (the explicit
    # per-manager opt-in — same discipline as fleet_telemetry above);
    # ``trace_lanes`` additionally installs one job-scoped tracer per rank
    # lane and exports trace_rank<N>.jsonl files for tools/trace_merge.py
    client_lanes = None
    if trace_lanes is not None:
        trace_wire = True
        client_lanes = [f"rank{c.rank}" for c in clients]
    if trace_wire:
        server.comm.trace_wire = True
        for c in clients:
            c.comm.trace_wire = True

    from fedml_tpu.comm.retry import retry_stats

    retries_before = retry_stats()["retries"]
    heartbeats = []
    if heartbeat_interval is not None:
        from fedml_tpu.comm.status import HeartbeatSender

        heartbeats = [
            HeartbeatSender(c.comm, c.rank, heartbeat_interval).start()
            for c in clients
        ]
    # fleet telemetry needs the process registry installed so clients
    # collect + piggyback; reuse an outer scope's registry when one exists
    _installed_registry = None
    if fleet_stats is not None and registry.get() is None:
        _installed_registry = registry.install()
    try:
        if trace_lanes is not None:
            with trace.lane_traces(trace_lanes, ["rank0"] + client_lanes):
                run_manager_protocol(server, clients,
                                     client_lanes=client_lanes,
                                     server_lane="rank0")
        else:
            run_manager_protocol(server, clients)
    finally:
        for hb in heartbeats:
            hb.stop()
        if fleet_stats is not None:
            if fleet is not None:
                fleet_stats["totals"] = fleet.snapshot()
            reg = registry.get()
            if reg is not None:
                fleet_stats["registry"] = reg.snapshot()
            if _installed_registry is not None \
                    and registry.get() is _installed_registry:
                registry.uninstall()
    if comm_stats is not None:
        from fedml_tpu.obs import metrics as metricslib

        if server.accountant is not None:
            comm_stats["totals"] = server.accountant.totals()
        if retry_policy is not None:
            comm_stats.setdefault("totals", {})[metricslib.COMM_RETRY_COUNT] = (
                retry_stats()["retries"] - retries_before
            )
        comm_stats.setdefault("totals", {})[metricslib.COMM_STALE_UPLOADS] = (
            int(getattr(server, "stale_uploads", 0))
        )
    if async_stats is not None and hasattr(server, "async_totals"):
        async_stats["totals"] = server.async_totals()
    return unpack_pytree(results["final"], desc)


def run_distributed_fedavg_loopback(
    trainer: ClientTrainer,
    train_data: FederatedArrays,
    worker_num: int,
    round_num: int,
    batch_size: int,
    seed: int = 0,
    on_round_done: Callable[[int, Any], None] | None = None,
    init_overrides=None,
    **runner_kwargs,
):
    """Distributed FedAvg on the in-process loopback fabric."""
    from fedml_tpu.comm.loopback import LoopbackCommManager, LoopbackFabric

    fabric = LoopbackFabric(worker_num + 1)
    return run_distributed_fedavg(
        trainer, train_data, worker_num, round_num, batch_size,
        lambda r: LoopbackCommManager(fabric, r), seed=seed,
        on_round_done=on_round_done, init_overrides=init_overrides,
        **runner_kwargs,
    )


def run_distributed_fedavg_shm(
    trainer: ClientTrainer,
    train_data: FederatedArrays,
    worker_num: int,
    round_num: int,
    batch_size: int,
    seed: int = 0,
    job: str | None = None,
    on_round_done: Callable[[int, Any], None] | None = None,
    init_overrides=None,
    **runner_kwargs,
):
    """Distributed FedAvg over the native shared-memory rings (the MPI-role
    single-host transport, comm/shm.py + ops/native/shm_ring.cpp)."""
    import uuid

    from fedml_tpu.comm.shm import ShmCommManager

    job = job or f"fedavg_{uuid.uuid4().hex[:8]}"
    mgrs = {
        r: ShmCommManager(job, r, worker_num + 1) for r in range(worker_num + 1)
    }
    try:
        return run_distributed_fedavg(
            trainer, train_data, worker_num, round_num, batch_size,
            lambda r: mgrs[r], seed=seed, on_round_done=on_round_done,
            init_overrides=init_overrides, **runner_kwargs,
        )
    finally:
        for m in mgrs.values():
            m.cleanup()


def run_distributed_fedavg_grpc(
    trainer: ClientTrainer,
    train_data: FederatedArrays,
    worker_num: int,
    round_num: int,
    batch_size: int,
    seed: int = 0,
    base_port: int = 29500,
    send_timeout: float = 600.0,
    send_workers: int = 4,
    on_round_done: Callable[[int, Any], None] | None = None,
    init_overrides=None,
    **runner_kwargs,
):
    """Distributed FedAvg over localhost gRPC (cross-host transport run
    single-host; an ip_config table generalizes it to a cluster, reference
    grpc_ipconfig.csv). ``send_timeout``/``send_workers`` plumb the run
    config into every rank's transport (per-send unary deadline and
    broadcast send-pool width)."""
    from fedml_tpu.comm.grpc_backend import GRPCCommManager

    ip_config = {
        r: ("127.0.0.1", base_port + r) for r in range(worker_num + 1)
    }
    mgrs = {
        r: GRPCCommManager(r, ip_config, send_timeout=send_timeout,
                           send_workers=send_workers)
        for r in range(worker_num + 1)
    }
    try:
        return run_distributed_fedavg(
            trainer, train_data, worker_num, round_num, batch_size,
            lambda r: mgrs[r], seed=seed, on_round_done=on_round_done,
            init_overrides=init_overrides, **runner_kwargs,
        )
    finally:
        for m in mgrs.values():
            m.stop_receive_message()


def run_distributed_fedavg_mqtt_s3(
    trainer: ClientTrainer,
    train_data: FederatedArrays,
    worker_num: int,
    round_num: int,
    batch_size: int,
    seed: int = 0,
    store_dir: str | None = None,
    mqtt_host: str | None = None,
    mqtt_port: int = 1883,
    topic: str = "fedml",
    threshold_bytes: int = 1 << 14,
    broadcast_generations: int = 2,
    on_round_done: Callable[[int, Any], None] | None = None,
    init_overrides=None,
    **runner_kwargs,
):
    """Distributed FedAvg over the production WAN combination: control
    messages on MQTT topics, model payloads through an object store keyed by
    reference (the reference's MQTT_S3 backend,
    mqtt_s3_multi_clients_comm_manager.py:178-249 / client_manager.py:28-50).

    ``mqtt_host=None`` (offline default) runs the real MqttCommManager logic
    over the in-process broker (comm/inproc_broker.py); a host string
    connects through real paho. The store is a FileSystemStore under
    ``store_dir`` — the S3Store drops in via the same ObjectStore interface.
    ``broadcast_generations`` is the sender-side shared-blob retention
    (how many newer fan-outs exist before a broadcast blob is retired);
    the async server additionally raises it in place from its observed
    staleness p99 when the downlink delta plane is armed, so a
    deliberately slow client never 404s its delta base.
    """
    import tempfile

    from fedml_tpu.comm.mqtt_backend import MqttCommManager
    from fedml_tpu.comm.object_store import FileSystemStore, OffloadCommManager

    factory = None
    if mqtt_host is None:
        from fedml_tpu.comm.inproc_broker import InProcessBroker

        factory = InProcessBroker().client_factory()
        mqtt_host = "inproc"
    tmp_store = None
    if store_dir is None:
        tmp_store = tempfile.mkdtemp(prefix="fedml_store_")
    store_root = store_dir or tmp_store

    def make_comm(rank: int):
        inner = MqttCommManager(
            mqtt_host, mqtt_port, topic=topic, client_id=rank,
            client_num=worker_num, client_factory=factory,
        )
        return OffloadCommManager(
            inner, FileSystemStore(store_root),
            threshold_bytes=threshold_bytes,
            broadcast_generations=broadcast_generations,
        )

    mgrs = {r: make_comm(r) for r in range(worker_num + 1)}
    try:
        return run_distributed_fedavg(
            trainer, train_data, worker_num, round_num, batch_size,
            lambda r: mgrs[r], seed=seed, on_round_done=on_round_done,
            init_overrides=init_overrides, **runner_kwargs,
        )
    finally:
        for m in mgrs.values():
            m.stop_receive_message()
        if tmp_store is not None:
            import shutil

            shutil.rmtree(tmp_store, ignore_errors=True)
