"""Cross-silo FL: WAN federation between silos, data parallelism within.

Reference: fedml_api/distributed/fedavg_cross_silo/ — each silo runs a
master process (ClientMasterManager.py:32) plus DDP slave processes over the
silo's GPUs (ClientSlaveManager.py:4, process_group_manager.py:23-27 builds
the in-silo torch process group), and masters talk to the FL server over the
WAN transport.

TPU composition: the whole slave/master choreography collapses into one
jitted program per silo — the silo's local epochs run with the batch axis
sharded over the silo's device mesh (XLA inserts the in-silo gradient
all-reduce the way DDP would), and the silo exchanges models with the FL
server through the ordinary message protocol (grpc/object-store for real
WANs, loopback/shm in tests). The server is the unmodified distributed
FedAvg server — cross-silo is a client-side composition, not a new protocol.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.fedavg_distributed import (
    FedAvgClientManager,
    FedAvgServerManager,
    init_template,
    run_manager_protocol,
)
from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import unpack_pytree
from fedml_tpu.core.trainer import ClientTrainer, make_local_train
from fedml_tpu.parallel import mesh as meshlib
from fedml_tpu.sim.cohort import FederatedArrays



def make_silo_local_train(trainer: ClientTrainer, silo_mesh) -> Callable:
    """The in-silo data-parallel round program: batches [S, B, ...] run with
    B sharded over the silo axis; parameter gradients all-reduce across the
    silo automatically (GSPMD) — the reference's DDP process group
    (process_group_manager.py:23-27) as one sharding annotation."""
    local_train = make_local_train(trainer)
    axis = (
        meshlib.SILO_AXIS
        if meshlib.SILO_AXIS in silo_mesh.axis_names
        else silo_mesh.axis_names[0]
    )
    batch_spec = P(None, axis)  # [steps, batch, ...]
    rep = NamedSharding(silo_mesh, P())

    @jax.jit
    def fn(variables, batches, rng):
        batches = jax.lax.with_sharding_constraint(
            batches, NamedSharding(silo_mesh, batch_spec)
        )
        variables = jax.lax.with_sharding_constraint(
            variables, rep
        )
        return local_train(variables, batches, rng)

    return fn


def run_cross_silo(
    trainer: ClientTrainer,
    silo_data: list[FederatedArrays],
    round_num: int,
    batch_size: int,
    make_comm: Callable[[int], BaseCommunicationManager],
    silo_meshes: list | None = None,
    seed: int = 0,
    on_round_done: Callable[[int, Any], None] | None = None,
):
    """End-to-end cross-silo FedAvg: one FL server + one manager per silo,
    each silo training data-parallel over its mesh. ``silo_data[i]`` is silo
    i's private dataset (single-client FederatedArrays: in cross-silo the
    silo IS the client, reference fedavg_cross_silo semantics); transports
    come from ``make_comm`` (grpc + object-store offload for real WANs).
    Returns the final global variables."""
    n_silos = len(silo_data)
    if silo_meshes is None:
        # one silo group spanning the local devices (clients axis size 1:
        # within a silo manager, the silo IS the single client)
        silo_meshes = [meshlib.silo_mesh(1)] * n_silos

    template, flat, desc = init_template(
        trainer, silo_data[0].arrays, batch_size, seed
    )

    results: dict[str, np.ndarray] = {}

    def _done(r, f):
        results["final"] = f
        if on_round_done is not None:
            on_round_done(r, unpack_pytree(f, desc))

    server = FedAvgServerManager(
        make_comm(0), n_silos, round_num, flat, desc,
        client_num_in_total=n_silos, on_round_done=_done,
    )
    # one compiled in-silo program per distinct mesh (identical silos would
    # otherwise pay n_silos identical XLA compiles)
    train_fns: dict[int, Callable] = {}

    def _silo_fn(mesh):
        key = id(mesh)
        if key not in train_fns:
            train_fns[key] = make_silo_local_train(trainer, mesh)
        return train_fns[key]

    # in-process execution serialization: every silo mesh spans the SAME
    # local devices (silo_mesh(1) above), so the silo threads' in-silo
    # programs contend for one device set — and on XLA:CPU two concurrently
    # dispatched GSPMD executables intermittently DEADLOCK in the runtime
    # thread pool (both client threads stuck in _local_train forever, the
    # pre-existing tier-1 cross-silo hang). Real cross-silo runs one
    # process per silo; in the in-process harness the shared device set
    # serializes execution anyway, so the lock costs no real parallelism
    # and removes the deadlock.
    exec_lock = threading.Lock()

    def _serialized(fn):
        def wrapped(*args):
            with exec_lock:
                return fn(*args)

        return wrapped

    clients = []
    for r in range(1, n_silos + 1):
        # full participation assigns worker r the global client index r-1;
        # key the silo's single private shard under that index
        data = silo_data[r - 1]
        if len(data.partition) != 1:
            raise ValueError(
                f"silo {r - 1}: cross-silo data must be a single-client "
                f"FederatedArrays (the silo IS the client); got "
                f"{len(data.partition)} partition entries"
            )
        keyed = FederatedArrays(
            data.arrays, {r - 1: next(iter(data.partition.values()))}
        )
        clients.append(
            FedAvgClientManager(
                make_comm(r), r, n_silos + 1, trainer,
                keyed, batch_size, template,
                local_train_fn=_serialized(_silo_fn(silo_meshes[r - 1])),
            )
        )
    run_manager_protocol(server, clients)
    if "final" not in results:
        raise RuntimeError("cross-silo run produced no final model")
    return unpack_pytree(results["final"], desc)
