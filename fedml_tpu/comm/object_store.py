"""Split control-plane / data-plane transport: the MQTT+S3 production pattern.

Reference: fedml_core/distributed/communication/mqtt_s3/ — control messages
ride MQTT while model payloads are uploaded to S3 and referenced by key
(mqtt_s3_multi_clients_comm_manager.py:178-215 download, 222+ upload;
remote_storage.py:14 ``S3Storage.write_model`` joblib-pickle → S3 + presigned
URL). Two reference defects not ported: pickled payloads (typed arrays here)
and the hard S3 dependency (the store is pluggable; a filesystem store covers
single-host/NFS deployments and tests, an S3 store activates when boto3
exists).

``OffloadCommManager`` wraps ANY base backend (loopback/shm/grpc/mqtt): on
send, array params bigger than ``threshold_bytes`` move to the object store
and the message carries ``{key}`` references (the reference's
MSG_ARG_KEY_MODEL_PARAMS → MODEL_PARAMS_URL swap); on receive they are
resolved back before observers see the message.
"""

from __future__ import annotations

import abc
import os
import threading
import uuid
from pathlib import Path

import numpy as np

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message


class ObjectStore(abc.ABC):
    """Data-plane blob store (reference S3Storage, remote_storage.py:14)."""

    @abc.abstractmethod
    def put(self, key: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> bytes: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...


class FileSystemStore(ObjectStore):
    """Directory-backed store — the S3 analogue for single-host / shared-FS
    deployments and hermetic tests (no reference equivalent; their tests hit
    real S3)."""

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        safe = key.replace("/", "_")
        return self.root / safe

    def put(self, key: str, data: bytes) -> None:
        tmp = self._path(key).with_suffix(".tmp-" + uuid.uuid4().hex[:8])
        tmp.write_bytes(data)
        tmp.rename(self._path(key))  # atomic publish

    def get(self, key: str) -> bytes:
        return self._path(key).read_bytes()

    def delete(self, key: str) -> None:
        self._path(key).unlink(missing_ok=True)


class S3Store(ObjectStore):
    """boto3-backed store (reference remote_storage.py:33 write_model /
    :50 read_model, with retries). Import is deferred: constructing raises a
    clear error when boto3 is absent."""

    def __init__(self, bucket: str, prefix: str = "fedml", **client_kwargs):
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "S3Store requires boto3; use FileSystemStore or install boto3"
            ) from e
        import boto3

        self.bucket = bucket
        self.prefix = prefix
        self.client = boto3.client("s3", **client_kwargs)

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}"

    def put(self, key: str, data: bytes) -> None:
        self.client.put_object(Bucket=self.bucket, Key=self._key(key), Body=data)

    def get(self, key: str) -> bytes:
        return self.client.get_object(Bucket=self.bucket, Key=self._key(key))["Body"].read()

    def delete(self, key: str) -> None:
        self.client.delete_object(Bucket=self.bucket, Key=self._key(key))


# ---------------------------------------------------------------------------


_OFFLOADED = "__offloaded__"  # header key: {param_key: store_key, ...}
# large TEXT payloads (e.g. the is_mobile nested-list JSON wire) ride the
# store too — raw utf-8 blobs under their own header so the receive side
# restores a str, not an array
_OFFLOADED_TEXT = "__offloaded_text__"
# marker on broadcast control messages: the referenced blobs are shared by
# every receiver of the fan-out, so receiver-side cleanup is suppressed and
# the SENDER retires them generationally instead
_OFFLOAD_SHARED = "__offload_shared__"


class OffloadCommManager(BaseCommunicationManager):
    """Control-plane messages over ``inner``, large arrays via ``store``.

    Mirrors MqttS3MultiClientsCommManager's send/receive payload swap
    (mqtt_s3_multi_clients_comm_manager.py:178-249) for any base transport.
    """

    def __init__(self, inner: BaseCommunicationManager, store: ObjectStore,
                 threshold_bytes: int = 1 << 16, cleanup: bool = True,
                 broadcast_generations: int = 2):
        super().__init__()
        self.inner = inner
        self.store = store
        self.threshold = threshold_bytes
        self.cleanup = cleanup
        # broadcast blobs are shared by all receivers, so the sender retires
        # them: a generation is deleted once `broadcast_generations` newer
        # fan-outs exist (2 keeps a one-round-stale straggler downloadable).
        # Configurable from the mqtt_s3 runner/CLI (--broadcast_generations),
        # and raised IN PLACE by the async server when the downlink delta
        # plane is armed — the floor tracks the observed staleness p99
        # (compress/downlink.py), so a deliberately slow client's delta-base
        # blob is still downloadable when it finally fetches. Reads happen
        # under _bcast_lock at trim time, so a concurrent raise is safe.
        self.broadcast_generations = max(1, int(broadcast_generations))
        self._bcast_lock = threading.Lock()
        self._bcast_gens: list[list[str]] = []  # guarded-by: _bcast_lock
        self._resolver = _Resolver(self)
        self.inner.add_observer(self._resolver)

    # -- send path ----------------------------------------------------------

    def _put(self, key: str, data: bytes) -> None:
        """Data-plane upload, under the retry plane when one is armed: a
        transient object-store hiccup is exactly the failure comm/retry.py
        exists for, and the put happens before any per-destination send
        isolation could cover it."""
        policy = self.retry_policy
        if policy is None:
            self.store.put(key, data)
        else:
            policy.run(lambda: self.store.put(key, data), store_key=key)

    def _offload_params(self, msg: Message) -> tuple[Message, dict[str, str], dict[str, str]]:
        """Upload every over-threshold array/text param once and strip it
        from a shallow copy of ``msg`` (the caller's Message stays intact so
        it can be reused). Returns (stripped message, array key table, text
        key table) — one definition shared by the per-receiver and broadcast
        send paths."""
        offloaded: dict[str, str] = {}
        offloaded_text: dict[str, str] = {}
        out = Message()
        out.msg_params = dict(msg.msg_params)
        for k, v in list(out.msg_params.items()):
            if isinstance(v, np.ndarray) and v.nbytes >= self.threshold:
                key = f"{k}-{uuid.uuid4().hex}"
                self._put(key, _array_bytes(v))
                offloaded[k] = key
                del out.msg_params[k]
            elif isinstance(v, str) and len(v) >= self.threshold:
                key = f"{k}-{uuid.uuid4().hex}"
                self._put(key, v.encode("utf-8"))
                offloaded_text[k] = key
                del out.msg_params[k]
        if offloaded:
            out.add_params(_OFFLOADED, offloaded)
        if offloaded_text:
            out.add_params(_OFFLOADED_TEXT, offloaded_text)
        return out, offloaded, offloaded_text

    def send_message(self, msg: Message) -> None:
        # each send uploads fresh blobs, which matters with cleanup=True —
        # the first receiver deletes them
        out, _, _ = self._offload_params(msg)
        self.inner.send_message(out)

    def broadcast_message(self, msg: Message, receiver_ids,
                          per_receiver: dict[int, dict] | None = None) -> None:
        """Encode-once for the data plane too: each large payload is uploaded
        to the store ONCE for the whole fan-out (vs once per receiver on the
        legacy path) and every receiver resolves the same key. Shared blobs
        are retired by the sender once ``broadcast_generations`` newer
        fan-outs exist — safe in round-synchronous protocols, where a
        receiver is at most one round stale before being dropped."""
        out, offloaded, offloaded_text = self._offload_params(msg)
        if offloaded or offloaded_text:
            out.add_params(_OFFLOAD_SHARED, 1)
            stale: list[str] = []
            with self._bcast_lock:
                self._bcast_gens.append(
                    list(offloaded.values()) + list(offloaded_text.values())
                )
                while len(self._bcast_gens) > self.broadcast_generations:
                    stale.extend(self._bcast_gens.pop(0))
            if self.cleanup:
                for key in stale:
                    try:
                        self.store.delete(key)
                    except OSError:
                        pass
        # the retry plane (comm/retry.py) arms the OUTERMOST manager; the
        # fan-out legs run inside the inner transport, so delegate the
        # policy there for the duration of this composition
        self.inner.retry_policy = self.retry_policy
        self.inner.broadcast_message(out, receiver_ids, per_receiver)

    # -- receive path -------------------------------------------------------

    def _resolve(self, msg: Message) -> Message:
        shared = bool(msg.get(_OFFLOAD_SHARED))
        for header, restore in ((_OFFLOADED, _bytes_array),
                                (_OFFLOADED_TEXT, lambda b: b.decode("utf-8"))):
            table = msg.get(header)
            if not table:
                continue
            for param_key, store_key in table.items():
                msg.add_params(param_key, restore(self.store.get(store_key)))
                if self.cleanup and not shared:
                    try:
                        self.store.delete(store_key)
                    except OSError:
                        pass
            del msg.msg_params[header]
        msg.msg_params.pop(_OFFLOAD_SHARED, None)
        return msg

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        # The last `broadcast_generations` fan-outs' blobs deliberately
        # OUTLIVE the sender: the final stop broadcast is usually still being
        # resolved by receivers when the sender stops, and deleting under
        # them fails their receive threads. Bounded leak (generation rotation
        # retires everything older); harnesses that know the protocol fully
        # drained can call retire_broadcast_blobs().
        self.inner.stop_receive_message()

    def retire_broadcast_blobs(self) -> None:
        """Delete ALL shared broadcast blobs this sender still tracks. Only
        safe once every receiver has resolved the final fan-out."""
        with self._bcast_lock:
            gens, self._bcast_gens = self._bcast_gens, []
        for keys in gens:
            for key in keys:
                try:
                    self.store.delete(key)
                except OSError:
                    pass


class _Resolver(Observer):
    def __init__(self, outer: OffloadCommManager):
        self.outer = outer

    def receive_message(self, msg_type: int, msg: Message) -> None:
        self.outer.notify(self.outer._resolve(msg))


def _array_bytes(a: np.ndarray) -> bytes:
    """Self-describing array blob: dtype/shape header + raw bytes."""
    import json

    a = np.ascontiguousarray(a)
    head = json.dumps({"dtype": str(a.dtype), "shape": list(a.shape)}).encode()
    return len(head).to_bytes(4, "little") + head + a.tobytes()


def _bytes_array(data: bytes) -> np.ndarray:
    import json

    hlen = int.from_bytes(data[:4], "little")
    head = json.loads(data[4 : 4 + hlen].decode())
    return np.frombuffer(
        data, dtype=np.dtype(head["dtype"]),
        count=int(np.prod(head["shape"])) if head["shape"] else 1,
        offset=4 + hlen,
    ).reshape(head["shape"])
