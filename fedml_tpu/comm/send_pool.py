"""Bounded send-worker pool for concurrent downlink fan-out.

The reference server (and this repo's managers until the wire-path rebuild)
sent every downlink message as a blocking unary call on the manager thread:
a broadcast to N workers serialized N round-trips — each with a multi-minute
timeout budget — before the receive loop could run again. The pool runs the
per-receiver sends of one broadcast concurrently so downlink wall time is
the slowest single send, not the sum.

Ordering contract: each destination is hashed to ONE worker thread, so two
sends to the same receiver can never reorder (the per-backend FIFO the
protocol layers rely on survives pooling); sends to different receivers run
concurrently. :meth:`SendWorkerPool.run_all` is a barrier — it returns after
every submitted send completed — so a broadcast call keeps its synchronous
semantics while its legs overlap. Failures are per-destination isolated:
every leg runs to completion regardless of the others, and ALL errors are
collected into one :class:`BroadcastSendError` naming the destination ranks
(a multi-receiver outage used to be reported as a single anonymous failure).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable


class BroadcastSendError(RuntimeError):
    """One or more per-destination sends of a fan-out failed. ``errors``
    maps destination rank -> the exception its send raised; the message
    names every failed rank so a multi-receiver outage is diagnosable from
    the log alone. Raised by :meth:`SendWorkerPool.run_all` and by the
    serial broadcast path in ``comm.base``."""

    def __init__(self, errors: dict[int, BaseException]):
        self.errors = dict(errors)
        detail = "; ".join(
            f"dst {d}: {type(e).__name__}: {e}"
            for d, e in sorted(self.errors.items())
        )
        super().__init__(
            f"broadcast failed to {len(self.errors)} receiver(s) "
            f"{sorted(self.errors)} — {detail}"
        )


class SendWorkerPool:
    """K worker threads, each owning a FIFO; destinations hash to workers."""

    def __init__(self, workers: int = 4, name: str = "comm-send"):
        self.workers = max(1, int(workers))
        self._name = name
        self._queues: list[queue.SimpleQueue] = [
            queue.SimpleQueue() for _ in range(self.workers)
        ]
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()
        self._started = False  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock

    def _ensure_started(self) -> None:
        with self._lock:
            if self._closed:
                raise RuntimeError(f"send pool {self._name!r} is closed")
            if self._started:
                return
            for i, q in enumerate(self._queues):
                t = threading.Thread(
                    target=self._worker, args=(q,),
                    name=f"{self._name}-{i}", daemon=True,
                )
                t.start()
                self._threads.append(t)
            self._started = True

    @staticmethod
    def _worker(q: queue.SimpleQueue) -> None:
        while True:
            fn = q.get()
            if fn is None:
                return
            fn()

    def run_all(self, tasks: list[tuple[int, Callable[[], None]]],
                timeout: float | None = None) -> None:
        """Run ``(destination, send_fn)`` tasks on the pool and block until
        all complete. Same-destination tasks run in submission order on one
        worker; distinct destinations overlap. Every task runs to
        completion regardless of other tasks' failures; if any failed, a
        :class:`BroadcastSendError` naming ALL failed destinations is
        raised."""
        if not tasks:
            return
        self._ensure_started()
        errors: dict[int, BaseException] = {}
        done = threading.Event()
        state_lock = threading.Lock()
        remaining = [len(tasks)]

        def wrap(dst: int, fn: Callable[[], None]) -> Callable[[], None]:
            def run() -> None:
                try:
                    fn()
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    with state_lock:
                        errors[dst] = e
                finally:
                    with state_lock:
                        remaining[0] -= 1
                        if remaining[0] == 0:
                            done.set()
            return run

        for dst, fn in tasks:
            self._queues[hash(dst) % self.workers].put(wrap(dst, fn))
        if not done.wait(timeout):
            raise TimeoutError(
                f"{remaining[0]} of {len(tasks)} pooled sends still pending "
                f"after {timeout}s"
            )
        if errors:
            raise BroadcastSendError(errors)

    def submit(self, dst: int, fn: Callable[[], None]) -> None:
        """Non-barrier enqueue: run ``fn`` on ``dst``'s worker, in submission
        order with every other send to ``dst``, and return immediately.
        Completion/error signaling is the caller's job (``fn`` must capture
        its own done/error channel) — the fair fan-out scheduler
        (tenancy/scheduler.py) dispatches its deficit-round-robin legs
        through this, keeping the per-destination FIFO contract while jobs'
        fan-outs interleave."""
        self._ensure_started()
        self._queues[hash(dst) % self.workers].put(fn)

    def close(self, timeout: float = 5.0) -> None:
        """Stop the workers (idempotent). Queued work submitted before close
        still drains; ``run_all`` after close raises."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            started = self._started
        if started:
            for q in self._queues:
                q.put(None)
            for t in self._threads:
                t.join(timeout)

    @property
    def alive_workers(self) -> int:
        return sum(t.is_alive() for t in self._threads)
