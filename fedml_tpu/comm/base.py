"""Communication backend contract.

Reference: fedml_core/distributed/communication/base_com_manager.py:7
(``BaseCommunicationManager``: send_message / add_observer /
handle_receive_message / stop_receive_message) and observer.py:4
(``Observer.receive_message(msg_type, msg_params)``). Contract preserved;
backends here are push-driven (no 0.3 s polling loop — the reference defect
listed in SURVEY §7 'what NOT to port').
"""

from __future__ import annotations

import abc
from typing import TYPE_CHECKING

from fedml_tpu.obs import trace

if TYPE_CHECKING:
    from fedml_tpu.comm.message import Message


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: int, msg: "Message") -> None: ...


class BaseCommunicationManager(abc.ABC):
    def __init__(self):
        self._observers: list[Observer] = []

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def notify(self, msg: "Message") -> None:
        tracer = trace.get()
        if tracer is None:  # disabled path: skip the payload-size walk too
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)
            return
        with tracer.span("comm/recv", msg_type=msg.get_type(),
                         sender=msg.get_sender_id(),
                         receiver=msg.get_receiver_id(),
                         bytes=msg.payload_nbytes()):
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    @abc.abstractmethod
    def send_message(self, msg: "Message") -> None: ...

    @abc.abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching incoming messages to observers, until stopped."""

    @abc.abstractmethod
    def stop_receive_message(self) -> None: ...
