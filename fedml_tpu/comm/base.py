"""Communication backend contract.

Reference: fedml_core/distributed/communication/base_com_manager.py:7
(``BaseCommunicationManager``: send_message / add_observer /
handle_receive_message / stop_receive_message) and observer.py:4
(``Observer.receive_message(msg_type, msg_params)``). Contract preserved;
backends here are push-driven (no 0.3 s polling loop — the reference defect
listed in SURVEY §7 'what NOT to port').

On top of the reference surface the contract grows the high-throughput
downlink primitive (docs/PERFORMANCE.md "The server wire path"):
``broadcast_message`` frames a message ONCE (one payload serialization for
the whole fan-out) and emits one wire copy per receiver through the
``_send_framed`` backend hook, optionally overlapping the per-receiver sends
on a bounded :class:`~fedml_tpu.comm.send_pool.SendWorkerPool`.
"""

from __future__ import annotations

import abc
from functools import partial
from typing import TYPE_CHECKING

from fedml_tpu.obs import trace

if TYPE_CHECKING:
    from fedml_tpu.comm.message import FramedMessage, Message
    from fedml_tpu.comm.retry import RetryPolicy
    from fedml_tpu.comm.send_pool import SendWorkerPool


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: int, msg: "Message") -> None: ...


class BaseCommunicationManager(abc.ABC):
    def __init__(self, send_pool: "SendWorkerPool | None" = None,
                 retry_policy: "RetryPolicy | None" = None):
        self._observers: list[Observer] = []
        self._send_pool = send_pool
        # retry/backoff send plane (docs/ROBUSTNESS.md "Failure recovery"):
        # when set, every broadcast leg (and the manager-layer unary send)
        # is re-attempted under the policy instead of failing the protocol
        # on the first transient transport error. Settable post-construction
        # (``mgr.retry_policy = policy``) so run harnesses can arm it on any
        # backend — including a fault-injection wrapper, whose seeded draws
        # then re-roll per attempt.
        self.retry_policy = retry_policy
        # cross-rank causal tracing opt-in (docs/OBSERVABILITY.md
        # "Cross-rank causal tracing"): when armed by the run harness
        # (same explicit-flag discipline as ``fleet_telemetry`` — never
        # inferred from a tracer being installed), the send/broadcast paths
        # stamp MSG_ARG_KEY_TRACE_CTX on outgoing headers and the receive
        # path links comm/recv spans to the sender's context. Off (the
        # default), wire bytes are identical to a pre-tracing build.
        self.trace_wire = False

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def stamp_trace_ctx(self, msg: "Message") -> None:
        """Stamp the calling thread's trace context on ``msg`` when the
        ``trace_wire`` opt-in is armed and a tracer resolves; no-op (and
        zero wire-byte change) otherwise. Callers stamp INSIDE their
        comm/send span so the context's span id names that send leg."""
        if not self.trace_wire:
            return
        ctx = trace.wire_ctx(origin=msg.get_sender_id())
        if ctx is not None:
            from fedml_tpu.comm.message import Message

            msg.add_params(Message.MSG_ARG_KEY_TRACE_CTX, ctx)

    def notify(self, msg: "Message") -> None:
        tracer = trace.get()
        if tracer is None:  # disabled path: skip the payload-size walk too
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)
            return
        from fedml_tpu.comm.message import Message

        ctx = msg.get(Message.MSG_ARG_KEY_TRACE_CTX)
        ctx_args = {}
        if isinstance(ctx, dict):
            # the incoming context opens this recv as a causal child of the
            # sender's send span: trace_merge matches (ctx_lane, ctx_span)
            # to that span's (lane, span_id) across per-rank files
            ctx_args = {"ctx_span": ctx.get("span"),
                        "ctx_lane": ctx.get("lane"),
                        "ctx_rank": ctx.get("rank"),
                        "ctx_sent_at": ctx.get("sent_at")}
        with tracer.span("comm/recv", msg_type=msg.get_type(),
                         sender=msg.get_sender_id(),
                         receiver=msg.get_receiver_id(),
                         bytes=msg.payload_nbytes(), **ctx_args):
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    @abc.abstractmethod
    def send_message(self, msg: "Message") -> None: ...

    def broadcast_message(self, msg: "Message",
                          receiver_ids: list[int],
                          per_receiver: dict[int, dict] | None = None) -> None:
        """Encode-once fan-out: frame ``msg`` once and send one wire copy to
        every receiver (the per-receiver header is patched, the payload
        segments are shared). ``per_receiver`` carries small header-only
        param overrides keyed by receiver (e.g. each worker's assigned
        client index); array overrides are rejected by the frame.

        With a send pool installed the per-receiver sends run concurrently
        and this call returns after all of them completed — downlink wall
        time is the slowest leg, not the sum.

        Failure handling is per-destination isolated: each leg runs under
        ``retry_policy`` (when set), one dead receiver never aborts or
        masks the other legs, and all exhausted legs are reported together
        as a :class:`~fedml_tpu.comm.send_pool.BroadcastSendError` naming
        the destination ranks.
        """
        frame = msg.frame()
        frame.tail_bytes()  # join the shared payload ONCE, before pooled
        # legs race the lazy cache and each redo the O(payload) join
        msg_type, sender = msg.get_type(), msg.get_sender_id()
        nbytes = frame.payload_nbytes

        def send_one(dst: int) -> None:
            ov = per_receiver.get(dst) if per_receiver else None
            policy = self.retry_policy
            with trace.span("comm/send", msg_type=msg_type, sender=sender,
                            receiver=dst, bytes=nbytes, broadcast=1):
                if self.trace_wire:
                    # stamped inside the span so the context names THIS
                    # leg; rides the header-only override path (the shared
                    # payload segments stay one serialization)
                    ctx = trace.wire_ctx(origin=sender)
                    if ctx is not None:
                        from fedml_tpu.comm.message import Message

                        ov = dict(ov) if ov else {}
                        ov[Message.MSG_ARG_KEY_TRACE_CTX] = ctx
                if policy is None:
                    self._send_framed(frame, dst, ov)
                else:
                    policy.run(partial(self._send_framed, frame, dst, ov),
                               dst=dst, msg_type=msg_type)

        pool = self._send_pool
        if pool is None:
            errors: dict[int, BaseException] = {}
            for dst in receiver_ids:
                try:
                    send_one(dst)
                except Exception as e:
                    if getattr(e, "unretryable", False):
                        raise  # an injected crash is process death, not a leg
                    errors[dst] = e
            if errors:
                from fedml_tpu.comm.send_pool import BroadcastSendError

                raise BroadcastSendError(errors)
        else:
            pool.run_all([(dst, partial(send_one, dst)) for dst in receiver_ids])

    def _send_framed(self, frame: "FramedMessage", dst: int,
                     overrides: dict | None = None) -> None:
        """Backend hook for one leg of a broadcast. The in-repo byte
        transports override this with a ``frame.bytes_for(dst)`` send (no
        payload re-serialization); this default keeps third-party backends
        correct by rebuilding a Message that shares the frame's payload
        buffers (their own ``send_message`` may still re-encode)."""
        self.send_message(frame.to_message(dst, overrides))

    def _close_send_pool(self) -> None:
        """Backends call this from ``stop_receive_message``."""
        if self._send_pool is not None:
            self._send_pool.close()

    @abc.abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching incoming messages to observers, until stopped."""

    @abc.abstractmethod
    def stop_receive_message(self) -> None: ...
