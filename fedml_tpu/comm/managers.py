"""Worker-manager runtime (L1): handler registry + run loop.

Reference: fedml_core/distributed/client/client_manager.py:21-102 and
server/server_manager.py:15-83 — backend mux, ``register_message_receive_
handler`` dict keyed by msg type (:87-88), blocking ``run()``, ``finish()``.
The reference's MPI ``finish`` calls ``MPI.COMM_WORLD.Abort()`` (:93) —
crash-the-world shutdown; here finish is a graceful stop (and backends own
their cleanup).
"""

from __future__ import annotations

import logging
from typing import Callable

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message
from fedml_tpu.obs import trace


def create_backend(backend: str, rank: int, world_size: int, **kw) -> BaseCommunicationManager:
    """Backend mux (client_manager.py:28-50 equivalent):
    loopback | shm | grpc | mqtt, each optionally composed with an object
    store for large payloads (``store_dir=...`` — the MQTT_S3 production
    pattern for any transport)."""
    if backend == "loopback":
        mgr = _loopback(kw, rank)
    elif backend == "shm":
        from fedml_tpu.comm.shm import ShmCommManager

        mgr = ShmCommManager(kw.get("job", "fedml"), rank, world_size)
    elif backend == "grpc":
        from fedml_tpu.comm.grpc_backend import GRPCCommManager, read_ip_config

        ip_config = kw.get("ip_config") or read_ip_config(kw["ip_config_path"])
        mgr = GRPCCommManager(
            rank, ip_config,
            send_timeout=kw.get("grpc_send_timeout", 600.0),
            send_workers=kw.get("grpc_send_workers", 4),
        )
    elif backend == "mqtt":
        from fedml_tpu.comm.mqtt_backend import MqttCommManager

        mgr = MqttCommManager(
            kw.get("mqtt_host", "localhost"), kw.get("mqtt_port", 1883),
            topic=kw.get("job", "fedml"), client_id=rank,
            client_num=world_size - 1,
        )
    else:
        raise ValueError(f"unknown backend {backend!r}")
    if kw.get("store_dir"):
        from fedml_tpu.comm.object_store import FileSystemStore, OffloadCommManager

        mgr = OffloadCommManager(
            mgr, FileSystemStore(kw["store_dir"]),
            threshold_bytes=kw.get("store_threshold", 1 << 16),
        )
    return mgr


def _loopback(kw, rank):
    from fedml_tpu.comm.loopback import LoopbackCommManager

    return LoopbackCommManager(kw["fabric"], rank)


class DistributedManager(Observer):
    """Common base of ClientManager / ServerManager."""

    def __init__(self, comm: BaseCommunicationManager, rank: int, size: int):
        self.comm = comm
        self.rank = rank
        self.size = size
        self._handlers: dict[int, Callable[[Message], None]] = {}
        # this manager's cumulative re-attempt count (comm/retry.py): the
        # per-rank view of the process-wide retry ledger, piggybacked on
        # uploads by the fleet telemetry plane (docs/OBSERVABILITY.md
        # "Fleet telemetry"). Plain int += under the GIL — sends on one
        # manager are serialized anyway.
        self.comm_retries = 0
        comm.add_observer(self)

    # reference API names kept (client_manager.py:55-95)
    def register_message_receive_handler(self, msg_type: int, handler: Callable[[Message], None]) -> None:
        self._handlers[msg_type] = handler

    def receive_message(self, msg_type: int, msg: Message) -> None:
        handler = self._handlers.get(msg_type)
        if handler is None:
            logging.warning("rank %d: no handler for msg type %s", self.rank, msg_type)
            return
        with trace.span("comm/handler", msg_type=msg_type, rank=self.rank):
            handler(msg)

    def send_message(self, msg: Message) -> None:
        # retry/backoff send plane: when the transport carries a policy,
        # unary sends re-attempt on transient failure (comm/retry.py) —
        # each attempt re-runs the full send path (fault wrappers included)
        policy = getattr(self.comm, "retry_policy", None)
        if policy is None:
            send = lambda: self.comm.send_message(msg)  # noqa: E731
        else:
            send = lambda: policy.run(  # noqa: E731
                lambda: self.comm.send_message(msg),
                on_retry=self._note_retry,
                dst=msg.get_receiver_id(), msg_type=msg.get_type(),
            )
        tracer = trace.get()
        if tracer is None:  # disabled path: skip the payload-size walk too
            send()
            return
        with tracer.span("comm/send", msg_type=msg.get_type(),
                         sender=self.rank,
                         receiver=msg.get_receiver_id(),
                         bytes=msg.payload_nbytes()):
            # cross-rank causal tracing: the transport stamps the outgoing
            # header with this send span's context when its trace_wire
            # opt-in is armed (no-op, zero wire bytes otherwise)
            stamp = getattr(self.comm, "stamp_trace_ctx", None)
            if stamp is not None:
                stamp(msg)
            send()

    def broadcast_message(self, msg: Message, receiver_ids: list[int],
                          per_receiver: dict[int, dict] | None = None) -> None:
        """Encode-once downlink fan-out (docs/PERFORMANCE.md "The server
        wire path"): the payload is framed once and every receiver gets a
        header-patched wire copy; ``per_receiver`` carries small header-only
        overrides (e.g. assigned client index). Per-leg ``comm/send`` spans
        are emitted by the backend (on pool worker threads when a send pool
        overlaps the legs); this wrapper adds the enclosing
        ``comm/broadcast`` span on the manager thread."""
        receiver_ids = list(receiver_ids)
        if not receiver_ids:
            return
        tracer = trace.get()
        if tracer is None:
            self.comm.broadcast_message(msg, receiver_ids, per_receiver)
            return
        with tracer.span("comm/broadcast", msg_type=msg.get_type(),
                         sender=self.rank, receivers=len(receiver_ids),
                         bytes=msg.payload_nbytes()):
            self.comm.broadcast_message(msg, receiver_ids, per_receiver)

    def _note_retry(self) -> None:
        self.comm_retries += 1

    def register_message_receive_handlers(self) -> None:
        raise NotImplementedError

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.comm.handle_receive_message()

    def finish(self) -> None:
        self.comm.stop_receive_message()


class ClientManager(DistributedManager):
    pass


class ServerManager(DistributedManager):
    pass
