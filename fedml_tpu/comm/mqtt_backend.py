"""MQTT broker backend for edge-device federation.

Reference: fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:14 —
broker pub/sub with the topic scheme: the server (id 0) publishes
``<topic>0_<clientID>`` and subscribes ``<topic><clientID>``; clients do the
inverse (:47-70, 99-120). The reference ships full JSON payloads inline; here
messages use the typed binary wire format (Message.to_bytes) and large model
payloads ride the object store via OffloadCommManager
(fedml_tpu/comm/object_store.py) — the MQTT_S3 production combination.

Also carried over: the last-will "offline" status message
(mqtt_s3_multi_clients_comm_manager.py:71-72) on the status topic consumed by
comm.status.

paho-mqtt is imported lazily — constructing without it installed raises a
clear error; the rest of the framework never imports this module implicitly.
"""

from __future__ import annotations

import json
import logging
import queue
import threading

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message


class MqttCommManager(BaseCommunicationManager):
    def __init__(self, host: str, port: int, topic: str = "fedml",
                 client_id: int = 0, client_num: int = 0,
                 status_topic: str | None = None, keepalive: int = 180,
                 client_factory=None):
        """``client_factory`` substitutes the broker client construction
        (paho by default) — e.g. ``InProcessBroker().client_factory()`` for
        the offline ``mqtt_s3`` CLI backend. Everything above it (topic
        scheme, wire format, wills, status) is unchanged."""
        super().__init__()
        self.topic = topic
        self.client_id = client_id
        self.client_num = client_num
        self.status_topic = status_topic or f"{topic}/status"
        self._stop = threading.Event()
        self._q: queue.Queue = queue.Queue()

        if client_factory is not None:
            self.client = client_factory(
                client_id=f"{topic}-{client_id}", protocol=None
            )
        else:
            try:
                import paho.mqtt.client as mqtt
            except ImportError as e:
                raise ImportError(
                    "MqttCommManager requires paho-mqtt (not in this image); "
                    "use the loopback/shm/grpc backends, or pass an "
                    "in-process client_factory (comm/inproc_broker.py)"
                ) from e
            if hasattr(mqtt, "CallbackAPIVersion"):  # paho-mqtt >= 2.0
                self.client = mqtt.Client(
                    mqtt.CallbackAPIVersion.VERSION1,
                    client_id=f"{topic}-{client_id}",
                    protocol=mqtt.MQTTv311,
                )
            else:
                self.client = mqtt.Client(
                    client_id=f"{topic}-{client_id}", protocol=mqtt.MQTTv311
                )
        # last-will: broker announces our death on the status topic
        self.client.will_set(
            self.status_topic,
            json.dumps({"id": client_id, "status": "OFFLINE"}),
            qos=1, retain=False,
        )
        self._subscribed = threading.Event()
        self._expected_subacks = self.client_num if client_id == 0 else 1
        self._suback_count = 0
        if self._expected_subacks == 0:
            # a server with no clients yet subscribes to nothing — there is
            # no SUBACK to wait for
            self._subscribed.set()
        self.client.on_connect = self._on_connect
        self.client.on_subscribe = self._on_subscribe
        self.client.on_message = self._on_message
        self.client.connect(host, port, keepalive)
        self.client.loop_start()
        # Block until the broker ACKNOWLEDGES our subscriptions (SUBACK via
        # on_subscribe — subscribe() only queues the packet): a QoS1
        # non-retained publish to a topic whose subscription the broker has
        # not registered yet is silently dropped, so the protocol's init
        # broadcast could vanish and hang the run. Construction-order
        # guarantee: every manager's constructor returns only after its own
        # subscriptions are live, so init messages sent after all managers
        # exist always have their subscribers.
        if not self._subscribed.wait(timeout=30.0):
            raise TimeoutError(
                f"mqtt: no SUBACK within 30 s (broker {host}:{port})"
            )

    # topic scheme (mqtt_comm_manager.py:47-70)
    def _send_topic(self, receiver_id: int) -> str:
        if self.client_id == 0:
            return f"{self.topic}0_{receiver_id}"
        return f"{self.topic}{self.client_id}"

    def _recv_topic(self) -> str:
        if self.client_id == 0:
            # server subscribes to every client's topic via wildcard-free loop
            return None  # handled in _on_connect
        return f"{self.topic}0_{self.client_id}"

    def _on_connect(self, client, userdata, flags, rc):
        if self.client_id == 0:
            for cid in range(1, self.client_num + 1):
                client.subscribe(f"{self.topic}{cid}", qos=1)
        else:
            client.subscribe(self._recv_topic(), qos=1)
        client.publish(
            self.status_topic,
            json.dumps({"id": self.client_id, "status": "ONLINE"}),
            qos=1,
        )

    def _on_subscribe(self, client, userdata, mid, granted_qos, properties=None):
        self._suback_count += 1
        if self._suback_count >= self._expected_subacks:
            self._subscribed.set()

    def _on_message(self, client, userdata, mqtt_msg):
        try:
            self._q.put(Message.from_bytes(mqtt_msg.payload))
        except Exception:
            logging.exception("mqtt: undecodable message on %s", mqtt_msg.topic)

    def send_message(self, msg: Message) -> None:
        topic = self._send_topic(msg.get_receiver_id())
        info = self.client.publish(topic, msg.to_bytes(), qos=1)
        info.wait_for_publish()

    def _send_framed(self, frame, dst: int, overrides: dict | None = None) -> None:
        # encode-once broadcast: per-receiver topics, shared payload bytes
        info = self.client.publish(
            self._send_topic(dst), frame.bytes_for(dst, overrides), qos=1
        )
        info.wait_for_publish()

    def handle_receive_message(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self._q.get(timeout=0.5)
            except queue.Empty:
                continue
            self.notify(msg)

    def stop_receive_message(self) -> None:
        self._stop.set()
        self.client.publish(
            self.status_topic,
            json.dumps({"id": self.client_id, "status": "FINISHED"}),
            qos=1,
        )
        self.client.loop_stop()
        self.client.disconnect()
