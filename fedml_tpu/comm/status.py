"""Client liveness / status protocol for cross-silo deployments.

Reference: the ONLINE/FINISHED client-status handshake in
fedavg_cross_silo/ClientMasterManager.py:65-77 (CONNECTION_IS_READY →
send_client_status ONLINE) and :169-188 (FINISHED on completion), plus
MqttS3StatusManager's JSON status pub/sub (mqtt_s3_status_manager.py:17) and
the MQTT last-will offline signal. The reference only has liveness on the
MQTT path; here the protocol is transport-agnostic: status is an ordinary
typed message on any backend.

The server holds a ClientStatusTracker and starts the round protocol once
every expected client reported ONLINE — replacing the reference's implicit
"MPI processes all exist" assumption with an explicit, failure-aware
handshake.

On top of the handshake the module carries the liveness half of the
fault-tolerant runtime (docs/ROBUSTNESS.md "Failure recovery"):
:class:`HeartbeatSender` re-sends ONLINE status on an interval from a
daemon thread, so the tracker's ``last_seen`` stays fresh while a worker
computes — letting the server distinguish SLOW (alive, missed the round
deadline, heartbeat fresh) from dead (silent on both planes) before the
elastic timeout fires, and letting an OFFLINE-excluded worker announce its
return for readmission.
"""

from __future__ import annotations

import threading
import time

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message


class ClientStatus:
    MSG_TYPE_CLIENT_STATUS = 7001  # reserved type id for status messages

    ONLINE = "ONLINE"
    FINISHED = "FINISHED"
    OFFLINE = "OFFLINE"
    # alive (heartbeat fresh) but missed the round deadline — dropped from
    # the round's aggregate like a dead worker, but diagnosably different
    # in the status table and eligible for contact-driven readmission
    SLOW = "SLOW"

    KEY_STATUS = "client_status"
    KEY_OS = "client_os"  # reference tags client OS in status msgs (message.py:21-24)


def send_client_status(comm: BaseCommunicationManager, client_id: int,
                       status: str, receiver_id: int = 0) -> None:
    """Reference ClientMasterManager.send_client_status(:169)."""
    msg = Message(ClientStatus.MSG_TYPE_CLIENT_STATUS, client_id, receiver_id)
    msg.add_params(ClientStatus.KEY_STATUS, status)
    msg.add_params(ClientStatus.KEY_OS, "linux-tpu")
    comm.send_message(msg)


class ClientStatusTracker:
    """Server-side liveness table; thread-safe (the reference's unsynchronized
    status dicts are a known hazard, SURVEY §5.2)."""

    def __init__(self, expected_clients: int):
        self.expected = expected_clients
        self._status: dict[int, str] = {}  # guarded-by: _lock
        self._last_seen: dict[int, float] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._all_online = threading.Event()
        # fleet telemetry hook (obs/registry.py FleetHealth): called as
        # ``on_transition(client_id, status)`` whenever a client's recorded
        # status CHANGES (heartbeats re-asserting ONLINE refresh last_seen
        # without firing it). Invoked UNDER the tracker lock so concurrent
        # updates (timer marking SLOW vs receive thread marking ONLINE)
        # deliver transitions in the order the table recorded them — the
        # hook must not call back into the tracker.
        self.on_transition = None

    def update(self, client_id: int, status: str, touch: bool = True) -> None:
        """Record ``status`` for the client. ``touch=False`` marks a
        SERVER-side judgement (SLOW/OFFLINE labels) without refreshing
        ``last_seen`` — only actual contact from the client may count as
        liveness evidence."""
        with self._lock:
            prev = self._status.get(client_id)
            self._status[client_id] = status
            if touch:
                self._last_seen[client_id] = time.monotonic()
            online = sum(1 for s in self._status.values() if s == ClientStatus.ONLINE)
            if online >= self.expected:
                self._all_online.set()
            if self.on_transition is not None and status != prev:
                self.on_transition(client_id, status)

    def stale(self, timeout: float) -> list[int]:
        """Clients silent for longer than ``timeout`` seconds (and not
        FINISHED) — candidates for OFFLINE marking / round dropping."""
        now = time.monotonic()
        with self._lock:
            return sorted(
                cid for cid, seen in self._last_seen.items()
                if now - seen > timeout
                and self._status.get(cid) not in (ClientStatus.FINISHED,
                                                  ClientStatus.OFFLINE)
            )


    def last_seen(self, client_id: int) -> float | None:
        """``time.monotonic`` of the client's last status contact (None if
        it never reported)."""
        with self._lock:
            return self._last_seen.get(client_id)

    def seen_within(self, client_id: int, window: float) -> bool:
        """True when the client reported status within the last ``window``
        seconds — the slow-vs-dead discriminator: a worker that missed the
        round deadline but heartbeats is SLOW, not dead."""
        seen = self.last_seen(client_id)
        return seen is not None and time.monotonic() - seen <= window

    def handle_message(self, msg: Message) -> None:
        self.update(msg.get_sender_id(), msg.get(ClientStatus.KEY_STATUS))

    def wait_all_online(self, timeout: float | None = None) -> bool:
        return self._all_online.wait(timeout)

    def snapshot(self) -> dict[int, str]:
        with self._lock:
            return dict(self._status)

    def finished_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._status.values() if s == ClientStatus.FINISHED)


class HeartbeatSender:
    """Periodic ONLINE status from a daemon thread (docs/ROBUSTNESS.md
    "Failure recovery").

    Heartbeats are ordinary :func:`send_client_status` messages, so they
    ride any backend (and any fault wrapper) unchanged; the server's
    status handler feeds them into its :class:`ClientStatusTracker`. Send
    errors are swallowed — a heartbeat is best-effort by definition, and a
    sender must survive its transport flapping (or the server restarting
    mid-run). Heartbeats never touch aggregation state, so a heartbeating
    run is bit-identical to a silent one (tools/ft_smoke.py guards this).
    """

    def __init__(self, comm: BaseCommunicationManager, client_id: int,
                 interval: float, receiver_id: int = 0):
        if interval <= 0:
            raise ValueError(f"heartbeat interval must be > 0, got {interval}")
        self.comm = comm
        self.client_id = client_id
        self.interval = float(interval)
        self.receiver_id = receiver_id
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                send_client_status(self.comm, self.client_id,
                                   ClientStatus.ONLINE, self.receiver_id)
            except Exception:  # noqa: BLE001 — best-effort by contract
                pass
            self._stop.wait(self.interval)

    def start(self) -> "HeartbeatSender":
        if self._thread is None:
            from fedml_tpu.obs import jobscope

            self._thread = threading.Thread(
                # inherit the starter's job binding (obs/jobscope.py): a
                # multi-tenant job's heartbeats trace/count into ITS scope
                target=jobscope.wrap_target(self._loop),
                name=f"heartbeat-c{self.client_id}",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
