"""Client liveness / status protocol for cross-silo deployments.

Reference: the ONLINE/FINISHED client-status handshake in
fedavg_cross_silo/ClientMasterManager.py:65-77 (CONNECTION_IS_READY →
send_client_status ONLINE) and :169-188 (FINISHED on completion), plus
MqttS3StatusManager's JSON status pub/sub (mqtt_s3_status_manager.py:17) and
the MQTT last-will offline signal. The reference only has liveness on the
MQTT path; here the protocol is transport-agnostic: status is an ordinary
typed message on any backend.

The server holds a ClientStatusTracker and starts the round protocol once
every expected client reported ONLINE — replacing the reference's implicit
"MPI processes all exist" assumption with an explicit, failure-aware
handshake.
"""

from __future__ import annotations

import threading
import time

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message


class ClientStatus:
    MSG_TYPE_CLIENT_STATUS = 7001  # reserved type id for status messages

    ONLINE = "ONLINE"
    FINISHED = "FINISHED"
    OFFLINE = "OFFLINE"

    KEY_STATUS = "client_status"
    KEY_OS = "client_os"  # reference tags client OS in status msgs (message.py:21-24)


def send_client_status(comm: BaseCommunicationManager, client_id: int,
                       status: str, receiver_id: int = 0) -> None:
    """Reference ClientMasterManager.send_client_status(:169)."""
    msg = Message(ClientStatus.MSG_TYPE_CLIENT_STATUS, client_id, receiver_id)
    msg.add_params(ClientStatus.KEY_STATUS, status)
    msg.add_params(ClientStatus.KEY_OS, "linux-tpu")
    comm.send_message(msg)


class ClientStatusTracker:
    """Server-side liveness table; thread-safe (the reference's unsynchronized
    status dicts are a known hazard, SURVEY §5.2)."""

    def __init__(self, expected_clients: int):
        self.expected = expected_clients
        self._status: dict[int, str] = {}
        self._last_seen: dict[int, float] = {}
        self._lock = threading.Lock()
        self._all_online = threading.Event()

    def update(self, client_id: int, status: str) -> None:
        with self._lock:
            self._status[client_id] = status
            self._last_seen[client_id] = time.monotonic()
            online = sum(1 for s in self._status.values() if s == ClientStatus.ONLINE)
            if online >= self.expected:
                self._all_online.set()

    def stale(self, timeout: float) -> list[int]:
        """Clients silent for longer than ``timeout`` seconds (and not
        FINISHED) — candidates for OFFLINE marking / round dropping."""
        now = time.monotonic()
        with self._lock:
            return sorted(
                cid for cid, seen in self._last_seen.items()
                if now - seen > timeout
                and self._status.get(cid) not in (ClientStatus.FINISHED,
                                                  ClientStatus.OFFLINE)
            )


    def handle_message(self, msg: Message) -> None:
        self.update(msg.get_sender_id(), msg.get(ClientStatus.KEY_STATUS))

    def wait_all_online(self, timeout: float | None = None) -> bool:
        return self._all_online.wait(timeout)

    def snapshot(self) -> dict[int, str]:
        with self._lock:
            return dict(self._status)

    def finished_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._status.values() if s == ClientStatus.FINISHED)
