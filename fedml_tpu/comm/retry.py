"""Retry/backoff send plane (docs/ROBUSTNESS.md "Failure recovery").

Until this module, ONE failed send anywhere in the runtime was fatal: a
transient gRPC unavailability, an object-store hiccup, or a faulted
loopback leg killed the whole broadcast (and with it the server's round
protocol). At the north-star scale transient failure is the steady state,
so the send plane gets the standard production treatment: bounded retries
with exponential backoff + jitter, applied OUTSIDE whatever transport (or
fault injector) actually performs the send, so each attempt re-runs the
full send path.

A :class:`RetryPolicy` is attached to a communication manager
(``mgr.retry_policy = policy``); :meth:`BaseCommunicationManager.
broadcast_message` wraps each per-destination leg and
``DistributedManager.send_message`` wraps unary sends. Fault-free runs
with a policy installed are BIT-IDENTICAL to runs without one (the policy
only adds a closure call — tools/ft_smoke.py guards this).

Every retry lands in three places: a ``comm/retry`` span on the tracer
(covering the backoff wait, with the attempt index and error), a
``comm/retry_count`` trace counter, and the process-wide
:func:`retry_stats` ledger (the ``Comm/RetryCount`` metric's source —
mirrors ``comm.message.wire_stats``).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable

from fedml_tpu.obs import trace

__all__ = [
    "RetryPolicy", "SendAttemptTimeout", "retry_stats", "reset_retry_stats",
]


class SendAttemptTimeout(TimeoutError):
    """One send attempt exceeded ``RetryPolicy.attempt_timeout``. The
    attempt's thread is abandoned (daemon — a hung transport call cannot be
    cancelled from Python), and the policy moves on to the next attempt."""


_stats_lock = threading.Lock()
_stats = {"retries": 0, "gave_up": 0}
# jitter only perturbs SLEEP durations, never results; module-level rng is
# deliberately unseeded (determinism of outputs does not depend on it)
_jitter_rng = random.Random()


def retry_stats() -> dict:
    """Process-wide retry ledger: ``retries`` = individual re-attempts after
    a failed send, ``gave_up`` = sends that exhausted every attempt."""
    with _stats_lock:
        return dict(_stats)


def reset_retry_stats() -> None:
    with _stats_lock:
        _stats["retries"] = 0
        _stats["gave_up"] = 0


def _count(key: str) -> int:
    with _stats_lock:
        _stats[key] += 1
        return _stats[key]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff for one send leg.

    ``max_attempts`` total tries (1 = no retries); the wait before attempt
    k+1 is ``min(base_delay * backoff**(k-1), max_delay)`` perturbed by
    ``±jitter`` (fractional, decorrelates a thundering herd of failed
    broadcast legs). ``attempt_timeout`` (seconds, optional) bounds each
    attempt by running it on a watchdog thread — a transport call that
    never returns is abandoned (the daemon thread leaks until the call
    dies; Python cannot cancel it) and counted as a failed attempt."""

    max_attempts: int = 3
    base_delay: float = 0.05
    backoff: float = 2.0
    max_delay: float = 2.0
    jitter: float = 0.1
    attempt_timeout: float | None = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        for name in ("base_delay", "max_delay"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        if self.backoff < 1.0:
            raise ValueError(f"backoff must be >= 1.0, got {self.backoff}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")

    def delay_for(self, attempt: int) -> float:
        """Backoff before re-attempt number ``attempt`` (1-based)."""
        d = min(self.base_delay * self.backoff ** (attempt - 1), self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * _jitter_rng.random() - 1.0)
        return max(d, 0.0)

    def _attempt(self, fn: Callable[[], None]):
        if self.attempt_timeout is None:
            return fn()
        result: list = []
        failure: list[BaseException] = []

        def run():
            try:
                result.append(fn())
            except BaseException as e:  # noqa: BLE001 — re-raised below
                failure.append(e)

        t = threading.Thread(target=run, name="comm-retry-attempt", daemon=True)
        t.start()
        t.join(self.attempt_timeout)
        if t.is_alive():
            raise SendAttemptTimeout(
                f"send attempt still running after {self.attempt_timeout}s"
            )
        if failure:
            raise failure[0]
        return result[0] if result else None

    def run(self, fn: Callable[[], None], on_retry: Callable[[], None] | None = None,
            **attrs):
        """Run ``fn`` with retries. ``attrs`` (e.g. dst/msg_type) annotate
        the ``comm/retry`` telemetry; ``on_retry`` (optional) fires once per
        re-attempt — the per-MANAGER attribution hook the fleet telemetry
        plane uses (the module ledger is process-wide, which cannot tell one
        in-process rank's retries from another's). Raises the LAST error
        once ``max_attempts`` is exhausted."""
        for attempt in range(1, self.max_attempts + 1):
            try:
                return self._attempt(fn)
            except Exception as e:
                if getattr(e, "unretryable", False):
                    # e.g. faults.InjectedCrash: re-sending cannot bring a
                    # dead process back — propagate immediately
                    raise
                if attempt >= self.max_attempts:
                    _count("gave_up")
                    trace.event("comm/retry_gave_up", attempts=attempt,
                                error=type(e).__name__, **attrs)
                    raise
                total = _count("retries")
                if on_retry is not None:
                    on_retry()
                trace.counter("comm/retry_count", total)
                with trace.span("comm/retry", attempt=attempt,
                                error=type(e).__name__, **attrs):
                    time.sleep(self.delay_for(attempt))
