"""In-process MQTT broker with the paho client surface.

The reference's MQTT backends are verified against a live broker
(mqtt_comm_manager.py:129-144 self-test); this environment has no network
egress, so the CLI's offline ``--backend mqtt_s3`` drives the REAL
``MqttCommManager`` topic/last-will/status logic through this hub instead of
a socket. It implements exactly the client surface MqttCommManager uses
(``will_set``/``connect``/``loop_start``/``subscribe``/``publish``/
``loop_stop``/``disconnect``) with paho semantics: synchronous delivery to
subscribers, wills fired on unclean drop, cleared by clean disconnect.

This is a transport, not a mock of the manager: everything above the socket —
envelope bytes, topic scheme, status messages — is the production code path.
The real-paho constructor branch remains covered only structurally (see
COVERAGE.md caveats).
"""

from __future__ import annotations

import threading
import types


class _PublishInfo:
    def wait_for_publish(self, timeout=None):
        return None


class InProcessBroker:
    """Topic hub shared by all ranks of one job."""

    def __init__(self):
        self._subs: dict[str, list] = {}  # guarded-by: _lock
        self._wills: dict[object, tuple] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def subscribe(self, topic: str, client) -> None:
        with self._lock:
            subs = self._subs.setdefault(topic, [])
            if client not in subs:
                subs.append(client)

    def unsubscribe_all(self, client) -> None:
        with self._lock:
            for subs in self._subs.values():
                if client in subs:
                    subs.remove(client)

    def publish(self, topic: str, payload) -> None:
        if isinstance(payload, str):
            payload = payload.encode()
        with self._lock:
            clients = list(self._subs.get(topic, []))
        for c in clients:
            cb = c.on_message
            if cb is not None:
                cb(c, None, types.SimpleNamespace(topic=topic, payload=payload))

    def set_will(self, client, topic: str, payload) -> None:
        with self._lock:
            self._wills[client] = (topic, payload)

    def clear_will(self, client) -> None:
        with self._lock:
            self._wills.pop(client, None)

    def drop(self, client) -> None:
        """Unclean disconnect: deliver the client's last will."""
        with self._lock:
            will = self._wills.pop(client, None)
        self.unsubscribe_all(client)
        if will is not None:
            self.publish(*will)

    def client_factory(self):
        """A ``client_factory`` for :class:`MqttCommManager`: called with the
        paho ``Client`` kwargs, returns a connected-on-demand client."""
        broker = self

        class _Client:
            def __init__(self, client_id: str = "", protocol=None):
                self.client_id = client_id
                self.on_connect = None
                self.on_subscribe = None
                self.on_message = None
                self._connected = False
                self._mid = 0

            def will_set(self, topic, payload, qos=0, retain=False):
                broker.set_will(self, topic, payload)

            def connect(self, host, port, keepalive=60):
                self._connected = True

            def loop_start(self):
                # paho fires on_connect from its network loop; sync here
                if self.on_connect is not None:
                    self.on_connect(self, None, {}, 0)

            def subscribe(self, topic, qos=0):
                broker.subscribe(topic, self)
                # registration is synchronous here; ack it like a SUBACK
                self._mid += 1
                if self.on_subscribe is not None:
                    self.on_subscribe(self, None, self._mid, (qos,))

            def publish(self, topic, payload, qos=0, retain=False):
                broker.publish(topic, payload)
                return _PublishInfo()

            def loop_stop(self):
                pass

            def disconnect(self):
                # clean disconnect: will is discarded, not delivered
                broker.clear_will(self)
                broker.unsubscribe_all(self)
                self._connected = False

        def factory(client_id: str = "", protocol=None):
            return _Client(client_id=client_id, protocol=protocol)

        return factory
