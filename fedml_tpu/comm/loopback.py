"""In-process loopback backend.

The reference has no fake/in-process backend — its framework tests run real
MPI on localhost (SURVEY §4: "a gap the TPU build should fix with an
in-process loopback comm backend"). This backend gives every rank a queue in
one process; ranks run in threads. It is the unit-test transport for the
manager/algorithm protocol layers and the semantic model for the shm/grpc
backends.
"""

from __future__ import annotations

import queue
import threading
from typing import Any

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message


class LoopbackFabric:
    """Shared post office: rank -> queue. One instance per simulated cluster."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.queues: dict[int, queue.Queue] = {r: queue.Queue() for r in range(world_size)}

    def post(self, msg: Message) -> None:
        # serialize/deserialize through the real wire format so tests cover it
        self.queues[msg.get_receiver_id()].put(msg.to_bytes())


class LoopbackCommManager(BaseCommunicationManager):
    _STOP = object()

    def __init__(self, fabric: LoopbackFabric, rank: int):
        super().__init__()
        self.fabric = fabric
        self.rank = rank
        self._running = False

    def send_message(self, msg: Message) -> None:
        self.fabric.post(msg)

    def handle_receive_message(self) -> None:
        self._running = True
        q = self.fabric.queues[self.rank]
        while self._running:
            item = q.get()
            if item is self._STOP:
                break
            self.notify(Message.from_bytes(item))

    def stop_receive_message(self) -> None:
        self._running = False
        self.fabric.queues[self.rank].put(self._STOP)
