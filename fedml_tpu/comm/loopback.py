"""In-process loopback backend.

The reference has no fake/in-process backend — its framework tests run real
MPI on localhost (SURVEY §4: "a gap the TPU build should fix with an
in-process loopback comm backend"). This backend gives every rank a queue in
one process; ranks run in threads. It is the unit-test transport for the
manager/algorithm protocol layers and the semantic model for the shm/grpc
backends.

Broadcast fan-outs post two-part ``(head, shared_tail)`` frames: every
receiver of one broadcast decodes zero-copy views into ONE shared payload
buffer (read-only — Message.from_buffers enforces it), so an N-worker model
broadcast materializes the payload bytes once, not N times.
"""

from __future__ import annotations

import queue
import threading

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import FramedMessage, Message
from fedml_tpu.comm.send_pool import SendWorkerPool


class LoopbackFabric:
    """Shared post office: rank -> queue. One instance per simulated cluster."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.queues: dict[int, queue.Queue] = {r: queue.Queue() for r in range(world_size)}

    def post(self, msg: Message) -> None:
        # serialize/deserialize through the real wire format so tests cover it
        self.post_raw(msg.get_receiver_id(), msg.to_bytes())

    def post_raw(self, receiver: int, data) -> None:
        """Queue already-framed wire data: ``bytes`` or a broadcast's
        ``(head, shared_tail)`` pair."""
        self.queues[receiver].put(data)


class OrderedUplinkFabric(LoopbackFabric):
    """Loopback fabric that holds one message type bound for ``receiver``
    until ``expected`` distinct senders posted it, then delivers the batch
    in sender order — pins the server's streaming fold order so bit-identity
    assertions (streaming vs buffered f64 accumulation) are deterministic
    even though client threads race. Used by tools/wire_smoke.py,
    tools/robust_smoke.py, and the wire-path tests."""

    def __init__(self, world_size: int, expected: int, msg_type: int,
                 receiver: int = 0):
        super().__init__(world_size)
        self._expected = expected
        self._type = msg_type
        self._receiver = receiver
        self._held: dict[int, bytes] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def post(self, msg: Message) -> None:
        if (msg.get_receiver_id() == self._receiver
                and msg.get_type() == self._type):
            with self._lock:
                self._held[msg.get_sender_id()] = msg.to_bytes()
                if len(self._held) < self._expected:
                    return
                batch, self._held = sorted(self._held.items()), {}
            for _, data in batch:
                self.post_raw(self._receiver, data)
            return
        super().post(msg)


class LoopbackCommManager(BaseCommunicationManager):
    _STOP = object()

    def __init__(self, fabric: LoopbackFabric, rank: int, send_workers: int = 0):
        super().__init__(send_pool=(
            SendWorkerPool(send_workers, name=f"loopback-send-r{rank}")
            if send_workers else None
        ))
        self.fabric = fabric
        self.rank = rank
        self._running = False

    def send_message(self, msg: Message) -> None:
        self.fabric.post(msg)

    def _send_framed(self, frame: FramedMessage, dst: int,
                     overrides: dict | None = None) -> None:
        # two-part post: per-receiver head, ONE shared payload buffer
        self.fabric.post_raw(dst, (frame.head_for(dst, overrides),
                                   frame.tail_bytes()))

    def handle_receive_message(self) -> None:
        self._running = True
        q = self.fabric.queues[self.rank]
        while self._running:
            item = q.get()
            if item is self._STOP:
                break
            if isinstance(item, tuple):
                self.notify(Message.from_buffers(*item))
            else:
                self.notify(Message.from_bytes(item))

    def stop_receive_message(self) -> None:
        self._running = False
        self._close_send_pool()
        self.fabric.queues[self.rank].put(self._STOP)
