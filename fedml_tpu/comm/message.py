"""Message envelope for the real-distributed path.

Reference: fedml_core/distributed/communication/message.py:5-86 — a dict with
type/sender/receiver plus arbitrary params, pickled whole (tensors included)
over MPI (mpi_send_thread.py:27) or JSON'd over MQTT/gRPC. Here the envelope
keeps the same key names (``msg_type``/``sender``/``receiver`` and the
MSG_ARG_* constants) but the wire format is explicitly typed: a JSON header +
a raw little-endian array segment per tensor — never pickled objects. Model
payloads are (flat byte vector, leaf-descriptor) pairs produced by
``pack_pytree`` — leaves keep their native dtypes bit-exactly; the descriptor
records path/shape/dtype per leaf.

Framing is zero-copy on both sides (docs/PERFORMANCE.md "The server wire
path"): packing an already-contiguous array contributes a ``memoryview`` of
its buffer (no model bytes copied until a byte-oriented transport joins the
frame), and unpacking produces alignment-safe ``np.frombuffer`` views into
the received buffer, marked read-only so two receivers of one shared
broadcast buffer can never alias-write each other's model. The encode-once
broadcast primitive is :class:`FramedMessage`: one payload serialization per
fan-out, with the per-receiver header patched in place.
"""

from __future__ import annotations

import json
import struct
import threading
from typing import Any

import numpy as np

import jax


# --- wire-level stats --------------------------------------------------------
# Counts payload serializations (frames built with at least one array
# segment) so the encode-once contract is testable: a broadcast to N workers
# increments this ONCE; the legacy per-rank loop increments it N times.
# bench.py's broadcast A/B probe and tools/wire_smoke.py read these.

_WIRE_LOCK = threading.Lock()
_WIRE_STATS = {"payload_serializations": 0, "frames": 0}


def wire_stats() -> dict[str, int]:
    """Snapshot of the process-wide wire counters."""
    with _WIRE_LOCK:
        return dict(_WIRE_STATS)


def reset_wire_stats() -> None:
    with _WIRE_LOCK:
        for k in _WIRE_STATS:
            _WIRE_STATS[k] = 0


def _byte_view(a: np.ndarray) -> np.ndarray:
    """Flat uint8 view of a contiguous array — zero-copy reinterpretation
    (``ascontiguousarray`` is a no-op on already-contiguous input)."""
    return np.ascontiguousarray(a).reshape(-1).view(np.uint8)


class Message:
    # key names kept for reference parity (message.py:9-24)
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    # protocol-shared header fields every manager family uses: the model
    # structure descriptor (pack_pytree), the authoritative round index a
    # sync/upload belongs to (PR 6: clients train AS this round, so a
    # replayed downlink leg cannot desynchronize a round counter), and the
    # graceful-stop flag on the final fan-out. Defined at the comm layer so
    # protocol modules (fedavg, fedgkt, splitnn, turbo, vertical, tree) and
    # the fault injector share one spelling without importing each other.
    MSG_ARG_KEY_MODEL_DESC = "model_desc"
    MSG_ARG_KEY_ROUND_IDX = "round_idx"
    MSG_ARG_KEY_FINISHED = "finished"
    # compressed-update payload (compress/codec.py EncodedUpdate): the flat
    # byte vector of all encoded planes + the recursive structure descriptor
    MSG_ARG_KEY_ENCODED_UPDATE = "encoded_update"
    MSG_ARG_KEY_ENCODED_DESC = "encoded_desc"
    # barrier-free server plane (fedml_tpu/async_agg): every async downlink
    # stamps the global-model version it carries, clients echo it on their
    # uploads, and the server staleness-weights the fold by the echoed
    # version; tree partials carry the tier's weight sum (what the parent
    # folds by) and fold count (observability: how many client updates the
    # super-update represents)
    MSG_ARG_KEY_MODEL_VERSION = "model_version"
    MSG_ARG_KEY_WEIGHT_SUM = "weight_sum"
    MSG_ARG_KEY_FOLD_COUNT = "fold_count"
    # async edge tiers (fedml_tpu/async_agg/tree.py): a barrier-free tier
    # emits SEVERAL partials per round — the emission sequence number makes
    # replayed legs idempotent at the parent ((round, seq) must advance
    # lexicographically per sender), and the window-complete flag marks the
    # emission that closes this tier's round contribution (the parent's
    # round barrier counts only complete emissions; a missing flag means a
    # legacy single-partial tier and is read as complete)
    MSG_ARG_KEY_PARTIAL_SEQ = "partial_seq"
    MSG_ARG_KEY_WINDOW_COMPLETE = "window_complete"
    # downlink delta coding (compress/downlink.py, docs/COMPRESSION.md
    # "Downlink delta coding"): a delta-coded sync's payload reconstructs
    # the stamped MODEL_VERSION from this base version — a header-only
    # per-receiver scalar riding FramedMessage overrides, so one shared
    # delta blob serves a whole fan-out group without re-serialization
    MSG_ARG_KEY_BASE_VERSION = "base_version"
    # fleet telemetry plane (fedml_tpu/obs/registry.py, docs/OBSERVABILITY.md
    # "Fleet telemetry"): a compact JSON-safe dict of sender-side health
    # metrics piggybacked on ordinary uploads/partials — header-only scalars
    # (never an array segment), OPTIONAL (absent = zero wire overhead), and
    # never read by the aggregation path, so telemetry-on runs stay
    # bit-identical to telemetry-off runs
    MSG_ARG_KEY_TELEMETRY = "telemetry"
    # multi-tenant job plane (fedml_tpu/tenancy/, docs/MULTITENANCY.md): the
    # federation a message belongs to when several jobs share one wire — a
    # header-only scalar stamped by the job's comm facade and read by the
    # server-side router to demux per-job state. OPTIONAL: a message with no
    # job id routes to the implicit default job, so a single-job run's wire
    # bytes and behavior are unchanged (tools/multijob_smoke.py).
    MSG_ARG_KEY_JOB_ID = "job_id"
    # cross-rank causal tracing (fedml_tpu/obs/trace.py wire_ctx,
    # docs/OBSERVABILITY.md "Cross-rank causal tracing"): the sender's open
    # span id + ancestor chain + lane/rank + send wall time, stamped by the
    # comm send/broadcast paths ONLY behind a manager's explicit
    # ``trace_wire`` opt-in. Header-only JSON scalars (never an array
    # segment), OPTIONAL (absent = zero wire overhead, bytes identical to a
    # pre-tracing run), and never read by the aggregation path — the
    # receive side only attaches it to its comm/recv span so
    # tools/trace_merge.py can link N per-rank traces causally.
    MSG_ARG_KEY_TRACE_CTX = "trace_ctx"

    def __init__(self, msg_type: int = 0, sender_id: int = 0, receiver_id: int = 0):
        self.msg_params: dict[str, Any] = {
            self.MSG_ARG_KEY_TYPE: int(msg_type),
            self.MSG_ARG_KEY_SENDER: int(sender_id),
            self.MSG_ARG_KEY_RECEIVER: int(receiver_id),
        }

    # --- reference API surface (message.py:26-73) ---
    def get_sender_id(self) -> int:
        return self.msg_params[self.MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[self.MSG_ARG_KEY_RECEIVER]

    def get_type(self) -> int:
        return self.msg_params[self.MSG_ARG_KEY_TYPE]

    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    def get_params(self) -> dict[str, Any]:
        return self.msg_params

    def get(self, key: str, default=None) -> Any:
        return self.msg_params.get(key, default)

    def payload_nbytes(self) -> int:
        """Array-payload size in bytes (the dominant wire cost; the JSON
        header adds a few hundred bytes on top). Cheap — sums ``nbytes``
        over array params without serializing — so the tracing layer can
        attach it to send/receive spans without re-packing the message."""
        n = 0
        for v in self.msg_params.values():
            if isinstance(v, (np.ndarray, jax.Array)):
                n += int(v.nbytes)
        return n

    # --- wire format: JSON header + raw array segments ---
    MAGIC = b"FTM1"

    def frame(self) -> "FramedMessage":
        """Encode this message once into a reusable wire frame (the
        broadcast fan-out primitive — see :class:`FramedMessage`)."""
        return FramedMessage(self)

    def to_bytes(self) -> bytes:
        return self.frame().bytes_for(self.get_receiver_id())

    @classmethod
    def from_bytes(cls, data) -> "Message":
        """Decode a wire frame. Array params are zero-copy read-only views
        into ``data`` (bytes, bytearray, or memoryview) — they stay valid as
        long as the message (which keeps ``data`` alive) does."""
        mv = memoryview(data)
        assert bytes(mv[:4]) == cls.MAGIC, "bad message magic"
        (hlen,) = struct.unpack_from("<I", mv, 4)
        header = json.loads(bytes(mv[8 : 8 + hlen]).decode())
        return cls._from_header_and_tail(header, mv[8 + hlen :])

    @classmethod
    def from_buffers(cls, head, tail) -> "Message":
        """Decode a two-part frame: ``head`` (magic + header) and ``tail``
        (the shared payload segments). The loopback backend posts broadcast
        fan-outs this way so every receiver's arrays view ONE shared payload
        buffer — zero per-receiver payload copies."""
        hv = memoryview(head)
        assert bytes(hv[:4]) == cls.MAGIC, "bad message magic"
        (hlen,) = struct.unpack_from("<I", hv, 4)
        header = json.loads(bytes(hv[8 : 8 + hlen]).decode())
        return cls._from_header_and_tail(header, memoryview(tail))

    @classmethod
    def _from_header_and_tail(cls, header: dict, tail: memoryview) -> "Message":
        # collect array descriptors in segment order
        descs = [(k, v) for k, v in header.items() if isinstance(v, dict) and "__arr__" in v]
        descs.sort(key=lambda kv: kv[1]["__arr__"])
        arrays = {}
        offset = 0
        for k, d in descs:
            (alen,) = struct.unpack_from("<Q", tail, offset)
            offset += 8
            arr = np.frombuffer(
                tail, dtype=np.dtype(d["dtype"]),
                count=int(np.prod(d["shape"])) if d["shape"] else 1, offset=offset,
            )
            # wire views are read-only even when the source buffer is
            # mutable: receivers must never alias-write a (possibly shared)
            # transport buffer
            arr.flags.writeable = False
            arrays[k] = arr.reshape(d["shape"])
            offset += alen
        msg = cls()
        for k, v in header.items():
            msg.msg_params[k] = arrays[k] if k in arrays else v
        return msg

    def __repr__(self):
        sizes = {
            k: f"array{tuple(v.shape)}" if isinstance(v, (np.ndarray, jax.Array)) else v
            for k, v in self.msg_params.items()
        }
        return f"Message({sizes})"


# --- encode-once wire frame --------------------------------------------------

# the receiver slot is rendered as an 11-char fixed-width decimal so it can
# be patched in place per receiver; whitespace padding keeps the header
# valid JSON ("receiver":         3)
_RECV_SENTINEL = -1097393539
_RECV_WIDTH = len(str(_RECV_SENTINEL))


class FramedMessage:
    """One message encoded once, emittable to many receivers.

    ``Message.to_bytes`` used to re-pack the full payload per call, so a
    model broadcast to N workers serialized the model N times. A frame holds
    the payload segments as zero-copy memoryviews plus a header template
    with a fixed-width receiver slot; ``bytes_for(dst)`` patches the slot in
    place (an O(header) operation) and joins the shared segments. Small
    per-receiver header params (e.g. the assigned client index) ride
    ``overrides`` — a cheap header re-dump, never a payload re-pack.
    Overriding array params is rejected: it would orphan a payload segment.
    """

    __slots__ = ("_header", "_arrays", "_tail", "_head", "_slot",
                 "_tail_bytes", "payload_nbytes")

    def __init__(self, msg: Message):
        header: dict[str, Any] = {}
        arrays: list[np.ndarray] = []
        for k, v in msg.msg_params.items():
            if isinstance(v, (np.ndarray, jax.Array)):
                a = np.ascontiguousarray(np.asarray(v))
                header[k] = {"__arr__": len(arrays), "dtype": str(a.dtype),
                             "shape": list(a.shape)}
                arrays.append(a)
            else:
                header[k] = v
        self._header = header
        self._arrays = arrays  # keeps the segment buffers alive
        tail: list = []
        nbytes = 0
        for a in arrays:
            seg = memoryview(_byte_view(a))
            tail.append(struct.pack("<Q", seg.nbytes))
            tail.append(seg)
            nbytes += seg.nbytes
        self._tail = tail
        self._tail_bytes: bytes | None = None
        self.payload_nbytes = nbytes
        # header template with the fixed-width receiver slot
        probe = dict(header)
        probe[Message.MSG_ARG_KEY_RECEIVER] = _RECV_SENTINEL
        hb = json.dumps(probe).encode()
        token = b'"%s": %d' % (Message.MSG_ARG_KEY_RECEIVER.encode(),
                               _RECV_SENTINEL)
        self._head = None
        self._slot = None
        if hb.count(token) == 1:
            # JSON string escaping makes a str-param collision impossible;
            # a nested dict param repeating key+sentinel falls back to the
            # re-dump path below
            at = hb.index(token) + len(token) - _RECV_WIDTH
            self._head = Message.MAGIC + struct.pack("<I", len(hb)) + hb
            self._slot = 8 + at
        with _WIRE_LOCK:
            _WIRE_STATS["frames"] += 1
            if arrays:
                _WIRE_STATS["payload_serializations"] += 1

    def head_for(self, receiver: int, overrides: dict | None = None) -> bytes:
        rid = int(receiver)
        if overrides is None and self._slot is not None:
            tok = b"%*d" % (_RECV_WIDTH, rid)
            if len(tok) == _RECV_WIDTH:
                head = bytearray(self._head)
                head[self._slot : self._slot + _RECV_WIDTH] = tok
                return bytes(head)
        h = dict(self._header)
        if overrides:
            for k, v in overrides.items():
                if isinstance(v, (np.ndarray, jax.Array)):
                    raise ValueError(
                        f"broadcast override {k!r} is an array: per-receiver "
                        "overrides are header-only (share the payload, vary "
                        "the scalars)"
                    )
                tmpl = self._header.get(k)
                if isinstance(tmpl, dict) and "__arr__" in tmpl:
                    raise ValueError(
                        f"cannot override array param {k!r}: it is a framed "
                        "payload segment"
                    )
                h[k] = v
        h[Message.MSG_ARG_KEY_RECEIVER] = rid
        hb = json.dumps(h).encode()
        return Message.MAGIC + struct.pack("<I", len(hb)) + hb

    def tail_bytes(self) -> bytes:
        """The payload segments joined once (lazily cached) — shared across
        every receiver of a broadcast."""
        tb = self._tail_bytes
        if tb is None:
            tb = self._tail_bytes = b"".join(self._tail)
        return tb

    def buffers_for(self, receiver: int, overrides: dict | None = None) -> list:
        """Vectored form: ``[head, len0, seg0, len1, seg1, ...]`` — the
        payload entries are zero-copy views of the original arrays."""
        return [self.head_for(receiver, overrides), *self._tail]

    def bytes_for(self, receiver: int, overrides: dict | None = None) -> bytes:
        """Contiguous wire bytes for one receiver (for byte-oriented
        transports: one join, no payload re-serialization)."""
        return self.head_for(receiver, overrides) + self.tail_bytes()

    def to_message(self, receiver: int, overrides: dict | None = None) -> Message:
        """Rebuild a Message addressed to ``receiver`` whose array params
        share this frame's buffers — the fallback for backends without a
        bytes-level framed-send hook."""
        msg = Message()
        msg.msg_params = dict(self._header)
        for k, v in list(msg.msg_params.items()):
            if isinstance(v, dict) and "__arr__" in v:
                msg.msg_params[k] = self._arrays[v["__arr__"]]
        if overrides:
            for k, v in overrides.items():
                if isinstance(v, (np.ndarray, jax.Array)):
                    raise ValueError(
                        f"broadcast override {k!r} is an array: per-receiver "
                        "overrides are header-only"
                    )
                msg.msg_params[k] = v
        msg.msg_params[Message.MSG_ARG_KEY_RECEIVER] = int(receiver)
        return msg


# --- pytree <-> wire payload -------------------------------------------------


def pack_pytree(tree: Any) -> tuple[np.ndarray, str]:
    """Flatten a pytree of arrays to (flat byte vector, json descriptor).
    The descriptor records leaf paths/shapes/dtypes so the receiver rebuilds
    the exact structure — the anti-pickle wire contract (SURVEY §5.8).
    Leaves keep their native dtypes byte-for-byte (int64 counters and f64
    leaves survive the wire unchanged). Each leaf contributes a zero-copy
    byte view; the single concatenation into ``flat`` is the only copy."""
    from fedml_tpu.core.tree import tree_leaves_with_paths

    leaves = tree_leaves_with_paths(tree)
    desc = [
        {"path": k, "shape": list(np.shape(v)), "dtype": str(np.asarray(v).dtype)}
        for k, v in leaves
    ]
    if leaves:
        flat = np.concatenate([_byte_view(np.asarray(v)) for _, v in leaves])
    else:
        flat = np.zeros((0,), np.uint8)
    return flat, json.dumps(desc)


def pack_encoded_update(enc) -> tuple[np.ndarray, str]:
    """Flatten a (possibly chain-nested) ``EncodedUpdate`` to (flat byte
    vector, json descriptor) — the encoded-update payload type. Each plane is
    packed with :func:`pack_pytree` (native dtypes bit-exact: bf16 values,
    int32 indices, packed-nibble uint8 all survive untouched); the descriptor
    records scheme/meta and per-plane pack descriptors recursively, so the
    receiver rebuilds the exact EncodedUpdate without densifying anything."""
    from fedml_tpu.compress.codec import EncodedUpdate

    segs: list[np.ndarray] = []

    def walk(e) -> dict:
        spec: dict[str, Any] = {"scheme": e.scheme, "meta": e.meta, "planes": {}}
        for name in sorted(e.planes):
            v = e.planes[name]
            if isinstance(v, EncodedUpdate):
                spec["planes"][name] = {"__enc__": walk(v)}
            else:
                flat, desc = pack_pytree(jax.tree.map(np.asarray, v))
                segs.append(flat)
                spec["planes"][name] = {"__tree__": json.loads(desc),
                                        "nbytes": int(flat.size)}
        return spec

    spec = walk(enc)
    flat = np.concatenate(segs) if segs else np.zeros((0,), np.uint8)
    return flat, json.dumps(spec)


def unpack_encoded_update(flat: np.ndarray, descriptor: str):
    """Inverse of :func:`pack_encoded_update`."""
    from fedml_tpu.compress.codec import EncodedUpdate

    flat = np.asarray(flat, dtype=np.uint8)
    offset = 0

    def walk(spec: dict):
        nonlocal offset
        planes = {}
        for name in sorted(spec["planes"]):
            p = spec["planes"][name]
            if "__enc__" in p:
                planes[name] = walk(p["__enc__"])
            else:
                n = int(p["nbytes"])
                planes[name] = unpack_pytree(
                    flat[offset : offset + n], json.dumps(p["__tree__"])
                )
                offset += n
        return EncodedUpdate(spec["scheme"], planes, spec["meta"])

    return walk(json.loads(descriptor))


def unpack_pytree(flat: np.ndarray, descriptor: str) -> Any:
    """Rebuild a nested dict from pack_pytree output (paths use '/').

    Leaves are alignment-safe zero-copy views into ``flat``, always marked
    read-only (matching the pre-view wire semantics, where every leaf was a
    frombuffer-of-bytes copy): a writable alias would let a consumer — e.g.
    a round callback handed views of the server's live global model —
    silently corrupt the source buffer. A leaf whose byte offset is
    misaligned for its dtype falls back to a copy."""
    desc = json.loads(descriptor)
    flat = np.asarray(flat, dtype=np.uint8)
    viewable = flat.flags.c_contiguous
    base_addr = flat.ctypes.data if viewable else 0
    out: dict[str, Any] = {}
    i = 0
    for d in desc:
        dt = np.dtype(d["dtype"])
        n = int(np.prod(d["shape"])) if d["shape"] else 1
        nbytes = n * dt.itemsize
        if viewable and (base_addr + i) % dt.itemsize == 0:
            view = flat[i : i + nbytes].view(dt)
            view.flags.writeable = False
            leaf = view.reshape(d["shape"])
        else:
            leaf = np.frombuffer(flat[i : i + nbytes].tobytes(), dtype=dt).reshape(d["shape"])
        i += nbytes
        node = out
        parts = d["path"].split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return out
