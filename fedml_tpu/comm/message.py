"""Message envelope for the real-distributed path.

Reference: fedml_core/distributed/communication/message.py:5-86 — a dict with
type/sender/receiver plus arbitrary params, pickled whole (tensors included)
over MPI (mpi_send_thread.py:27) or JSON'd over MQTT/gRPC. Here the envelope
keeps the same key names (``msg_type``/``sender``/``receiver`` and the
MSG_ARG_* constants) but the wire format is explicitly typed: a JSON header +
a raw little-endian array segment per tensor — never pickled objects. Model
payloads are (flat byte vector, leaf-descriptor) pairs produced by
``pack_pytree`` — leaves keep their native dtypes bit-exactly; the descriptor
records path/shape/dtype per leaf.
"""

from __future__ import annotations

import io
import json
import struct
from typing import Any

import numpy as np

import jax


class Message:
    # key names kept for reference parity (message.py:9-24)
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_MODEL_PARAMS_URL = "model_params_url"
    # compressed-update payload (compress/codec.py EncodedUpdate): the flat
    # byte vector of all encoded planes + the recursive structure descriptor
    MSG_ARG_KEY_ENCODED_UPDATE = "encoded_update"
    MSG_ARG_KEY_ENCODED_DESC = "encoded_desc"

    def __init__(self, msg_type: int = 0, sender_id: int = 0, receiver_id: int = 0):
        self.msg_params: dict[str, Any] = {
            self.MSG_ARG_KEY_TYPE: int(msg_type),
            self.MSG_ARG_KEY_SENDER: int(sender_id),
            self.MSG_ARG_KEY_RECEIVER: int(receiver_id),
        }

    # --- reference API surface (message.py:26-73) ---
    def get_sender_id(self) -> int:
        return self.msg_params[self.MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[self.MSG_ARG_KEY_RECEIVER]

    def get_type(self) -> int:
        return self.msg_params[self.MSG_ARG_KEY_TYPE]

    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    def get_params(self) -> dict[str, Any]:
        return self.msg_params

    def get(self, key: str, default=None) -> Any:
        return self.msg_params.get(key, default)

    def payload_nbytes(self) -> int:
        """Array-payload size in bytes (the dominant wire cost; the JSON
        header adds a few hundred bytes on top). Cheap — sums ``nbytes``
        over array params without serializing — so the tracing layer can
        attach it to send/receive spans without re-packing the message."""
        n = 0
        for v in self.msg_params.values():
            if isinstance(v, (np.ndarray, jax.Array)):
                n += int(v.nbytes)
        return n

    # --- wire format: JSON header + raw array segments ---
    MAGIC = b"FTM1"

    def to_bytes(self) -> bytes:
        header: dict[str, Any] = {}
        arrays: list[np.ndarray] = []
        for k, v in self.msg_params.items():
            if isinstance(v, (np.ndarray, jax.Array)):
                a = np.ascontiguousarray(np.asarray(v))
                header[k] = {"__arr__": len(arrays), "dtype": str(a.dtype), "shape": list(a.shape)}
                arrays.append(a)
            else:
                header[k] = v
        hbytes = json.dumps(header).encode()
        buf = io.BytesIO()
        buf.write(self.MAGIC)
        buf.write(struct.pack("<I", len(hbytes)))
        buf.write(hbytes)
        for a in arrays:
            raw = a.tobytes()
            buf.write(struct.pack("<Q", len(raw)))
            buf.write(raw)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Message":
        assert data[:4] == cls.MAGIC, "bad message magic"
        (hlen,) = struct.unpack_from("<I", data, 4)
        header = json.loads(data[8 : 8 + hlen].decode())
        offset = 8 + hlen
        # collect array descriptors in insertion order
        descs = [(k, v) for k, v in header.items() if isinstance(v, dict) and "__arr__" in v]
        descs.sort(key=lambda kv: kv[1]["__arr__"])
        arrays = {}
        for k, d in descs:
            (alen,) = struct.unpack_from("<Q", data, offset)
            offset += 8
            arr = np.frombuffer(data, dtype=np.dtype(d["dtype"]), count=int(np.prod(d["shape"])) if d["shape"] else 1, offset=offset)
            arrays[k] = arr.reshape(d["shape"])
            offset += alen
        msg = cls()
        for k, v in header.items():
            msg.msg_params[k] = arrays[k] if k in arrays else v
        return msg

    def __repr__(self):
        sizes = {
            k: f"array{tuple(v.shape)}" if isinstance(v, (np.ndarray, jax.Array)) else v
            for k, v in self.msg_params.items()
        }
        return f"Message({sizes})"


# --- pytree <-> wire payload -------------------------------------------------


def pack_pytree(tree: Any) -> tuple[np.ndarray, str]:
    """Flatten a pytree of arrays to (flat byte vector, json descriptor).
    The descriptor records leaf paths/shapes/dtypes so the receiver rebuilds
    the exact structure — the anti-pickle wire contract (SURVEY §5.8).
    Leaves keep their native dtypes byte-for-byte (int64 counters and f64
    leaves survive the wire unchanged)."""
    from fedml_tpu.core.tree import tree_leaves_with_paths

    leaves = tree_leaves_with_paths(tree)
    desc = [
        {"path": k, "shape": list(np.shape(v)), "dtype": str(np.asarray(v).dtype)}
        for k, v in leaves
    ]
    if leaves:
        flat = np.concatenate(
            [np.frombuffer(np.ascontiguousarray(np.asarray(v)).tobytes(), np.uint8)
             for _, v in leaves]
        )
    else:
        flat = np.zeros((0,), np.uint8)
    return flat, json.dumps(desc)


def pack_encoded_update(enc) -> tuple[np.ndarray, str]:
    """Flatten a (possibly chain-nested) ``EncodedUpdate`` to (flat byte
    vector, json descriptor) — the encoded-update payload type. Each plane is
    packed with :func:`pack_pytree` (native dtypes bit-exact: bf16 values,
    int32 indices, packed-nibble uint8 all survive untouched); the descriptor
    records scheme/meta and per-plane pack descriptors recursively, so the
    receiver rebuilds the exact EncodedUpdate without densifying anything."""
    from fedml_tpu.compress.codec import EncodedUpdate

    segs: list[np.ndarray] = []

    def walk(e) -> dict:
        spec: dict[str, Any] = {"scheme": e.scheme, "meta": e.meta, "planes": {}}
        for name in sorted(e.planes):
            v = e.planes[name]
            if isinstance(v, EncodedUpdate):
                spec["planes"][name] = {"__enc__": walk(v)}
            else:
                flat, desc = pack_pytree(jax.tree.map(np.asarray, v))
                segs.append(flat)
                spec["planes"][name] = {"__tree__": json.loads(desc),
                                        "nbytes": int(flat.size)}
        return spec

    spec = walk(enc)
    flat = np.concatenate(segs) if segs else np.zeros((0,), np.uint8)
    return flat, json.dumps(spec)


def unpack_encoded_update(flat: np.ndarray, descriptor: str):
    """Inverse of :func:`pack_encoded_update`."""
    from fedml_tpu.compress.codec import EncodedUpdate

    flat = np.asarray(flat, dtype=np.uint8)
    offset = 0

    def walk(spec: dict):
        nonlocal offset
        planes = {}
        for name in sorted(spec["planes"]):
            p = spec["planes"][name]
            if "__enc__" in p:
                planes[name] = walk(p["__enc__"])
            else:
                n = int(p["nbytes"])
                planes[name] = unpack_pytree(
                    flat[offset : offset + n], json.dumps(p["__tree__"])
                )
                offset += n
        return EncodedUpdate(spec["scheme"], planes, spec["meta"])

    return walk(json.loads(descriptor))


def unpack_pytree(flat: np.ndarray, descriptor: str) -> Any:
    """Rebuild a nested dict from pack_pytree output (paths use '/')."""
    desc = json.loads(descriptor)
    flat = np.asarray(flat, dtype=np.uint8)
    out: dict[str, Any] = {}
    i = 0
    for d in desc:
        dt = np.dtype(d["dtype"])
        n = int(np.prod(d["shape"])) if d["shape"] else 1
        nbytes = n * dt.itemsize
        leaf = np.frombuffer(flat[i : i + nbytes].tobytes(), dtype=dt).reshape(d["shape"])
        i += nbytes
        node = out
        parts = d["path"].split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return out
