"""Seeded fault injection over any communication backend
(docs/ROBUSTNESS.md "Fault injection").

PR 5's wire path grew real failure handling — elastic round timeout with
renormalized weights, ``EmptyRoundError`` on an all-dropped round, duplicate
uploads resolved first-wins, OFFLINE exclusion after consecutive misses —
but until now those paths were only driven by hand-built unit tests.
:class:`FaultyCommManager` wraps one rank's transport and injects faults on
its SEND side (client wrappers fault the uplink, the server wrapper faults
broadcast legs), so the whole failure surface runs end-to-end under the
real protocol on any backend (loopback, shm, grpc, mqtt_s3).

Faults (all seeded — a given (seed, rank, message order) replays exactly):

- ``drop=p``      lose the message with probability p
- ``delay=s[@p]`` deliver s seconds late (prob p, default 1.0) on a timer
                  thread — the sender never blocks, and delayed uploads can
                  arrive after the round timeout (the stale-upload path)
- ``dup=p``       send the message twice (duplicate first-wins path)
- ``corrupt=p``   flip bytes in the model payload (clip/reject defense path)
- ``fail=p``      the send RAISES :class:`TransientSendError` instead of
                  delivering — the retry/backoff plane's test surface
                  (comm/retry.py); each retry attempt re-rolls the draw
- ``recv_drop=p``     lose an ARRIVING message with probability p (downlink
                      loss as seen by the wrapped rank — uplink injection
                      alone cannot exercise receive-side recovery)
- ``recv_delay=s[@p]`` deliver an arriving message s seconds late on a
                      timer thread (receive-side reordering)
- ``crash=r``     raise :class:`InjectedCrash` on the first send carrying a
                  round index >= r — simulates the process dying mid-run;
                  never retried, never isolated to one broadcast leg
                  (tools/ft_smoke.py kills the server with it and restarts
                  from the round checkpoint)

Spec string (the ``--fault_spec`` CLI syntax): ``;``-separated per-rank
entries, ``<rank|*>:<fault>=<val>[,<fault>=<val>...]`` — e.g.
``"2:drop=1.0;3:delay=0.2@0.5,dup=0.3;*:corrupt=0.05"``. ``*`` applies to
every rank without an explicit entry (rank 0 is the server).

Protocol stop messages (``finished``) are never faulted: losing one leaks a
blocked client thread, which tests liveness of the harness rather than the
protocol's failure handling.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import FramedMessage, Message
from fedml_tpu.obs import trace

# payload params eligible for corruption (header scalars stay intact: the
# fault models a corrupted model payload, not an unparseable frame)
_CORRUPTIBLE = (Message.MSG_ARG_KEY_MODEL_PARAMS,
                Message.MSG_ARG_KEY_ENCODED_UPDATE)

# the authoritative round index every sync/upload carries since PR 6 —
# now defined at the comm layer (Message), so no algorithm-layer import
# and no second spelling of the wire field
_ROUND_IDX_KEY = Message.MSG_ARG_KEY_ROUND_IDX


class TransientSendError(ConnectionError):
    """Injected send failure (``fail=p``): the transport 'lost the
    connection' for this attempt. The retry plane (comm/retry.py) is
    expected to recover it; without retries it fails the leg."""


class InjectedCrash(RuntimeError):
    """Injected process death (``crash=r``): the wrapped rank 'dies' when
    it first touches round ``r``. Marked unretryable so the retry plane
    propagates it immediately, and re-raised out of per-leg broadcast
    isolation — a crash must kill the protocol loop, that is the point."""

    unretryable = True


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One rank's fault profile. Probabilities in [0, 1]; ``delay``/
    ``recv_delay`` in seconds; ``corrupt_frac`` is the fraction of payload
    bytes flipped per corrupted message; ``crash_round`` < 0 disables the
    crash."""

    drop: float = 0.0
    delay: float = 0.0
    delay_prob: float = 1.0
    dup: float = 0.0
    corrupt: float = 0.0
    corrupt_frac: float = 0.01
    fail: float = 0.0
    recv_drop: float = 0.0
    recv_delay: float = 0.0
    recv_delay_prob: float = 1.0
    crash_round: int = -1

    def __post_init__(self):
        for name in ("drop", "delay_prob", "dup", "corrupt", "fail",
                     "recv_drop", "recv_delay_prob"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"FaultSpec.{name}={v} must be in [0, 1]")
        for name in ("delay", "recv_delay"):
            if getattr(self, name) < 0:
                raise ValueError(f"FaultSpec.{name} must be >= 0")

    @property
    def active(self) -> bool:
        return (self.drop > 0 or self.dup > 0 or self.corrupt > 0
                or self.fail > 0 or self.crash_round >= 0
                or (self.delay > 0 and self.delay_prob > 0)
                or self.recv_active)

    @property
    def recv_active(self) -> bool:
        return (self.recv_drop > 0
                or (self.recv_delay > 0 and self.recv_delay_prob > 0))


def parse_fault_spec(spec: str) -> dict:
    """Parse the ``--fault_spec`` syntax into ``{rank_or_'*': FaultSpec}``.
    Unknown fault names and malformed entries fail loudly — a typo'd fault
    silently running a clean experiment would be worse than a crash."""
    out: dict = {}
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        target, sep, faults = entry.partition(":")
        if not sep or not faults:
            raise ValueError(
                f"fault spec entry {entry!r}: expected "
                "'<rank|*>:<fault>=<val>[,...]'"
            )
        target = target.strip()
        key: int | str = "*" if target == "*" else int(target)
        if key in out:
            raise ValueError(f"fault spec: duplicate target {target!r}")
        kw: dict = {}
        for f in faults.split(","):
            name, sep, val = f.strip().partition("=")
            if not sep:
                raise ValueError(f"fault {f!r}: expected '<name>=<value>'")
            name = name.strip()
            if name in ("delay", "recv_delay"):
                secs, at, prob = val.partition("@")
                kw[name] = float(secs)
                if at:
                    kw[f"{name}_prob"] = float(prob)
            elif name == "crash":
                kw["crash_round"] = int(val)
            elif name in ("drop", "dup", "corrupt", "corrupt_frac", "fail",
                          "recv_drop"):
                kw[name] = float(val)
            else:
                raise ValueError(
                    f"unknown fault {name!r} (expected drop | delay | dup | "
                    "corrupt | corrupt_frac | fail | recv_drop | recv_delay "
                    "| crash)"
                )
        out[key] = FaultSpec(**kw)
    if not out:
        raise ValueError(f"empty fault spec {spec!r}")
    return out


class FaultyCommManager(BaseCommunicationManager):
    """Wrap ``inner`` and apply ``spec``'s faults to outgoing messages.

    The receive side delegates untouched (observers land on ``inner``), so
    the wrapper composes with any backend and with OffloadCommManager.
    Applied faults are recorded in ``self.applied`` as
    ``(kind, msg_type, receiver)`` tuples and as ``comm/fault`` instant
    events on the process tracer."""

    def __init__(self, inner: BaseCommunicationManager, spec: FaultSpec,
                 rank: int = 0, seed: int = 0):
        super().__init__()
        self.inner = inner
        self.spec = spec
        self.rank = rank
        self._rng = np.random.RandomState((seed * 9176 + rank * 131) % (2**31))  # guarded-by: _rng_lock
        # independent stream for the receive side so adding downlink faults
        # never shifts an existing seeded send-side schedule
        self._recv_rng = np.random.RandomState(  # guarded-by: _rng_lock
            (seed * 9176 + rank * 131 + 0x5EC5) % (2**31)
        )
        self._rng_lock = threading.Lock()
        self.applied: list[tuple[str, int, int]] = []  # guarded-by: _rng_lock
        # per-kind totals maintained at append time so applied_counts()
        # never rescans the ledger (telemetry reads it every round)
        self._applied_counts: dict[str, int] = {}  # guarded-by: _rng_lock
        self._shims: dict[object, "_RecvFaultShim"] = {}
        self._crashed = False  # guarded-by: _rng_lock

    # -- receive side: delegation, optionally through the fault shim ---------

    def add_observer(self, observer) -> None:
        if not self.spec.recv_active:
            self.inner.add_observer(observer)
            return
        shim = _RecvFaultShim(self, observer)
        self._shims[observer] = shim
        self.inner.add_observer(shim)

    def remove_observer(self, observer) -> None:
        self.inner.remove_observer(self._shims.pop(observer, observer))

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self.inner.stop_receive_message()

    # -- send side: seeded faults --------------------------------------------

    def _decide(self, msg_type: int, receiver: int) -> dict:
        """One seeded draw per enabled fault kind (fixed draw pattern per
        message — outcomes never shift the sequence, so a run replays).
        The ``fail`` draw comes LAST so enabling it never shifts the draws
        of a pre-existing seeded schedule."""
        s = self.spec
        with self._rng_lock:
            r = self._rng
            plan = {
                "drop": s.drop > 0 and r.random_sample() < s.drop,
                "corrupt": s.corrupt > 0 and r.random_sample() < s.corrupt,
                "dup": s.dup > 0 and r.random_sample() < s.dup,
                "delay": (s.delay > 0 and s.delay_prob > 0
                          and r.random_sample() < s.delay_prob),
                "fail": s.fail > 0 and r.random_sample() < s.fail,
            }
            # recorded under the same lock (fedlint guarded-by): send
            # threads and the receive shim both append to ``applied``
            for kind, hit in plan.items():
                if hit:
                    self.applied.append((kind, msg_type, receiver))
                    self._applied_counts[kind] = (
                        self._applied_counts.get(kind, 0) + 1
                    )
        for kind, hit in plan.items():
            if hit:
                trace.event("comm/fault", kind=kind, msg_type=msg_type,
                            sender=self.rank, receiver=receiver)
        return plan

    def applied_counts(self) -> dict:
        """Per-kind totals of the faults applied so far (a consistent
        snapshot taken under the ledger's lock; maintained incrementally
        at append time, O(kinds) per call) — the population adapter's
        clients report their own dropped-upload count from this."""
        with self._rng_lock:
            return dict(self._applied_counts)

    def _maybe_crash(self, round_idx) -> None:
        """``crash=r``: die on the first send touching round >= r, and stay
        dead — once crashed, EVERY later send from this rank raises too
        (heartbeat threads and other round-index-free senders included: a
        dead process sends nothing). Checked before anything else on the
        send path (a dead process does not get to pick which messages
        still leave)."""
        with self._rng_lock:
            if self._crashed:
                raise InjectedCrash(f"rank {self.rank} is crashed (injected)")
            cr = self.spec.crash_round
            crash_now = (cr >= 0 and round_idx is not None
                         and int(round_idx) >= cr)
            if crash_now:
                self._crashed = True
                self.applied.append(("crash", -1, -1))
                self._applied_counts["crash"] = (
                    self._applied_counts.get("crash", 0) + 1
                )
        if crash_now:
            trace.event("comm/fault", kind="crash", sender=self.rank,
                        round=int(round_idx))
            raise InjectedCrash(
                f"rank {self.rank} crashed at round {int(round_idx)} "
                f"(injected crash={cr})"
            )

    def _corrupt_message(self, msg: Message) -> Message:
        """Copy ``msg`` with seeded byte flips in its model payload(s)."""
        out = Message()
        out.msg_params = dict(msg.msg_params)
        with self._rng_lock:
            for key in _CORRUPTIBLE:
                v = out.msg_params.get(key)
                if not isinstance(v, np.ndarray):
                    continue
                buf = np.array(v)  # owned contiguous copy
                raw = buf.reshape(-1).view(np.uint8)
                n_flip = max(1, int(self.spec.corrupt_frac * raw.size))
                pos = self._rng.randint(0, raw.size, size=n_flip)
                raw[pos] ^= 0xFF
                out.msg_params[key] = buf
        return out

    def _deliver(self, thunks, delay: float) -> None:
        if delay > 0:
            t = threading.Timer(delay, lambda: [fn() for fn in thunks])
            t.daemon = True
            t.start()
        else:
            for fn in thunks:
                fn()

    @staticmethod
    def _protected(msg: Message) -> bool:
        return bool(msg.get(Message.MSG_ARG_KEY_FINISHED))

    def send_message(self, msg: Message) -> None:
        self._maybe_crash(msg.get(_ROUND_IDX_KEY))
        if not self.spec.active or self._protected(msg):
            self.inner.send_message(msg)
            return
        plan = self._decide(msg.get_type(), msg.get_receiver_id())
        if plan["fail"]:
            raise TransientSendError(
                f"injected send failure rank {self.rank} -> "
                f"{msg.get_receiver_id()}"
            )
        if plan["drop"]:
            return
        if plan["corrupt"]:
            msg = self._corrupt_message(msg)
        sends = 2 if plan["dup"] else 1
        self._deliver([lambda m=msg: self.inner.send_message(m)] * sends,
                      self.spec.delay if plan["delay"] else 0.0)

    def broadcast_message(self, msg: Message, receiver_ids: list,
                          per_receiver: dict | None = None) -> None:
        # crash is checked at fan-out entry, NOT per leg: process death
        # must escape the broadcast's per-destination fault isolation
        self._maybe_crash(msg.get(_ROUND_IDX_KEY))
        if not self.spec.active or self._protected(msg):
            self.inner.broadcast_message(msg, receiver_ids, per_receiver)
            return
        # base implementation frames once and routes each leg through our
        # _send_framed, where the per-leg faults land
        super().broadcast_message(msg, receiver_ids, per_receiver)

    def _send_framed(self, frame: FramedMessage, dst: int,
                     overrides: dict | None = None) -> None:
        plan = self._decide(frame._header.get(Message.MSG_ARG_KEY_TYPE, 0), dst)
        if plan["fail"]:
            raise TransientSendError(
                f"injected send failure rank {self.rank} -> {dst}"
            )
        if plan["drop"]:
            return
        if plan["corrupt"]:
            # corruption needs a mutable payload copy: rebuild the leg as a
            # Message (faulted legs give up the zero-copy fast path)
            m = self._corrupt_message(frame.to_message(dst, overrides))
            thunk = [lambda: self.inner.send_message(m)]
        else:
            thunk = [lambda: self.inner._send_framed(frame, dst, overrides)]
        self._deliver(thunk * (2 if plan["dup"] else 1),
                      self.spec.delay if plan["delay"] else 0.0)


class _RecvFaultShim:
    """Observer wrapper applying receive-side faults before delivery.

    Wraps each observer registered through a :class:`FaultyCommManager`
    whose spec has receive faults: arriving messages are dropped or
    delivered late on a timer thread (seeded, independent rng stream from
    the send side). ``finished`` stop messages pass through untouched —
    same liveness rationale as the send side."""

    def __init__(self, mgr: "FaultyCommManager", observer):
        self._mgr = mgr
        self._observer = observer

    def receive_message(self, msg_type: int, msg: Message) -> None:
        mgr, s = self._mgr, self._mgr.spec
        if FaultyCommManager._protected(msg):
            self._observer.receive_message(msg_type, msg)
            return
        with mgr._rng_lock:
            r = mgr._recv_rng
            drop = s.recv_drop > 0 and r.random_sample() < s.recv_drop
            delay = (s.recv_delay > 0 and s.recv_delay_prob > 0
                     and r.random_sample() < s.recv_delay_prob)
            # same critical section as the draws: ``applied`` is
            # guarded-by _rng_lock and the send side appends under it too
            for kind, hit in (("recv_drop", drop), ("recv_delay", delay)):
                if hit:
                    mgr.applied.append((kind, msg_type, mgr.rank))
                    mgr._applied_counts[kind] = (
                        mgr._applied_counts.get(kind, 0) + 1
                    )
        for kind, hit in (("recv_drop", drop), ("recv_delay", delay)):
            if hit:
                trace.event("comm/fault", kind=kind, msg_type=msg_type,
                            sender=msg.get_sender_id(), receiver=mgr.rank)
        if drop:
            return
        mgr._deliver(
            [lambda: self._observer.receive_message(msg_type, msg)],
            s.recv_delay if delay else 0.0,
        )


def wrap_make_comm(make_comm, specs, seed: int = 0, registry: list | None = None):
    """Wrap a ``make_comm(rank)`` factory so ranks with a fault spec get a
    :class:`FaultyCommManager`. ``specs`` is a ``{rank|'*': FaultSpec}`` map
    or a :func:`parse_fault_spec` string; ``registry`` (optional list)
    collects the created wrappers so harnesses can assert on
    ``wrapper.applied``."""
    if isinstance(specs, str):
        specs = parse_fault_spec(specs)

    def wrapped(rank: int):
        inner = make_comm(rank)
        spec = specs.get(rank, specs.get("*"))
        if spec is None or not spec.active:
            return inner
        mgr = FaultyCommManager(inner, spec, rank=rank, seed=seed)
        if registry is not None:
            registry.append(mgr)
        return mgr

    return wrapped
