"""gRPC backend for cross-host / cross-silo federation.

Reference: fedml_core/distributed/communication/gRPC/ — per-rank gRPC server,
ip table from CSV (grpc_comm_manager.py:109-119), 1 GB max message (:37-38).
Reference defects NOT ported (SURVEY §7): the 50000-vs-8888 port-base
mismatch, and the fresh channel per message (:63-75) — channels here are
persistent per destination. Proto-less generic RPC (bytes in/bytes out)
carries the typed Message wire format; no pickles.
"""

from __future__ import annotations

import csv
import logging
import threading
from collections import deque
from concurrent import futures
from pathlib import Path

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.send_pool import SendWorkerPool

try:
    import grpc

    HAS_GRPC = True
except Exception:  # pragma: no cover
    HAS_GRPC = False

_METHOD = "/fedml_tpu.Comm/Send"
_MAX_LEN = 1024 * 1024 * 1024  # 1 GB, reference parity (grpc_comm_manager.py:37)
_IDENT = lambda b: b  # noqa: E731


def read_ip_config(path: str | Path) -> dict[int, tuple[str, int]]:
    """CSV: receiver_id,ip[,port] (reference grpc_ipconfig.csv; port defaults
    to base 50000 + rank on BOTH sides — the mismatch bug is not ported)."""
    out: dict[int, tuple[str, int]] = {}
    with open(path) as fh:
        for row in csv.reader(fh):
            # fedlint: disable=wire-contract -- CSV header sniff ("receiver_id,ip,port"), not the wire field
            if not row or row[0].strip().startswith("receiver"):
                continue
            rank = int(row[0])
            host = row[1].strip()
            port = int(row[2]) if len(row) > 2 else 50000 + rank
            out[rank] = (host, port)
    return out


class GRPCCommManager(BaseCommunicationManager):
    def __init__(self, rank: int, ip_config: dict[int, tuple[str, int]],
                 send_timeout: float = 600.0, send_workers: int = 4):
        """``send_timeout`` (seconds, per unary send) and ``send_workers``
        (broadcast send-pool width; 0 = serial fan-out on the caller thread)
        are plumbed from the run config (``--grpc_send_timeout`` /
        ``--grpc_send_workers`` on main_fedavg, or ``create_backend`` kw)."""
        if not HAS_GRPC:
            raise RuntimeError("grpcio not available")
        super().__init__(send_pool=(
            SendWorkerPool(send_workers, name=f"grpc-send-r{rank}")
            if send_workers else None
        ))
        self.rank = rank
        self.ip_config = ip_config
        self.send_timeout = float(send_timeout)
        self._queue: deque[bytes] = deque()
        self._cv = threading.Condition()
        self._channels: dict[int, grpc.Channel] = {}  # guarded-by: _stub_lock
        self._stubs: dict[int, object] = {}  # guarded-by: _stub_lock
        self._stub_lock = threading.Lock()
        self._running = False

        host, port = ip_config[rank]
        opts = [
            ("grpc.max_send_message_length", _MAX_LEN),
            ("grpc.max_receive_message_length", _MAX_LEN),
        ]
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8), options=opts)

        mgr = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method != _METHOD:
                    return None

                def _recv(request: bytes, context) -> bytes:
                    with mgr._cv:
                        mgr._queue.append(request)
                        mgr._cv.notify()
                    return b"ok"

                return grpc.unary_unary_rpc_method_handler(
                    _recv, request_deserializer=_IDENT, response_serializer=_IDENT
                )

        self._server.add_generic_rpc_handlers((_Handler(),))
        bound = self._server.add_insecure_port(f"[::]:{port}")
        if bound == 0:
            raise OSError(f"grpc bind failed on port {port}")
        self._server.start()
        logging.info("grpc server rank %d listening on %d", rank, port)

    def _stub(self, dst: int):
        # pooled broadcast legs may create stubs concurrently
        with self._stub_lock:
            if dst not in self._stubs:
                host, port = self.ip_config[dst]
                opts = [
                    ("grpc.max_send_message_length", _MAX_LEN),
                    ("grpc.max_receive_message_length", _MAX_LEN),
                ]
                ch = grpc.insecure_channel(f"{host}:{port}", options=opts)
                self._channels[dst] = ch
                self._stubs[dst] = ch.unary_unary(
                    _METHOD, request_serializer=_IDENT, response_deserializer=_IDENT
                )
            return self._stubs[dst]

    def send_message(self, msg: Message) -> None:
        self._stub(msg.get_receiver_id())(msg.to_bytes(), timeout=self.send_timeout)

    def _send_framed(self, frame, dst: int, overrides: dict | None = None) -> None:
        self._stub(dst)(frame.bytes_for(dst, overrides), timeout=self.send_timeout)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            with self._cv:
                while not self._queue and self._running:
                    self._cv.wait(timeout=0.2)
                if not self._running:
                    break
                data = self._queue.popleft()
            self.notify(Message.from_bytes(data))

    def stop_receive_message(self) -> None:
        self._running = False
        with self._cv:
            self._cv.notify_all()
        self._close_send_pool()
        # snapshot under the stub lock (fedlint guarded-by): a pooled
        # broadcast leg may still be creating stubs while we stop
        with self._stub_lock:
            channels = list(self._channels.values())
        for ch in channels:
            ch.close()
        self._server.stop(grace=0.5)
