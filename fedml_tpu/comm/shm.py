"""Shared-memory transport backend (native C++ ring via ctypes).

Single-host multi-process federation: the role the reference fills with MPI
on localhost (run_fedavg_distributed_pytorch.sh:19 writes `hostname >
mpi_host_file`). Each rank owns one MPSC ring in POSIX shm; send writes into
the receiver's ring; receive blocks on a process-shared condvar (no polling —
contrast the reference's 0.3 s queue poll, mpi/com_manager.py:71-78).

The C++ source lives in fedml_tpu/ops/native/shm_ring.cpp and is compiled on
first use with g++ (cached next to the source).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path

from fedml_tpu.comm.base import BaseCommunicationManager
from fedml_tpu.comm.message import Message

_NATIVE_DIR = Path(__file__).parent.parent / "ops" / "native"
_SRC = _NATIVE_DIR / "shm_ring.cpp"
_SO = _NATIVE_DIR / "libshmring.so"

_lib = None
_lib_lock = threading.Lock()


def _load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", str(_SO), str(_SRC), "-lpthread", "-lrt"]
            logging.info("building native shm ring: %s", " ".join(cmd))
            subprocess.run(cmd, check=True, capture_output=True)
        lib = ctypes.CDLL(str(_SO))
        lib.shmring_create.restype = ctypes.c_void_p
        lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shmring_open.restype = ctypes.c_void_p
        lib.shmring_open.argtypes = [ctypes.c_char_p]
        lib.shmring_send.restype = ctypes.c_int
        lib.shmring_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        lib.shmring_recv.restype = ctypes.c_longlong
        lib.shmring_recv.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        lib.shmring_close.restype = ctypes.c_int
        lib.shmring_close.argtypes = [ctypes.c_void_p]
        lib.shmring_unlink.restype = ctypes.c_int
        lib.shmring_unlink.argtypes = [ctypes.c_char_p]
        _lib = lib
        return lib


class ShmRing:
    """One named MPSC ring."""

    def __init__(self, name: str, capacity: int = 64 << 20, create: bool = False):
        self.lib = _load_lib()
        self.name = name.encode()
        self.handle = (
            self.lib.shmring_create(self.name, capacity)
            if create
            else self.lib.shmring_open(self.name)
        )
        if not self.handle:
            raise OSError(f"shmring {'create' if create else 'open'} failed: {name}")
        self._recv_buf = ctypes.create_string_buffer(capacity if create else 64 << 20)

    def send(self, data: bytes, timeout_ms: int = 60_000) -> None:
        rc = self.lib.shmring_send(self.handle, data, len(data), timeout_ms)
        if rc == -1:
            raise TimeoutError(f"shmring send timeout on {self.name!r}")
        if rc != 0:
            raise OSError(f"shmring send failed rc={rc}")

    def recv(self, timeout_ms: int = 1000) -> bytes | None:
        n = self.lib.shmring_recv(self.handle, self._recv_buf, len(self._recv_buf), timeout_ms)
        if n == -1:
            return None
        if n < 0:
            raise OSError(f"shmring recv failed rc={n}")
        return self._recv_buf.raw[:n]

    def close(self) -> None:
        if self.handle:
            self.lib.shmring_close(self.handle)
            self.handle = None

    def unlink(self) -> None:
        self.lib.shmring_unlink(self.name)


class ShmCommManager(BaseCommunicationManager):
    """Backend over the native rings: rank r receives on ring
    ``/<job>_r<r>``; send opens the receiver's ring lazily."""

    def __init__(self, job: str, rank: int, world_size: int, capacity: int = 64 << 20):
        super().__init__()
        self.job = job
        self.rank = rank
        self.world_size = world_size
        self.capacity = capacity
        self.my_ring = ShmRing(self._ring_name(rank), capacity, create=True)
        self._out: dict[int, ShmRing] = {}
        self._running = False

    def _ring_name(self, rank: int) -> str:
        return f"/{self.job}_r{rank}"

    def _ring(self, dst: int) -> ShmRing:
        if dst not in self._out:
            # receiver creates its ring at startup; create= True is idempotent
            self._out[dst] = ShmRing(self._ring_name(dst), self.capacity, create=True)
        return self._out[dst]

    def send_message(self, msg: Message) -> None:
        self._ring(msg.get_receiver_id()).send(msg.to_bytes())

    def _send_framed(self, frame, dst: int, overrides: dict | None = None) -> None:
        # encode-once: the shared frame tail is joined once per fan-out; each
        # receiver's ring write reuses it behind a patched header
        self._ring(dst).send(frame.bytes_for(dst, overrides))

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            data = self.my_ring.recv(timeout_ms=200)
            if data is None:
                continue
            msg = Message.from_bytes(data)
            if msg.get_type() == -999:  # internal stop sentinel
                break
            self.notify(msg)

    def stop_receive_message(self) -> None:
        self._running = False
        stop = Message(msg_type=-999, sender_id=self.rank, receiver_id=self.rank)
        try:
            self.my_ring.send(stop.to_bytes(), timeout_ms=1000)
        except Exception:
            pass

    def cleanup(self) -> None:
        self.my_ring.close()
        self.my_ring.unlink()
        for ring in self._out.values():
            ring.close()
