"""Population trace save/replay (docs/PERFORMANCE.md "Heterogeneous
populations").

A trace is the REALIZED population schedule of a run — per round: the
sampled cohort (with its empty-slot padding), each member's speed
multiplier, the mid-round dropout schedule, and the upload jitter — written
as JSONL so it is diffable and append-streamable. Replaying a trace through
:class:`TracePopulation` reproduces cohorts, step budgets, and dropouts
**bit-exactly**: floats ride JSON's shortest-round-trip repr (exact for
float64), ints are ints, and the loader refuses silently-wrong replays
(schema/shape/round mismatches all fail loudly).

    pop = Population("speed=lognormal:0,0.5;avail=0.8;dropout=0.05", N, seed)
    save_trace("run.jsonl", pop, rounds=50, cohort_size=64)
    replay = load_trace("run.jsonl")   # .round_view() == the original's
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from fedml_tpu.population.model import Population, RoundView

TRACE_SCHEMA = 1
_KIND = "fedml_tpu_population_trace"


def _view_record(view: RoundView) -> dict:
    return {
        "round": view.round_idx,
        "cohort": [int(c) for c in view.cohort],
        "speed": [float(s) for s in view.speed],
        "dropped": [int(d) for d in view.dropped],
        "drop_frac": [float(f) for f in view.drop_frac],
        "jitter_s": [float(j) for j in view.jitter_s],
        "eligible_count": view.eligible_count,
    }


def _record_view(rec: dict, cohort_size: int) -> RoundView:
    fields = ("cohort", "speed", "dropped", "drop_frac", "jitter_s")
    for f in fields:
        if f not in rec:
            raise ValueError(
                f"population trace round record missing {f!r} "
                f"(round={rec.get('round')})"
            )
        if len(rec[f]) != cohort_size:
            raise ValueError(
                f"population trace round {rec.get('round')}: {f!r} has "
                f"{len(rec[f])} entries, header says cohort_size="
                f"{cohort_size}"
            )
    return RoundView(
        round_idx=int(rec["round"]),
        cohort=np.asarray(rec["cohort"], np.int32),
        speed=np.asarray(rec["speed"], np.float64),
        dropped=np.asarray(rec["dropped"], bool),
        drop_frac=np.asarray(rec["drop_frac"], np.float64),
        jitter_s=np.asarray(rec["jitter_s"], np.float64),
        eligible_count=int(rec["eligible_count"]),
    )


class TracePopulation:
    """Replay of a saved trace: the same ``round_view`` interface as
    :class:`fedml_tpu.population.model.Population`, serving the recorded
    views verbatim. Requests outside the recorded rounds (or with a
    different cohort size) fail loudly — a trace cannot be extrapolated."""

    def __init__(self, num_clients: int, cohort_size: int,
                 views: dict[int, RoundView], source: str = "<memory>",
                 spec: str | None = None, seed: int | None = None):
        self.num_clients = int(num_clients)
        self.cohort_size = int(cohort_size)
        self._views = dict(views)
        self.source = source
        self.spec_string = spec
        self.seed = seed

    @property
    def rounds(self) -> list[int]:
        return sorted(self._views)

    @property
    def jitter_active(self) -> bool:
        """True when any recorded round carries a nonzero upload jitter —
        the wire-only knob the sim engine rejects on the generative spec
        path, held to the same contract on replay."""
        return any(
            (view.jitter_s > 0.0).any() for view in self._views.values()
        )

    def round_view(self, round_idx: int, cohort_size: int) -> RoundView:
        if int(cohort_size) != self.cohort_size:
            raise ValueError(
                f"population trace {self.source} was captured with "
                f"cohort_size={self.cohort_size}; this run asks for "
                f"{cohort_size} — a trace replays one cohort geometry only"
            )
        view = self._views.get(int(round_idx))
        if view is None:
            raise ValueError(
                f"population trace {self.source} records rounds "
                f"[{self.rounds[0]}..{self.rounds[-1]}] but round "
                f"{round_idx} was requested — a trace cannot be "
                "extrapolated; capture more rounds or use the generative "
                "spec"
            )
        return view

    def describe(self) -> dict:
        return {
            "kind": "trace",
            "source": self.source,
            "num_clients": self.num_clients,
            "cohort_size": self.cohort_size,
            "rounds": len(self._views),
            "spec": self.spec_string,
        }


def capture_trace(population: Population, rounds: int,
                  cohort_size: int) -> TracePopulation:
    """Materialize ``rounds`` round views from a generative population into
    an in-memory replayable trace (what ``save_trace`` writes)."""
    views = {
        r: population.round_view(r, cohort_size) for r in range(int(rounds))
    }
    return TracePopulation(
        population.num_clients, cohort_size, views,
        spec=population.spec.to_string(), seed=population.seed,
    )


def save_trace(path: str | Path, population: Population, rounds: int,
               cohort_size: int) -> Path:
    """Capture and write a JSONL trace: one header line, one line per
    round. Returns the path written."""
    trace = capture_trace(population, rounds, cohort_size)
    path = Path(path)
    header = {
        "kind": _KIND,
        "schema": TRACE_SCHEMA,
        "num_clients": trace.num_clients,
        "cohort_size": trace.cohort_size,
        "rounds": len(trace.rounds),
        "spec": trace.spec_string,
        "seed": trace.seed,
    }
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for r in trace.rounds:
            f.write(json.dumps(_view_record(trace.round_view(
                r, trace.cohort_size))) + "\n")
    return path


def load_trace(path: str | Path) -> TracePopulation:
    """Load a JSONL trace written by :func:`save_trace`."""
    path = Path(path)
    lines = [ln for ln in path.read_text().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"population trace {path}: empty file")
    header = json.loads(lines[0])
    if header.get("kind") != _KIND:
        raise ValueError(
            f"population trace {path}: not a population trace (header kind "
            f"{header.get('kind')!r})"
        )
    if header.get("schema") != TRACE_SCHEMA:
        raise ValueError(
            f"population trace {path}: schema {header.get('schema')!r} "
            f"(this build reads schema {TRACE_SCHEMA})"
        )
    cohort_size = int(header["cohort_size"])
    views: dict[int, RoundView] = {}
    for ln in lines[1:]:
        rec = json.loads(ln)
        view = _record_view(rec, cohort_size)
        if view.round_idx in views:
            raise ValueError(
                f"population trace {path}: duplicate round "
                f"{view.round_idx}"
            )
        views[view.round_idx] = view
    if len(views) != int(header.get("rounds", len(views))):
        raise ValueError(
            f"population trace {path}: header promises "
            f"{header.get('rounds')} rounds, file carries {len(views)} "
            "(truncated write?)"
        )
    return TracePopulation(
        int(header["num_clients"]), cohort_size, views, source=str(path),
        spec=header.get("spec"), seed=header.get("seed"),
    )
