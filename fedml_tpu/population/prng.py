"""The population subsystem's ONE seeded-generator constructor.

Every draw the population model makes — static per-client attributes,
per-round availability, dropout, jitter, the wire adapter's per-rank
profiles — flows through :func:`spawn`, keyed by ``(seed, stream, index)``.
That single funnel is what makes a saved trace replay bit-exactly: there is
no global-rng state anywhere in ``fedml_tpu/population/``, and the fedlint
``traced-purity`` gate bans ``np.random.*`` module-wide here
(``banned-module-calls`` in pyproject's ``[tool.fedlint]``) so a stray
``np.random.rand()`` can never silently break replay determinism.

Streams are small integer ids (module constants below), never strings —
Python's ``hash(str)`` is per-process randomized and would poison
determinism across runs.
"""

from __future__ import annotations

import numpy as np

# draw-stream ids: each logically-independent draw family gets its own
# stream so adding one can never shift another's seeded schedule (the
# comm/faults.py draw-ordering discipline, applied at the generator level)
STREAM_SPEED = 1      # static per-client speed multipliers
STREAM_AVAIL = 2      # per-(client, block) availability
STREAM_DROP = 3       # per-(round, cohort slot) mid-round dropout
STREAM_JITTER = 4     # per-(round, cohort slot) upload-arrival jitter
STREAM_WIRE = 5       # the wire adapter's static per-rank profiles

_MOD = 2**31 - 1  # RandomState seeds must fit 32 bits


def spawn(seed: int, stream: int, index: int = 0) -> np.random.RandomState:
    """A fresh deterministic generator for ``(seed, stream, index)``.

    ``index`` is the time axis of the stream (round index, availability
    block, ...); distinct (stream, index) pairs land on distinct
    multiplicative lanes so neighbouring rounds never share a schedule."""
    mixed = (int(seed) * 1_000_003 + int(stream) * 7_919
             + int(index) * 104_729) % _MOD
    # the subsystem-wide single construction site (see module docstring)
    # fedlint: disable=traced-purity -- the population subsystem's ONE seeded-generator constructor; every population draw flows through it, which is exactly what keeps trace replay deterministic
    return np.random.RandomState(mixed)
