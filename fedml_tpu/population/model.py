"""Trace-driven heterogeneous population model (docs/PERFORMANCE.md
"Heterogeneous populations").

The reference's mobile/IoT paradigm is defined by device speed/availability
skew (SURVEY §1; its heterogeneity-aware ``scheduler.DP_schedule``,
scheduler.py:109, bins work by predicted device speed) — but every systems
plane in this repo so far ran against an idealized population: packed lanes
bin by nominal steps, async staleness comes from hand-written fault specs,
the FT plane is driven by synthetic specs. This module is the missing
population: a deterministic, seeded model of

- a **per-client speed multiplier** (static, drawn once from a configurable
  distribution) — drives per-client step budgets, replacing the uniform
  ``straggler_frac`` draw,
- an **availability on/off process** (per-(client, block) draws with a
  configurable block length, so clients go dark for whole stretches of
  rounds, not i.i.d. coin flips) — drives cohort eligibility,
- a **mid-round dropout** probability + executed-fraction draw — drives
  dropout injection (a dropped client trains part of its budget and its
  update never aggregates),
- an **upload-arrival jitter** distribution (seconds) — the wire-only knob
  the population adapter (population/wire.py) maps onto per-rank delays.

Everything is a pure function of ``(spec, num_clients, seed, round)``
through :mod:`fedml_tpu.population.prng`, so any round is random-access
(the pipelined driver prefetches staging out of band) and a saved trace
(population/trace.py) replays bit-exactly.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from fedml_tpu.core import rng as rnglib
from fedml_tpu.population import prng

# distribution grammar: name:param[,param] — the three families the
# population knobs accept (plus const for degenerate/identity arms)
DIST_ARITY = {"const": 1, "uniform": 2, "lognormal": 2, "zipf": 1}


@dataclasses.dataclass(frozen=True)
class Dist:
    """One parsed distribution. ``draw`` consumes a generator from
    :func:`fedml_tpu.population.prng.spawn` — never global rng state.

    - ``const:v`` — every draw is v
    - ``uniform:lo,hi`` — uniform on [lo, hi)
    - ``lognormal:mu,sigma`` — exp(N(mu, sigma)); median e^mu
    - ``zipf:a`` — **inverse** Zipf: 1/Z with Z ~ zipf(a), a > 1. As a speed
      multiplier this puts the heavy tail on SLOW clients (a 1/k-speed
      straggler at Zipf rank k), the power-law device skew the mobile
      paradigm is about — a raw Zipf draw would make the tail *fast*, which
      no budget model can use (budgets cap at the nominal step count).
    """

    name: str
    params: tuple[float, ...]

    def draw(self, rng: np.random.RandomState, n: int) -> np.ndarray:
        p = self.params
        if self.name == "const":
            return np.full(n, p[0], np.float64)
        if self.name == "uniform":
            return p[0] + (p[1] - p[0]) * rng.random_sample(n)
        if self.name == "lognormal":
            return np.exp(p[0] + p[1] * rng.standard_normal(n))
        # zipf (validated in parse_dist): inverse draw, see class docstring
        return 1.0 / rng.zipf(p[0], n).astype(np.float64)

    @property
    def is_const(self) -> bool:
        return self.name == "const"

    def to_string(self) -> str:
        return f"{self.name}:{','.join(repr(float(v)) for v in self.params)}"


def parse_dist(spec: str) -> Dist:
    """``name:p1[,p2]`` -> :class:`Dist`. Unknown names and wrong arities
    fail loudly — a typo'd distribution silently running a different
    experiment would be worse than a crash (the fault-spec convention)."""
    name, sep, raw = spec.strip().partition(":")
    name = name.strip()
    if name not in DIST_ARITY:
        raise ValueError(
            f"unknown distribution {name!r} in {spec!r} (expected "
            f"{' | '.join(sorted(DIST_ARITY))})"
        )
    if not sep:
        raise ValueError(
            f"distribution {spec!r}: expected '{name}:<param>"
            f"{',<param>' * (DIST_ARITY[name] - 1)}'"
        )
    try:
        params = tuple(float(v) for v in raw.split(","))
    except ValueError:
        raise ValueError(
            f"distribution {spec!r}: non-numeric parameter"
        ) from None
    if len(params) != DIST_ARITY[name]:
        raise ValueError(
            f"distribution {spec!r}: {name} takes {DIST_ARITY[name]} "
            f"parameter(s), got {len(params)}"
        )
    if name == "zipf" and params[0] <= 1.0:
        raise ValueError(f"distribution {spec!r}: zipf needs a > 1")
    if name == "uniform" and params[1] < params[0]:
        raise ValueError(f"distribution {spec!r}: uniform needs hi >= lo")
    if name == "lognormal" and params[1] < 0:
        raise ValueError(f"distribution {spec!r}: lognormal needs sigma >= 0")
    return Dist(name, params)


@dataclasses.dataclass(frozen=True)
class PopulationSpec:
    """The population's knobs. CLI/`SimConfig` carry the string form
    (:func:`parse_population_spec`); defaults are the identity population
    (every client full speed, always available, never dropping)."""

    speed: Dist = Dist("const", (1.0,))
    avail: float = 1.0        # stationary availability probability
    avail_block: int = 1      # rounds per on/off availability block
    dropout: float = 0.0      # per-(round, cohort member) mid-round dropout
    drop_frac: Dist = Dist("uniform", (0.0, 1.0))  # budget fraction executed
    jitter: Dist = Dist("const", (0.0,))           # upload delay seconds

    def __post_init__(self):
        for name in ("avail", "dropout"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(
                    f"population {name}={v} must be in [0, 1]"
                )
        if self.avail_block < 1:
            raise ValueError(
                f"population avail_block={self.avail_block} must be >= 1"
            )

    @property
    def jitter_active(self) -> bool:
        """True when the spec schedules upload delays — a wire-only knob
        the sim engine rejects (there is no wire on the sim backend)."""
        return not (self.jitter.is_const and self.jitter.params[0] == 0.0)

    def to_string(self) -> str:
        return ";".join([
            f"speed={self.speed.to_string()}",
            f"avail={self.avail!r}",
            f"avail_block={self.avail_block}",
            f"dropout={self.dropout!r}",
            f"drop_frac={self.drop_frac.to_string()}",
            f"jitter={self.jitter.to_string()}",
        ])


_SCALAR_KEYS = {"avail": float, "avail_block": int, "dropout": float}
_DIST_KEYS = ("speed", "drop_frac", "jitter")


def parse_population_spec(spec: str | PopulationSpec) -> PopulationSpec:
    """The ``--population`` syntax: ``;``-separated ``key=value`` entries,
    e.g. ``"speed=lognormal:0,0.5;avail=0.8;avail_block=4;dropout=0.05"``.
    Unknown keys, duplicate keys, and malformed values fail loudly."""
    if isinstance(spec, PopulationSpec):
        return spec
    kw: dict = {}
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        key, sep, val = entry.partition("=")
        key = key.strip()
        if not sep or not val.strip():
            raise ValueError(
                f"population spec entry {entry!r}: expected 'key=value'"
            )
        if key in kw:
            raise ValueError(f"population spec: duplicate key {key!r}")
        if key in _SCALAR_KEYS:
            kw[key] = _SCALAR_KEYS[key](val)
        elif key in _DIST_KEYS:
            kw[key] = parse_dist(val)
        else:
            raise ValueError(
                f"unknown population key {key!r} (expected "
                f"{' | '.join([*_SCALAR_KEYS, *_DIST_KEYS])})"
            )
    if not kw:
        raise ValueError(f"empty population spec {spec!r}")
    return PopulationSpec(**kw)


@dataclasses.dataclass(frozen=True)
class RoundView:
    """One round's realized population state over a fixed-size cohort.

    ``cohort`` always has exactly ``cohort_size`` slots; when availability
    churn leaves fewer eligible clients than the cohort wants, the tail
    slots hold ``-1`` (an empty slot: zero weight, zero steps — the staging
    machinery's existing padding convention, so compiled shapes never
    change). Per-slot arrays are aligned with ``cohort``; empty slots carry
    neutral values (speed 1, not dropped, jitter 0)."""

    round_idx: int
    cohort: np.ndarray        # [K] int32 client ids, -1 = empty slot
    speed: np.ndarray         # [K] float64 speed multipliers
    dropped: np.ndarray       # [K] bool — drops mid-round
    drop_frac: np.ndarray     # [K] float64 — budget fraction executed
    jitter_s: np.ndarray      # [K] float64 — upload-arrival delay (wire)
    eligible_count: int       # how many clients were available this round

    @property
    def cohort_size(self) -> int:
        return len(self.cohort)

    def real(self) -> np.ndarray:
        """[K] bool — slots holding an actual sampled client."""
        return self.cohort >= 0


class Population:
    """The generative population: static per-client attributes drawn at
    construction, per-round dynamics drawn on demand — every draw seeded
    through :mod:`fedml_tpu.population.prng`, so ``round_view`` is a pure
    function of ``(spec, num_clients, seed, round_idx, cohort_size)``."""

    def __init__(self, spec: PopulationSpec | str, num_clients: int,
                 seed: int = 0):
        self.spec = parse_population_spec(spec)
        if num_clients < 1:
            raise ValueError(f"population needs num_clients >= 1, got "
                             f"{num_clients}")
        self.num_clients = int(num_clients)
        self.seed = int(seed)
        # static per-client speed multipliers; floored away from zero so a
        # pathological draw can never produce a zero-step budget for a
        # non-dropped client
        self.speed = np.maximum(
            self.spec.speed.draw(
                prng.spawn(self.seed, prng.STREAM_SPEED), self.num_clients
            ),
            1e-6,
        )

    def availability_mask(self, round_idx: int) -> np.ndarray:
        """[num_clients] bool — who is reachable this round. Drawn per
        (client, block) with block = round // avail_block, so a client that
        goes dark stays dark for the whole block (temporal correlation, the
        'on/off process'), and any round remains random-access."""
        if self.spec.avail >= 1.0:
            return np.ones(self.num_clients, bool)
        block = int(round_idx) // self.spec.avail_block
        rng = prng.spawn(self.seed, prng.STREAM_AVAIL, block)
        return rng.random_sample(self.num_clients) < self.spec.avail

    def round_view(self, round_idx: int, cohort_size: int) -> RoundView:
        mask = self.availability_mask(round_idx)
        eligible = np.nonzero(mask)[0]
        k = min(int(cohort_size), len(eligible))
        cohort = np.full(cohort_size, -1, np.int32)
        if k:
            cohort[:k] = rnglib.sample_clients(
                round_idx, self.num_clients, k, eligible=eligible
            )
        real = cohort >= 0
        speed = np.where(real, self.speed[np.maximum(cohort, 0)], 1.0)
        # dropout: one uniform + one fraction draw PER SLOT in a fixed
        # order, so the schedule never shifts with eligibility
        rng_d = prng.spawn(self.seed, prng.STREAM_DROP, round_idx)
        u = rng_d.random_sample(cohort_size)
        frac = np.clip(
            self.spec.drop_frac.draw(rng_d, cohort_size), 0.0, 1.0
        )
        dropped = real & (self.spec.dropout > 0) & (u < self.spec.dropout)
        jitter = np.maximum(
            self.spec.jitter.draw(
                prng.spawn(self.seed, prng.STREAM_JITTER, round_idx),
                cohort_size,
            ),
            0.0,
        )
        return RoundView(
            round_idx=int(round_idx),
            cohort=cohort,
            speed=speed,  # empty slots already neutralized to 1.0 above
            dropped=dropped,
            drop_frac=np.where(dropped, frac, 1.0),
            jitter_s=np.where(real, jitter, 0.0),
            eligible_count=int(len(eligible)),
        )

    def describe(self) -> dict:
        """Static accounting for run-start logs (the pack_summary shape)."""
        return {
            "kind": "generative",
            "spec": self.spec.to_string(),
            "num_clients": self.num_clients,
            "seed": self.seed,
            "speed_minmax": [float(self.speed.min()),
                             float(self.speed.max())],
        }


def step_budgets(view: RoundView, nominal_steps: int
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Map a round view onto per-slot step budgets: ``(actual, predicted)``
    int32 arrays aligned with ``view.cohort``.

    ``predicted`` is the scheduler's view — what the speed model says the
    client completes within the round deadline: ``ceil(min(1, speed) *
    nominal)`` clipped to [1, nominal] for real slots, 0 for empty slots.
    ``actual`` truncates predicted by the mid-round dropout draw
    (``floor(drop_frac * predicted)``, possibly 0 — dropped before the
    first step lands). ``actual <= predicted`` always — the invariant the
    predicted-binning packer (sim/cohort.pack_cohort) relies on."""
    real = view.real()
    nominal = int(nominal_steps)
    frac = np.minimum(view.speed, 1.0)
    predicted = np.where(
        real, np.clip(np.ceil(frac * nominal), 1, nominal), 0
    ).astype(np.int32)
    actual = np.where(
        view.dropped,
        np.floor(view.drop_frac * predicted),
        predicted,
    ).astype(np.int32)
    return actual, predicted
