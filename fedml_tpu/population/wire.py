"""Population adapter for the message-passing wire path.

On the sim backend the population drives cohorts and step budgets inside
the engine; on the wire the physical fleet is the RANK set, so the adapter
maps the same configured distributions onto per-rank upload behaviour and
schedules it through the existing seeded fault machinery
(:mod:`fedml_tpu.comm.faults`):

- per-rank upload delay = ``jitter_draw / min(speed, 1)`` seconds — a slow
  device's upload lands late (the async server's staleness distribution
  and the sync server's SLOW/stale-upload paths are stressed by a
  *population-shaped* arrival process instead of a hand-written spec),
- per-rank upload drop probability = the spec's ``dropout`` — a mid-round
  dropout on the wire IS a lost upload (the elastic-timeout /
  EmptyRoundError / heartbeat-readmission surface).

The adapter also carries per-rank profiles (speed, predicted step
fraction) that fleet-telemetry-armed clients piggyback as
predicted-vs-actual step gauges, so ``tools/fleet_report.py`` renders the
churn (docs/OBSERVABILITY.md "Fleet telemetry").

An identity spec (full speed, no dropout, zero jitter) produces NO active
fault specs — the wrapped transports are never constructed and a
population-armed run is bit-identical to a plain one
(tools/population_smoke.py holds the contract).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from fedml_tpu.population import prng
from fedml_tpu.population.model import PopulationSpec, parse_population_spec


@dataclasses.dataclass(frozen=True)
class PopulationWireAdapter:
    """Resolved wire-side population: seeded per-rank fault specs (only
    ranks with an ACTIVE spec appear — wrap_make_comm leaves the rest
    unwrapped) plus per-rank profiles for telemetry."""

    spec: PopulationSpec
    seed: int
    worker_num: int
    fault_specs: dict  # {rank: comm.faults.FaultSpec}, active ranks only
    profiles: dict     # {rank: {"speed", "delay_s", "drop",
                       #         "predicted_frac"}}

    @property
    def active(self) -> bool:
        return bool(self.fault_specs)

    @property
    def max_delay_s(self) -> float:
        return max(
            (s.delay for s in self.fault_specs.values()), default=0.0
        )

    @property
    def drops_uploads(self) -> bool:
        return any(s.drop > 0 for s in self.fault_specs.values())

    def spec_for(self, rank: int):
        """Active fault spec for one rank (None = identity, leave the
        transport unwrapped). Tree mode indexes by GLOBAL leaf number
        (``leaf_base + cell_rank``), so one churn trace spans every cell of
        the hierarchy with the same per-client draws the flat wire path
        would see."""
        return self.fault_specs.get(int(rank))

    def describe(self) -> dict:
        return {
            "kind": "wire",
            "spec": self.spec.to_string(),
            "worker_num": self.worker_num,
            "seed": self.seed,
            "faulted_ranks": sorted(self.fault_specs),
            "max_delay_s": round(self.max_delay_s, 4),
        }


def population_fault_specs(spec: PopulationSpec | str, worker_num: int,
                           seed: int = 0) -> PopulationWireAdapter:
    """Build the wire adapter: per-rank (1..worker_num) profiles drawn from
    the population distributions on the dedicated wire stream, mapped onto
    :class:`fedml_tpu.comm.faults.FaultSpec` upload delays/drops."""
    from fedml_tpu.comm.faults import FaultSpec

    spec = parse_population_spec(spec)
    if worker_num < 1:
        raise ValueError(f"population wire adapter needs worker_num >= 1, "
                         f"got {worker_num}")
    speeds = np.maximum(
        spec.speed.draw(prng.spawn(seed, prng.STREAM_WIRE, 0), worker_num),
        1e-6,
    )
    jitter = np.maximum(
        spec.jitter.draw(prng.spawn(seed, prng.STREAM_WIRE, 1), worker_num),
        0.0,
    )
    fault_specs: dict[int, FaultSpec] = {}
    profiles: dict[int, dict] = {}
    for i in range(worker_num):
        rank = i + 1
        delay = float(jitter[i] / min(float(speeds[i]), 1.0))
        fs = FaultSpec(drop=spec.dropout, delay=delay,
                       delay_prob=1.0 if delay > 0 else 0.0)
        if fs.active:
            fault_specs[rank] = fs
        profiles[rank] = {
            "speed": float(speeds[i]),
            "delay_s": delay,
            "drop": float(spec.dropout),
            "predicted_frac": min(1.0, float(speeds[i])),
        }
    return PopulationWireAdapter(
        spec=spec, seed=int(seed), worker_num=int(worker_num),
        fault_specs=fault_specs, profiles=profiles,
    )
