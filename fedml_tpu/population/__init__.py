"""Trace-driven heterogeneous population simulator (docs/PERFORMANCE.md
"Heterogeneous populations").

- :mod:`fedml_tpu.population.model` — the seeded generative model
  (speed / availability / dropout / jitter distributions, round views,
  step-budget mapping)
- :mod:`fedml_tpu.population.trace` — bit-exact JSONL trace save/replay
- :mod:`fedml_tpu.population.wire` — the message-passing adapter mapping
  the population onto per-rank upload delays/drops via comm/faults.py
- :mod:`fedml_tpu.population.prng` — the subsystem's single seeded-rng
  funnel (fedlint's ``banned-module-calls`` keeps it the only one)

CLI surface (``add_cli_flags`` / ``sim_config_fields``) mirrors
``fedml_tpu.algorithms.robust``: one canonical flag set shared by
``main_fedavg`` and the repro entry points.
"""

from __future__ import annotations

from fedml_tpu.population.model import (
    Dist,
    Population,
    PopulationSpec,
    RoundView,
    parse_dist,
    parse_population_spec,
    step_budgets,
)
from fedml_tpu.population.trace import (
    TracePopulation,
    capture_trace,
    load_trace,
    save_trace,
)
from fedml_tpu.population.wire import (
    PopulationWireAdapter,
    population_fault_specs,
)

__all__ = [
    "Dist", "Population", "PopulationSpec", "RoundView",
    "parse_dist", "parse_population_spec", "step_budgets",
    "TracePopulation", "capture_trace", "load_trace", "save_trace",
    "PopulationWireAdapter", "population_fault_specs",
    "add_cli_flags", "sim_config_fields",
]


def add_cli_flags(parser):
    """Register the canonical population flags on an entry point (one help
    text everywhere; mirrors ``fedml_tpu.algorithms.robust.add_cli_flags``).
    The flags map 1:1 onto the SimConfig population fields via
    :func:`sim_config_fields`."""
    parser.add_argument(
        "--population", type=str, default=None,
        help="heterogeneous population spec (docs/PERFORMANCE.md "
             "'Heterogeneous populations'): ';'-separated key=value with "
             "keys speed=<dist> | avail=<p> | avail_block=<rounds> | "
             "dropout=<p> | drop_frac=<dist> | jitter=<dist>, dist grammar "
             "const:v | uniform:lo,hi | lognormal:mu,sigma | zipf:a — e.g. "
             "'speed=lognormal:0,0.5;avail=0.8;dropout=0.05'. Drives "
             "cohort eligibility + per-client step budgets + mid-round "
             "dropout on the sim backend, per-rank upload delays/drops on "
             "the message-passing backends (jitter is wire-only). Default "
             "off; results with the flag unset are unchanged",
    )
    parser.add_argument(
        "--population_trace", type=str, default=None,
        help="replay a saved population trace (JSONL from "
             "fedml_tpu.population.save_trace) instead of drawing from "
             "--population: cohorts, step budgets, and dropouts reproduce "
             "bit-exactly; sim backend only",
    )
    parser.add_argument(
        "--population_seed", type=int, default=None,
        help="seed for the population's draws (default: the run seed); "
             "separate so the same federated run can be replayed under a "
             "different population realization",
    )
    return parser


def sim_config_fields(args) -> dict:
    """The SimConfig kwargs for :func:`add_cli_flags`'s values."""
    return {
        "population": getattr(args, "population", None),
        "population_trace": getattr(args, "population_trace", None),
        "population_seed": getattr(args, "population_seed", None),
    }
